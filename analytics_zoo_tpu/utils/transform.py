"""Chainable transform base — shared by feature preprocessing chains.

The reference composes preprocessing as ``ChainedPreprocessing(list)``
(ref: zoo feature/common Preprocessing.scala ``->`` operator); here one
base provides ``>>`` composition with chain flattening for both the image
transform chain (data/image.py) and the NNFrames column preprocessing
(frames/nnframes.py).
"""

from __future__ import annotations

from typing import Callable, List, Sequence


class Transform:
    """Wraps ``fn(x) -> x``; compose left-to-right with ``>>``."""

    chain_cls: type = None  # bound to Chain below (subclasses override)

    def __init__(self, fn: Callable, name: str = "transform"):
        self.fn = fn
        self.name = name

    def __call__(self, x):
        return self.fn(x)

    def _steps(self) -> List["Transform"]:
        return [self]

    def __rshift__(self, other: "Transform") -> "Transform":
        cls = self.chain_cls or Chain
        return cls(self._steps() + other._steps())


class Chain(Transform):
    """Flattened left-to-right composition of Transforms."""

    def __init__(self, steps: Sequence[Transform]):
        self.steps = list(steps)
        super().__init__(self._apply, "chained")

    def _steps(self) -> List[Transform]:
        return list(self.steps)

    def _apply(self, x):
        for s in self.steps:
            x = s(x)
        return x


Transform.chain_cls = Chain

"""Flash attention — fused Pallas TPU kernel (fwd + custom-VJP bwd).

No reference counterpart (the reference's TransformerLayer/BERT materialise
full [T, T] score matrices on CPU — ref: zoo pipeline/api/keras/layers
self_attention); this is TPU perf work the rebuild owns: the score matrix
never hits HBM, softmax is computed online block-by-block in VMEM
(O(T) memory instead of O(T^2)), and q·k / p·v ride the MXU in the operand
dtype (bf16 in the transformer stack) with f32 accumulators.

Kernel structure (canonical TPU flash): 3D grid — (batch*heads, q-blocks,
k-blocks) with the k dimension marked ``arbitrary`` so Mosaic pipelines
K/V block DMAs against compute; online-softmax state (running max, sum,
accumulator) lives in VMEM scratch across the k iterations; outputs are
written on the last k step.  Causal runs skip fully-masked blocks.

Interface matches the model stack: q, k, v are [B, T, H, D]; optional
``kv_mask`` [B, Tk] bool (True = attend) covers padding; ``causal`` adds the
autoregressive mask.  On non-TPU backends the kernels run in Pallas
interpret mode, so the same code path is unit-testable on the CPU mesh
(SURVEY.md §4 single-box test doctrine).

Layout notes (Mosaic): per-row stats (max / logsumexp / delta) are kept as
[rows, 1] columns end-to-end — including the HBM residual, shaped
[B*H, T, 1] — so no row->column relayout is ever needed; the key mask is
[B, 1, Tk] int32, read as [1, bk] lane-aligned slices.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from analytics_zoo_tpu.parallel.mesh import shard_map as _shard_map

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp/where NaN-free


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pad_to(x, mult, axis):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _causal_mask(s, q0, k0, bq, bk):
    qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _block_live(causal, qi, kj, bq, bk):
    """False only when the causal mask kills the whole (qi, kj) block."""
    if not causal:
        return True
    return (qi + 1) * bq - 1 >= kj * bk


def _params(interpret, n_arb):
    if interpret:
        return {"interpret": True}
    sem = ("parallel",) * (3 - n_arb) + ("arbitrary",) * n_arb
    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=sem)}


# ---------------------------------------------------------------------------
# forward kernel:  grid (B*H, num_q_blocks, num_k_blocks)
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k):
    qi, kj = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(_block_live(causal, qi, kj, block_q, block_k))
    def _accumulate():
        q = q_ref[0]                                   # [bq, D] (op dtype)
        k = k_ref[0]                                   # [bk, D]
        v = v_ref[0]
        s = scale * jax.lax.dot_general(               # [bq, bk] f32 accum
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        kvm = mask_ref[0]                              # [1, bk] int32
        s = jnp.where(kvm > 0, s, NEG_INF)
        if causal:
            s = _causal_mask(s, qi * block_q, kj * block_k,
                             block_q, block_k)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # fully-masked row: s - m_new would be 0 everywhere (both NEG_INF);
        # subtract 0 instead so exp(NEG_INF) underflows to 0
        m_sub = jnp.where(m_new > NEG_INF * 0.5, m_new, 0.0)
        p = jnp.exp(s - m_sub)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # logsumexp residual; fully-masked rows get +big so bwd's
        # exp(s - lse) underflows to 0 instead of exp(-inf - -inf) = 1
        lse_ref[0] = jnp.where(l > 0, m_ref[:] + jnp.log(l_safe), -NEG_INF)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k):
    qi, kj = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(_block_live(causal, qi, kj, block_q, block_k))
    def _accumulate():
        q = q_ref[0]                                   # [bq, D]
        do = do_ref[0]
        lse, delta = lse_ref[0], delta_ref[0]          # [bq, 1]
        k = k_ref[0]                                   # [bk, D]
        v = v_ref[0]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        kvm = mask_ref[0]
        s = jnp.where(kvm > 0, s, NEG_INF)
        if causal:
            s = _causal_mask(s, qi * block_q, kj * block_k,
                             block_q, block_k)
        p = jnp.exp(s - lse)                           # [bq, bk]
        dp = jax.lax.dot_general(                      # dO @ V^T
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc[:] = dq_acc[:] + scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, mask_ref, q_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale, causal, block_q, block_k):
    # grid (B*H, num_k_blocks, num_q_blocks) — innermost walks q blocks
    kj, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_block_live(causal, qi, kj, block_q, block_k))
    def _accumulate():
        k = k_ref[0]                                   # [bk, D]
        v = v_ref[0]
        kvm = mask_ref[0]                              # [1, bk]
        q = q_ref[0]                                   # [bq, D]
        do = do_ref[0]
        lse, delta = lse_ref[0], delta_ref[0]          # [bq, 1]
        s = scale * jax.lax.dot_general(               # [bq, bk]
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        s = jnp.where(kvm > 0, s, NEG_INF)
        if causal:
            s = _causal_mask(s, qi * block_q, kj * block_k,
                             block_q, block_k)
        p = jnp.exp(s - lse)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(   # P^T @ dO
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                          # [bq, bk]
        dk_acc[:] = dk_acc[:] + scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing (operands flattened to [B*H, T, D])
# ---------------------------------------------------------------------------

def _fwd_call(q, k, v, mask, *, scale, causal, bq, bk, interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]
    h_per_b = bh // mask.shape[0]
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk)
    return pl.pallas_call(
        kernel,
        grid=(bh, tq // bq, tk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, i, j: (b // h_per_b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        **_params(interpret, 1),
    )(q, k, v, mask)


def _bwd_call(q, k, v, mask, o, lse, do, *, scale, causal, bq, bk,
              interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]
    h_per_b = bh // mask.shape[0]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)            # [BH, Tq, 1]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(bh, tq // bq, tk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, i, j: (b // h_per_b, 0, j)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        **_params(interpret, 1),
    )(q, k, v, mask, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(bh, tk // bk, tq // bq),
        in_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, j, i: (b // h_per_b, 0, j)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        **_params(interpret, 1),
    )(k, v, mask, q, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-VJP wrapper (per static config, cached)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _flash_fn(scale, causal, bq, bk, interpret):
    cfg = dict(scale=scale, causal=causal, bq=bq, bk=bk,
               interpret=interpret)

    @jax.custom_vjp
    def fa(q, k, v, mask):
        return _fwd_call(q, k, v, mask, **cfg)[0]

    def fwd(q, k, v, mask):
        o, lse = _fwd_call(q, k, v, mask, **cfg)
        return o, (q, k, v, mask, o, lse)

    def bwd(res, g):
        q, k, v, mask, o, lse = res
        dq, dk, dv = _bwd_call(q, k, v, mask, o, lse, g, **cfg)
        return dq, dk, dv, np.zeros(mask.shape, jax.dtypes.float0)

    fa.defvjp(fwd, bwd)
    return fa


def flash_attention(q, k, v, kv_mask=None, *, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: Optional[bool] = None):
    """Fused attention over [B, T, H, D] operands.

    kv_mask: [B, Tk] bool, True = key position attends (padding mask).
    Padding to block multiples is handled here; padded keys are masked,
    padded query rows are dropped from the output (their grads flow back
    as zeros through the pad's VJP).
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = _interpret_default()
    # Mosaic tiles are (8, 128): block sublane dims must be 8-multiples
    # (T itself gets padded up to the block size below, so rounding is free)
    bq = min(block_q, max(8, -(-Tq // 8) * 8))
    bk = min(block_k, max(8, -(-Tk // 8) * 8))

    # [B, T, H, D] -> [B*H, T, D]
    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    qf = _pad_to(flat(q), bq, axis=1)
    kf = _pad_to(flat(k), bk, axis=1)
    vf = _pad_to(flat(v), bk, axis=1)
    mask = jnp.ones((B, Tk), jnp.int32) if kv_mask is None \
        else kv_mask.astype(jnp.int32)
    mask = _pad_to(mask, bk, axis=1)[:, None, :]   # [B, 1, Tk]

    fa = _flash_fn(float(scale), bool(causal), bq, bk, bool(interpret))
    of = fa(qf, kf, vf, mask)
    return of[:, :Tq, :].reshape(B, H, Tq, D).transpose(0, 2, 1, 3)


def sharded_flash_attention(q, k, v, mesh, kv_mask=None, *,
                            causal: bool = False, **kw):
    """flash_attention on a multi-device mesh.

    A Mosaic kernel is a custom call XLA cannot GSPMD-partition, so under a
    dp/tp-sharded train step the plain kernel would force full all-gathers
    (or fail to compile).  Attention is independent per (batch row, head):
    shard_map over the mesh's batch axes (B) and ``tp`` (H) runs the kernel
    on each shard's local block with zero collectives.
    """
    from jax.sharding import PartitionSpec as P

    from analytics_zoo_tpu.parallel.mesh import batch_axes

    batch = batch_axes(mesh) or None
    tp = "tp" if "tp" in mesh.axis_names and mesh.shape["tp"] > 1 else None
    qkv_spec = P(batch, None, tp, None)
    mask_spec = P(batch, None)

    def local(qs, ks, vs, ms):
        return flash_attention(qs, ks, vs, ms, causal=causal, **kw)

    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:1] + k.shape[1:2], bool)
    return _shard_map(
        local, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec, check_vma=False,
    )(q, k, v, kv_mask)


# ---------------------------------------------------------------------------
# paged attention (block-pool KV cache, serving/paged_cache.py)
#
# Pool layout is HEAD-MAJOR: ``[N, KH, bs, D]`` (physical block, kv
# head, position-in-block, head dim).  The fused kernel streams one
# (block, head) tile per grid step, so the minor-most two dims of its
# K/V BlockSpec must be the Mosaic-tiled ``(bs, D)`` pair — the same
# page layout jax's production TPU paged-attention kernel uses.  The
# gather fallback and the scatter below address the identical storage.
# ---------------------------------------------------------------------------

KV_SCALE_DTYPE = jnp.bfloat16   # per-(block, position, head) int8 scales


@jax.tree_util.register_pytree_node_class
class QuantKV:
    """int8 KV block arena + per-(block, position, kv-head) scales.

    ``data``: int8 ``[..., N, KH, bs, D]`` (leading dims free — the
    engine stacks a layers axis in front); ``scale``: ``data.shape[:-1]``
    in :data:`KV_SCALE_DTYPE`.  One scale per stored K/V row (amax over
    D / 127) keeps the scatter in :func:`paged_kv_update` local — a
    write never has to re-read or re-scale the rest of its block — and
    at bf16 scales the storage cost is ``D + 2`` bytes per row vs
    ``2*D`` for bf16 K/V: ~1.94x the blocks at equal HBM for D=64.

    Registered as a pytree so it threads OPAQUELY through jit / scan /
    donate_argnums / ``flax.apply`` exactly like the plain array pool it
    replaces; ``__getitem__`` mirrors the per-layer ``pools[i]``
    indexing the model's decode loop does.
    """

    __slots__ = ("data", "scale")

    def __init__(self, data, scale):
        self.data, self.scale = data, scale

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __getitem__(self, idx):
        return QuantKV(self.data[idx], self.scale[idx])

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype


def quantize_kv(x, scale_dtype=KV_SCALE_DTYPE):
    """Symmetric per-row int8 quantization over the LAST axis.

    Returns ``(q int8 x.shape, scale scale_dtype x.shape[:-1])`` with
    ``x ~= q * scale``.  The scale is rounded to its STORAGE dtype
    before the divide, so :func:`dequantize_kv` reproduces exactly what
    any reader of the stored (data, scale) pair computes — round-trip
    error is pure integer rounding, identical for the gather fallback
    and the fused kernel.  All-zero rows quantize to (0, scale 1)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(scale_dtype)
    sf = scale.astype(jnp.float32)[..., None]
    q = jnp.clip(jnp.round(xf / sf), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_kv(data, scale):
    """Inverse of :func:`quantize_kv`: f32 ``data * scale[..., None]``."""
    return data.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]


def _paged_scatter_index(tables, pos, S, bs, N, limit):
    """(physical block, offset) per written position, drop-encoded.

    Logical position p of row b maps to (``tables[b, p // bs]``,
    ``p % bs``); block indices past the table width clamp to the last
    column (the allocator keeps unallocated entries at the sink block),
    and positions ``>= limit[b]`` get the out-of-range block id N so a
    ``mode="drop"`` scatter skips them outright."""
    B = pos.shape[0]
    M = tables.shape[1]
    p = pos[:, None] + jnp.arange(S)[None, :]               # [B, S]
    blk = jnp.minimum(p // bs, M - 1)
    phys = jnp.take_along_axis(tables, blk, axis=1)         # [B, S]
    if limit is not None:
        # out-of-range index + mode="drop" = the write never happens
        phys = jnp.where(p < limit[:, None], phys, N)
    return phys, p % bs


def paged_kv_update(pool_k, pool_v, tables, pos, new_k, new_v,
                    limit=None):
    """Scatter S new K/V rows per batch row into a block-pool cache.

    pool_k/pool_v: ``[N, KH, bs, D]`` — the flat head-major block arena
    (N physical blocks of bs token positions each) — or a
    :class:`QuantKV` pair of the same geometry, in which case the new
    rows are QUANTIZED ON WRITE (:func:`quantize_kv`) and both the int8
    data and the per-row scales scatter through the same index.
    tables: ``[B, M]`` int32 — row b's logical block j lives in
    physical block ``tables[b, j]``.  pos: ``[B]`` int32 — row b's
    tokens land at logical positions ``pos[b] .. pos[b]+S-1``.
    new_k/new_v: ``[B, S, KH, D]``.

    Logical position p maps to (physical block ``tables[b, p // bs]``,
    offset ``p % bs``); positions whose logical block index exceeds the
    table width clamp to the last table entry, which the allocator
    keeps pointed at the sink block for anything unallocated, so
    overshoot writes land in garbage space instead of a live block.
    Distinctness contract (the allocator's invariant, not checked
    here): every (row, position) a caller actually cares about maps to
    a PRIVATE tail block of that row, so real writes never collide;
    sink-block collisions are garbage-on-garbage.

    Speculative verify rides this same scatter: the engine writes k+1
    positions per row per round (``S = k+1``) and REJECTION IS POINTER
    ROLLBACK — the next round re-enters with ``pos`` advanced only past
    the accepted prefix, so rejected entries are overwritten in place
    before any attention read can reach them (reads mask to ``<= pos``)
    and no block is ever copied.  Rejected positions that spill past a
    row's allocated table clamp into the sink block per the rule above,
    which is why the engine only has to allocate blocks through
    ``pos + k`` rather than the worst-case round end.

    ``limit`` (``[B]`` int32, optional): row b's writes at logical
    positions ``>= limit[b]`` are DROPPED outright.  Chunked prefill
    passes its per-row true length here: with tables SLICED to a narrow
    ``[B, M']`` window (bounded compile shapes proportional to the fill
    frontier, not the max sequence), a padding position past the window
    would otherwise clamp to table column M'-1 — a live frontier block
    — and corrupt real K/V.  Reads are unaffected; attention masking is
    :func:`paged_attention`'s job.
    """
    if isinstance(pool_k, QuantKV):
        N, KH, bs, D = pool_k.data.shape
        S = new_k.shape[1]
        phys, off = _paged_scatter_index(tables, pos, S, bs, N, limit)
        qk, sk = quantize_kv(new_k, pool_k.scale.dtype)
        qv, sv = quantize_kv(new_v, pool_v.scale.dtype)
        # advanced indices (phys, off) straddle the KH slice, so the
        # indexed dims lead the result: [B, S, KH, D] — new_k's own
        # layout, no transpose needed.  Same for the [B, S, KH] scales.
        pk = QuantKV(
            pool_k.data.at[phys, :, off].set(qk, mode="drop"),
            pool_k.scale.at[phys, :, off].set(sk, mode="drop"))
        pv = QuantKV(
            pool_v.data.at[phys, :, off].set(qv, mode="drop"),
            pool_v.scale.at[phys, :, off].set(sv, mode="drop"))
        return pk, pv
    N, KH, bs, D = pool_k.shape
    S = new_k.shape[1]
    phys, off = _paged_scatter_index(tables, pos, S, bs, N, limit)
    pk = pool_k.at[phys, :, off].set(new_k.astype(pool_k.dtype),
                                     mode="drop")
    pv = pool_v.at[phys, :, off].set(new_v.astype(pool_v.dtype),
                                     mode="drop")
    return pk, pv


# ---------------------------------------------------------------------------
# fused paged-attention kernel
#
# Grid (B, KH, M): one program per (batch row, kv head, logical block),
# the M dimension ``arbitrary`` so online-softmax state carries across
# it in VMEM scratch while Mosaic pipelines the next block's DMA against
# compute.  The block-table indirection lives in the K/V BlockSpec
# index_maps — ``tables``/``pos`` ride as scalar-prefetch operands, so
# each grid step DMAs exactly ONE [bs, D] tile per tensor straight from
# the pool in HBM: the [B, M*bs, KH, D] gather is never materialised.
#
# Queries are regrouped head-major ([B, KH, S*G, D], row r = s*G + g,
# padded to 8 sublanes): each program owns ALL G query heads of its KV
# head, which is what makes grouped-query attention free here.  VMEM
# per program: q/acc [SGp, D] + m/l columns + one [bs, D] K/V tile each.
# Masking matches the gather fallback exactly — query s attends logical
# positions <= pos[b] + s; blocks past the frontier skip compute via
# pl.when (their table entries point at the sink, so the DMA is
# harmless), in-block tails mask element-wise to NEG_INF.
#
# int8 pools add two [bs]-lane scale operands: k-scales multiply the
# logits columns post-matmul, v-scales fold into p pre-matmul — both
# in-register, algebraically identical to dequantizing the tiles.
# ---------------------------------------------------------------------------

def _paged_fused_kernel(tables_ref, pos_ref, *refs, scale, bs, G, S,
                        quant):
    if quant:
        (q_ref, k_ref, v_ref, sk_ref, sv_ref, o_ref,
         acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    b, j = pl.program_id(0), pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # block j holds logical positions [j*bs, (j+1)*bs); the furthest
    # position any query row attends is pos[b] + S - 1
    @pl.when(j * bs <= pos_ref[b] + (S - 1))
    def _accumulate():
        q = q_ref[0, 0]                                # [SGp, D]
        k = k_ref[0, 0]                                # [bs, D]
        s = scale * jax.lax.dot_general(               # [SGp, bs] f32
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if quant:
            s = s * sk_ref[0].astype(jnp.float32)      # [1, bs] bcast
        rows = acc_ref.shape[0]
        lpos = j * bs + jax.lax.broadcasted_iota(
            jnp.int32, (rows, bs), 1)
        qrow = jax.lax.broadcasted_iota(
            jnp.int32, (rows, bs), 0) // G             # row r -> s=r//G
        s = jnp.where(lpos <= pos_ref[b] + qrow, s, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # fully-masked row: subtract 0 instead of NEG_INF so
        # exp(NEG_INF) underflows to 0 (same trick as _fwd_kernel)
        m_sub = jnp.where(m_new > NEG_INF * 0.5, m_new, 0.0)
        p = jnp.exp(s - m_sub)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if quant:
            # fold the v scales into p's columns: (p * sv) @ v_int8
            # == p @ (v_int8 * sv[:, None]) without a [bs, D] dequant
            p = p * sv_ref[0].astype(jnp.float32)
            v = v_ref[0, 0].astype(jnp.float32)
        else:
            v = v_ref[0, 0]
            p = p.astype(v.dtype)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _paged_attention_fused(q, pool_k, pool_v, tables, pos, interpret):
    B, S, H, D = q.shape
    quant = isinstance(pool_k, QuantKV)
    kd = pool_k.data if quant else pool_k
    vd = pool_v.data if quant else pool_v
    N, KH, bs, _ = kd.shape
    if H % KH:
        raise ValueError(f"query heads {H} not a multiple of KV heads "
                         f"{KH}")
    G = H // KH
    M = tables.shape[1]
    SG = S * G
    SGp = -(-SG // 8) * 8          # Mosaic sublane multiple
    # [B, S, H, D] -> [B, KH, S*G, D]: row r of kv head h is query
    # (s = r // G, head h*G + r % G), padded rows are mask-dead
    qf = q.reshape(B, S, KH, G, D).transpose(0, 2, 1, 3, 4)
    qf = _pad_to(qf.reshape(B, KH, SG, D), 8, axis=2)
    scale = 1.0 / float(np.sqrt(D))
    kernel = functools.partial(_paged_fused_kernel, scale=scale,
                               bs=bs, G=G, S=S, quant=quant)
    in_specs = [
        pl.BlockSpec((1, 1, SGp, D), lambda b, h, j, t, p: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, bs, D),
                     lambda b, h, j, t, p: (t[b, j], h, 0, 0)),
        pl.BlockSpec((1, 1, bs, D),
                     lambda b, h, j, t, p: (t[b, j], h, 0, 0)),
    ]
    operands = [qf, kd, vd]
    if quant:
        sspec = pl.BlockSpec((1, 1, bs),
                             lambda b, h, j, t, p: (t[b, j], h, 0))
        in_specs += [sspec, sspec]
        operands += [pool_k.scale, pool_v.scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KH, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, SGp, D),
                               lambda b, h, j, t, p: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((SGp, D), jnp.float32),
            pltpu.VMEM((SGp, 1), jnp.float32),
            pltpu.VMEM((SGp, 1), jnp.float32),
        ])
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KH, SGp, D), jnp.float32),
        **_params(interpret, 1),
    )(tables.astype(jnp.int32), pos.astype(jnp.int32), *operands)
    out = out[:, :, :SG, :].reshape(B, KH, S, G, D)
    return out.transpose(0, 2, 1, 3, 4).reshape(B, S, H, D)


def _paged_attention_fused_tp(q, pool_k, pool_v, tables, pos, mesh,
                              kv_sharded, interpret):
    """The fused kernel under a tensor-parallel mesh.

    A Mosaic kernel is a custom call XLA cannot GSPMD-partition, so the
    tp-sharded pool is read through :func:`shard_map` instead: each chip
    runs :func:`_paged_attention_fused` on its LOCAL pool shard — the
    kv-heads grid dimension shrinks tp-fold (grid ``(B, KH/tp, M)`` per
    chip) and the block-table indirection needs no change because
    tables/pos are replicated host-side state.  Correctness rides the
    head-contiguity of the layout: query head ``h = kh*G + g`` (GQA
    fold), so a contiguous shard of the KV heads owns exactly the
    contiguous shard of the query heads that attend through it — zero
    collectives, like :func:`sharded_flash_attention`.

    ``kv_sharded=False`` is the divisibility hatch (``KH % tp != 0``:
    the engine replicates the pool instead of sharding it) — every spec
    drops to replicated and each chip redundantly computes the full
    attention, bitwise-equal across chips.

    int8 ``QuantKV`` pools are unpacked into (data, scale) leaves so the
    per-block scales shard on the same kv-heads axis as the data — one
    spec per leaf, rebuilt into ``QuantKV`` inside the per-chip body.
    """
    from jax.sharding import PartitionSpec as P

    quant = isinstance(pool_k, QuantKV)
    KH = (pool_k.data if quant else pool_k).shape[1]
    tp = "tp" if ("tp" in mesh.axis_names and mesh.shape["tp"] > 1
                  and kv_sharded) else None
    if tp is not None and KH % mesh.shape["tp"]:
        raise ValueError(
            f"kv heads {KH} not divisible by tp={mesh.shape['tp']}: a "
            f"pool this shape must be replicated (pass kv_sharded=False)")
    q_spec = P(None, None, tp, None)        # [B, S, H, D]: heads
    pool_spec = P(None, tp, None, None)     # [N, KH, bs, D]: kv heads
    scale_spec = P(None, tp, None)          # [N, KH, bs]: kv heads
    tab_spec = P(None, None)                # replicated host-side state
    pos_spec = P(None)

    if quant:
        def local(qs, kd, ksc, vd, vsc, t, p):
            return _paged_attention_fused(qs, QuantKV(kd, ksc),
                                          QuantKV(vd, vsc), t, p,
                                          interpret)
        in_specs = (q_spec, pool_spec, scale_spec, pool_spec,
                    scale_spec, tab_spec, pos_spec)
        operands = (q, pool_k.data, pool_k.scale, pool_v.data,
                    pool_v.scale, tables, pos)
    else:
        def local(qs, kd, vd, t, p):
            return _paged_attention_fused(qs, kd, vd, t, p, interpret)
        in_specs = (q_spec, pool_spec, pool_spec, tab_spec, pos_spec)
        operands = (q, pool_k, pool_v, tables, pos)
    return _shard_map(local, mesh=mesh, in_specs=in_specs,
                      out_specs=q_spec, check_vma=False)(*operands)


def paged_attention(q, pool_k, pool_v, tables, pos, *,
                    kernel: str = "gather",
                    interpret: Optional[bool] = None,
                    mesh=None, kv_sharded: bool = True):
    """Block-causal attention of S query tokens per row against a PAGED
    KV cache: keys/values live behind per-row block tables in one flat
    head-major ``[N, KH, bs, D]`` pool (or a :class:`QuantKV` int8 pool
    of the same geometry), so co-resident sequences share physical
    blocks (prefix caching) and only occupy the blocks they have
    actually filled.

    q: ``[B, S, H, D]`` (already rope'd/scaled upstream conventions —
    this op applies the 1/sqrt(D) scale itself, matching the dense
    decode paths); pos: ``[B]`` int32, row b's queries sit at logical
    positions ``pos[b] .. pos[b]+S-1`` and query j attends logical cache
    positions ``<= pos[b]+j`` (its own K/V must already be in the pool —
    call :func:`paged_kv_update` first; write-then-read inside one jit
    is a plain data dependency).  ``KH <= H`` is grouped-query
    attention: q regroups ``[B, S, KH, G, D]`` so each KV head serves
    its G query heads without materialising expanded K/V.  Output is
    f32 (the accumulation dtype) under both kernels.

    The table width M is a free parameter: callers may pass a SLICED
    ``[B, M']`` table whose window covers every position ``<= pos[b] +
    S - 1`` they attend — chunked prefill does exactly this so the
    attention cost tracks the fill frontier (bucketed for a bounded
    compile count), not the max sequence length.

    ``kernel`` selects the implementation; both honor the identical
    masking/GQA/quantization contract, so greedy decode is
    token-identical across them:

    - ``"fused"`` — the Pallas TPU kernel above: grid ``(B, KH, M)``
      with the block dimension ``arbitrary``, block tables as
      scalar-prefetch operands indirecting the K/V BlockSpecs, one
      ``[bs, D]`` tile DMA'd HBM->VMEM per grid step, online softmax in
      VMEM scratch (the dense flash kernel's structure), int8 scales
      applied in-register.  The decode hot path on TPU.
    - ``"gather"`` — the ``jnp.take`` fallback: one materialised
      ``[B, M, KH, bs, D]`` gather (int8 pools dequantize the gathered
      rows) then the masked einsum-softmax the dense decode path runs,
      f32 accumulation.  The CPU / interpret-free reference path —
      tier-1 parity tests pin the fused kernel (in Pallas interpret
      mode) against it.

    ``interpret`` (fused only): run the kernel in Pallas interpret mode;
    defaults to True off-TPU, like :func:`flash_attention`.

    ``mesh`` (fused only): run the kernel per-chip under
    :func:`shard_map` — the tp-sharded-pool read path
    (:func:`_paged_attention_fused_tp`).  ``kv_sharded`` says whether
    the pool actually shards over ``tp`` on the kv-heads dim (the
    engine's default layout) or is replicated (the ``KH % tp != 0``
    hatch); it must match the pool's real placement.  The gather
    fallback ignores both — ``jnp.take`` is GSPMD-partitionable as-is.
    """
    if kernel not in ("gather", "fused"):
        raise ValueError(f"kernel must be 'gather' or 'fused', got "
                         f"{kernel!r}")
    if kernel == "fused":
        if interpret is None:
            interpret = _interpret_default()
        if mesh is not None:
            return _paged_attention_fused_tp(q, pool_k, pool_v, tables,
                                             pos, mesh, kv_sharded,
                                             bool(interpret))
        return _paged_attention_fused(q, pool_k, pool_v, tables, pos,
                                      bool(interpret))
    B, S, H, D = q.shape
    quant = isinstance(pool_k, QuantKV)
    N, KH, bs, _ = (pool_k.data if quant else pool_k).shape
    if H % KH:
        raise ValueError(f"query heads {H} not a multiple of KV heads "
                         f"{KH}")
    G = H // KH
    M = tables.shape[1]
    L = M * bs

    def gathered(pool):
        # [B, M] tables -> [B, M*bs(=L), KH, D] rows: logical position
        # l of row b is pool[tables[b, l // bs], :, l % bs]
        if isinstance(pool, QuantKV):
            data = jnp.take(pool.data, tables, axis=0)  # [B,M,KH,bs,D]
            cache = dequantize_kv(data,
                                  jnp.take(pool.scale, tables, axis=0))
        else:
            cache = jnp.take(pool, tables, axis=0)
        return jnp.moveaxis(cache, 2, 3).reshape(B, L, KH, D)

    cache_k = gathered(pool_k)
    cache_v = gathered(pool_v)
    p = pos[:, None] + jnp.arange(S)[None, :]               # [B, S]
    mask = (jnp.arange(L)[None, None, :]
            <= p[:, :, None])[:, None, None, :, :]          # [B,1,1,S,L]
    scale = 1.0 / jnp.sqrt(jnp.float32(D))
    qg = q.reshape(B, S, KH, G, D)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, cache_k,
                        preferred_element_type=jnp.float32) * scale
    logits = jnp.where(mask, logits, -jnp.inf)
    w = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", w.astype(cache_v.dtype),
                   cache_v, preferred_element_type=jnp.float32)
    return o.reshape(B, S, H, D)

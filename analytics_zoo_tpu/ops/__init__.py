from analytics_zoo_tpu.ops.flash_attention import (
    flash_attention, sharded_flash_attention)

__all__ = ["flash_attention", "sharded_flash_attention"]

"""Device-mesh construction.

The reference's cluster substrate is Spark executors + Ray workers
(ref: pyzoo/zoo/ray/raycontext.py, pyzoo/zoo/common/nncontext.py); ours is a
`jax.sharding.Mesh` over TPU chips.  All parallelism in the framework is
expressed as named mesh axes + `PartitionSpec`s — XLA emits the collectives
(psum / all_gather / reduce_scatter / ppermute) over ICI/DCN, which replaces
the reference's entire zoo of communication backends (Spark BlockManager
all-reduce, gloo, MPI, TF collectives; SURVEY.md §2.3).
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from analytics_zoo_tpu.common.config import MeshConfig

# Canonical axis order: batch-like (outermost, over DCN if multi-slice) first,
# then model axes (want fastest ICI).
CANONICAL_AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")


def resolve_axis_sizes(
    axes: Dict[str, int], n_devices: int
) -> Dict[str, int]:
    """Resolve -1 ("fill") entries so that prod(sizes) == n_devices.

    At most one -1 is allowed.  Fixed axes must divide n_devices.
    """
    fills = [k for k, v in axes.items() if v == -1]
    if len(fills) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {fills}")
    fixed = int(np.prod([v for v in axes.values() if v != -1], dtype=np.int64))
    if fills:
        if n_devices % fixed != 0:
            raise ValueError(
                f"Fixed mesh axes {axes} (product {fixed}) do not divide "
                f"device count {n_devices}")
        resolved = dict(axes)
        resolved[fills[0]] = n_devices // fixed
        return resolved
    if fixed != n_devices:
        raise ValueError(
            f"Mesh axes {axes} (product {fixed}) != device count {n_devices}")
    return dict(axes)


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axes: Optional[Dict[str, int]] = None,
) -> Mesh:
    """Build a Mesh from a MeshConfig (or explicit axis dict).

    Uses `jax.make_mesh` so the logical mesh is laid out along the physical
    ICI topology (axis order: later axes get the fastest links — we order
    model axes last via CANONICAL_AXES).
    """
    if axes is None:
        axes = (config or MeshConfig()).axes
    devices = list(devices if devices is not None else jax.devices())
    sizes = resolve_axis_sizes(dict(axes), len(devices))
    # Drop size-1 axes? No — keep them: PartitionSpecs referencing them stay
    # valid, and scaling up is a config change, not a code change.
    names = sorted(sizes.keys(),
                   key=lambda n: CANONICAL_AXES.index(n)
                   if n in CANONICAL_AXES else len(CANONICAL_AXES))
    shape = tuple(sizes[n] for n in names)
    # jax>=0.9 defaults make_mesh to Explicit axis types, which changes
    # sharding semantics under jit (shardings become part of array types and
    # ops like x @ x.T error on duplicate axes).  We want classic Auto/pjit
    # semantics: request it explicitly.  Older jax (< 0.5) has no AxisType
    # at all — every axis is already Auto there, so omit the kwarg.
    axis_type = getattr(jax.sharding, "AxisType", None)
    kwargs = {} if axis_type is None else \
        {"axis_types": (axis_type.Auto,) * len(names)}
    if devices == list(jax.devices()):
        try:
            return jax.make_mesh(shape, tuple(names), **kwargs)
        except (ValueError, RuntimeError):
            pass  # fall through to manual reshape (e.g. odd device subsets)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(names), **kwargs)


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions: newer jax exposes it at top
    level with a ``check_vma`` kwarg; 0.4.x only has
    ``jax.experimental.shard_map.shard_map`` with the same check spelled
    ``check_rep``."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def single_device_mesh(axis: str = "dp") -> Mesh:
    return make_mesh(axes={axis: 1}, devices=[jax.devices()[0]])


def batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Axes over which the batch dim is sharded (dp-like axes present)."""
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)


def mesh_batch_size(mesh: Mesh) -> int:
    return int(math.prod(mesh.shape[a] for a in batch_axes(mesh)) or 1)

"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

No reference counterpart (SURVEY.md §2.3 item 6: the reference is a CPU
data-parallel stack and predates LLM-scale training); this is a TPU-first
capability the rebuild treats as core: long sequences are sharded over the
``sp`` axis, each device holds its Q/K/V chunk, and K/V chunks rotate around
the ring via ``lax.ppermute`` (one ICI hop per step) while a numerically
stable online-softmax accumulator builds the exact attention output —
compute overlaps the rotation, memory per device is O(T/sp).

Used inside ``shard_map`` (see ``ring_self_attention``) by the transformer
models when the mesh has sp > 1; with sp == 1 it degenerates to one local
attention step, so models can call it unconditionally.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.mesh import shard_map


def _axis_size(axis_name: str) -> int:
    """``lax.axis_size`` is newer jax; on 0.4.x ``psum(1, axis)`` is the
    idiom and returns a static Python int under the shard_map trace."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def _chunk_attn(q, k, v, *, scale, mask):
    """One Q-chunk x K-chunk attention block with f32 accumulators.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D], mask: bool broadcastable to
    [B, Tq, Tk] (or None).  Returns (scores_max [B,H,Tq], exp_sum [B,H,Tq],
    out [B,Tq,H,D]) pieces for online-softmax merging.
    """
    # Operands stay in their input dtype (bf16 on the MXU path);
    # preferred_element_type gives f32 accumulation — softmax math is f32.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        mask = jnp.broadcast_to(mask, logits.shape[:1] + logits.shape[2:])
        logits = jnp.where(mask[:, None], logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)  # [B,H,Tq]
    # Guard fully-masked rows: exp(-inf - -inf) -> nan; use 0 contribution.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask[:, None], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return m_safe, l, o


def _merge(m1, l1, o1, m2, l2, o2):
    """Merge two online-softmax partial results."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    o = o1 * a1.transpose(0, 2, 1)[..., None] + \
        o2 * a2.transpose(0, 2, 1)[..., None]
    return m, l, o


def ring_attention(q, k, v, kv_mask=None, *, axis_name: str = "sp",
                   causal: bool = False, scale: Optional[float] = None):
    """Exact attention over a sequence sharded on `axis_name`.

    Must be called inside shard_map/pmap with `axis_name` bound.  Shapes
    (per device): q, k, v: [B, T_local, H, D]; kv_mask: [B, T_local] bool
    (True = attend) rotating around the ring with K/V.  Returns
    [B, T_local, H, D].
    """
    sp = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / jnp.sqrt(D).astype(
        jnp.float32)

    perm = [(j, (j + 1) % sp) for j in range(sp)]
    # positions for causal masking
    q_pos = my * T + jnp.arange(T)

    def attend(i, k_cur, v_cur, mask_cur, m, l, o):
        src = (my - i) % sp  # whose chunk we currently hold
        mask = None
        if causal:
            k_pos = src * T + jnp.arange(T)
            mask = (q_pos[:, None] >= k_pos[None, :])[None]  # [1,Tq,Tk]
        if mask_cur is not None:
            kvm = mask_cur[:, None, :]  # [B,1,Tk]
            mask = kvm if mask is None else (mask & kvm)
        m2, l2, o2 = _chunk_attn(q, k_cur, v_cur, scale=scale, mask=mask)
        return _merge(m, l, o, m2, l2, o2)

    def step(carry, i):
        k_cur, v_cur, mask_cur, m, l, o = carry
        m, l, o = attend(i, k_cur, v_cur, mask_cur, m, l, o)
        # rotate K/V (and their mask) one step around the ring
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = None if mask_cur is None else \
            lax.ppermute(mask_cur, axis_name, perm)
        return (k_nxt, v_nxt, mask_nxt, m, l, o), None

    # Derive the fresh accumulators from q (times zero) so they carry
    # exactly q's device-varying axes — shard_map's type system requires the
    # scan carry to match its (varying) outputs, and which axes vary depends
    # on the enclosing mesh, not just the ring axis.  XLA folds the zeros.
    zero32 = q.astype(jnp.float32) * 0.0  # accumulators are f32
    base = jnp.sum(zero32, axis=-1).transpose(0, 2, 1)  # [B,H,T]
    m0 = base - jnp.inf
    l0 = base
    o0 = zero32
    # The last chunk needs no rotation afterwards (the carry is discarded),
    # so scan sp-1 rotating steps and attend to the final chunk outside —
    # saves one ppermute round (fwd AND bwd) per call.
    carry = (k, v, kv_mask, m0, l0, o0)
    if sp > 1:
        carry, _ = lax.scan(step, carry, jnp.arange(sp - 1))
    k_l, v_l, mask_l, m, l, o = carry
    m, l, o = attend(sp - 1, k_l, v_l, mask_l, m, l, o)
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def full_attention(q, k, v, kv_mask=None, *, causal: bool = False,
                   scale: Optional[float] = None):
    """Single-device reference attention, [B, T, H, D] layout.
    kv_mask: [B, T] bool, True = position may be attended to."""
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / jnp.sqrt(D).astype(
        jnp.float32)
    mask = None
    if causal:
        pos = jnp.arange(T)
        mask = (pos[:, None] >= pos[None, :])[None]  # [1,T,T]
    if kv_mask is not None:
        kvm = kv_mask[:, None, :]  # [B,1,T]
        mask = kvm if mask is None else (mask & kvm)
    m, l, o = _chunk_attn(q, k, v, scale=scale, mask=mask)
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ulysses_attention(q, k, v, kv_mask=None, *, axis_name: str = "sp",
                      causal: bool = False):
    """Ulysses-style all-to-all sequence parallelism: exchange the local
    sequence shard for a head shard (one all_to_all over ICI), run EXACT
    full attention on the complete sequence for H/sp heads, and exchange
    back.  The alternative to the ring: 2 all_to_alls total instead of
    sp-1 ppermute rounds, at the cost of requiring heads % sp == 0 and
    holding the full sequence per device for the local heads.

    Must be called inside shard_map with `axis_name` bound; per-device
    shapes q/k/v: [B, T_local, H, D]; kv_mask: [B, T_local] bool.
    """
    sp = _axis_size(axis_name)
    if sp == 1:
        return full_attention(q, k, v, kv_mask, causal=causal)
    H = q.shape[2]
    if H % sp:
        raise ValueError(
            f"ulysses needs heads ({H}) divisible by the sp axis ({sp}); "
            "use the ring strategy for this mesh")

    def seq2head(x):    # [B, T/sp, H, D] -> [B, T, H/sp, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)
    mask = None
    if kv_mask is not None:
        mask = lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
    o = full_attention(qg, kg, vg, mask, causal=causal)
    # [B, T, H/sp, D] -> [B, T/sp, H, D]
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ring_self_attention(q, k, v, mesh: Mesh, kv_mask=None, *,
                        causal: bool = False, batch_axes=("dp", "fsdp"),
                        seq_axis: str = "sp", head_axis: str = "tp",
                        strategy: str = "ring"):
    """shard_map wrapper: global [B, T, H, D] arrays sharded
    (B over dp, T over sp, H over tp) -> exact global attention.
    kv_mask: optional [B, T] bool padding mask.

    ``strategy``: "ring" (K/V rotate via ppermute, O(T/sp) memory,
    works for any head count) or "ulysses" (2 all_to_alls exchanging
    seq-shards for head-shards, full attention locally; needs
    local heads % sp == 0).  Degenerates gracefully: any axis missing
    from the mesh is ignored.
    """
    if strategy not in ("ring", "ulysses"):
        # validate BEFORE the degenerate early-returns: a typo'd strategy
        # must fail on the dev box, not first on the production sp mesh
        raise ValueError(f"unknown sp strategy {strategy!r} "
                         "(expected 'ring' or 'ulysses')")
    batch = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    seq = seq_axis if seq_axis in mesh.axis_names else None
    heads = head_axis if head_axis in mesh.axis_names else None
    spec = P(batch, seq, heads, None)
    mspec = P(batch, seq)

    if seq is None:
        # No sequence axis: plain attention; XLA already handles dp/tp
        # sharding of the einsums without manual collectives.
        return full_attention(q, k, v, kv_mask, causal=causal)

    fn = functools.partial(
        ulysses_attention if strategy == "ulysses" else ring_attention,
        axis_name=seq, causal=causal)
    if kv_mask is None:
        mapped = shard_map(lambda q, k, v: fn(q, k, v), mesh=mesh,
                           in_specs=(spec, spec, spec), out_specs=spec)
        return mapped(q, k, v)
    mapped = shard_map(fn, mesh=mesh,
                       in_specs=(spec, spec, spec, mspec), out_specs=spec)
    return mapped(q, k, v, kv_mask)

"""Partition rules: regex path -> PartitionSpec, plus sharding helpers.

This is the single place parallelism strategy lives.  The reference encoded
its (only) strategy — block-partitioned data-parallel all-reduce — deep in
BigDL's AllReduceParameter (SURVEY.md §2.3); here a model ships a list of
``(param-path-regex, PartitionSpec)`` rules and XLA compiles the matching
collectives.  Data-parallel is the default (params replicated, batch sharded
over dp/fsdp axes); tensor-parallel models add rules for their weight dims.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PartitionRules = Sequence[Tuple[str, P]]

# Rules for plain data-parallel: every param replicated.
DP_RULES: PartitionRules = ((".*", P()),)

# ZeRO-style fully-sharded data parallel: every sizable tensor's leading
# dim sharded over the `fsdp` mesh axis — params AND optimizer state
# (state_sharding applies the rules to the whole TrainState, and adam's
# mu/nu mirror the param paths).  XLA inserts the all-gather before each
# use and reduce-scatters the gradients; tensors whose leading dim does
# not divide the axis fall back to replication (_valid_spec).  The
# reference has no counterpart (SURVEY §2.3: ZeRO absent upstream) —
# this is a TPU-native extension for models larger than one chip's HBM.
FSDP_RULES: PartitionRules = ((r".*", P("fsdp")),)


def _param_path(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _valid_spec(spec: P, leaf: Any, mesh: Optional[Mesh]) -> P:
    """Drop spec entries that don't divide the leaf's shape (or exceed rank).

    Lets one rule set serve many layer sizes: a ('tp'-sharded) rule applied
    to a tensor whose dim isn't divisible by the tp size falls back to
    replication on that dim rather than erroring at jit time.
    """
    shape = getattr(leaf, "shape", ())
    if len(spec) > len(shape):
        spec = P(*spec[: len(shape)])
    if mesh is None:
        return spec
    out = []
    for dim, entry in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if entry is None:
            out.append(None)
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        if any(n not in mesh.shape for n in names):
            out.append(None)  # rule references an axis this mesh lacks
            continue
        size = int(np.prod([mesh.shape[n] for n in names]))
        out.append(entry if dim % size == 0 else None)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def match_partition_rules(
    rules: PartitionRules,
    tree: Any,
    mesh: Optional[Mesh] = None,
) -> Any:
    """Map a pytree of arrays to a pytree of PartitionSpec by regex rules.

    Scalars are always replicated.  First matching rule wins; a tree leaf
    matching no rule is replicated (unlike the reference snippet pattern which
    errors — replication is always correct, just maybe slow).
    """

    def spec_for(path, leaf) -> P:
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            return P()
        name = _param_path(path)
        for pattern, spec in rules:
            if re.search(pattern, name):
                return _valid_spec(spec, leaf, mesh)
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, tree)


def data_sharding(mesh: Mesh, *, extra_batch_axes: Sequence[str] = ()) -> NamedSharding:
    """Sharding for a host batch: leading dim split over all dp-like axes."""
    from analytics_zoo_tpu.parallel.mesh import batch_axes

    axes = tuple(batch_axes(mesh))
    axes += tuple(a for a in extra_batch_axes
                  if a in mesh.axis_names and a not in axes)
    return NamedSharding(mesh, P(axes if axes else None))


def state_sharding(mesh: Mesh, state: Any,
                   rules: PartitionRules = DP_RULES) -> Any:
    """NamedSharding pytree for a TrainState/params pytree under `rules`."""
    specs = match_partition_rules(rules, state, mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def data_process_groups(sharding: NamedSharding):
    """Group processes by their row coverage under the batch sharding.

    The batch dim is sharded over the dp-like mesh axes; whether two
    PROCESSES hold the same or disjoint rows depends on how those axes lie
    relative to the process boundary.  dp across hosts (the classic layout)
    -> every process owns a distinct row block and hosts feed disjoint
    data; a pp/ep/tp-only process boundary -> every process owns ALL row
    blocks and hosts must feed IDENTICAL (replicated) data.  Mixed layouts
    (4 hosts over dp=2 x pp=2) give groups of replica processes.

    Returns ``(n_groups, my_group, group_of_process)`` where
    ``group_of_process[p]`` is the group id of process p, groups ordered by
    first owned row block.  Data loaders split datasets across GROUPS (one
    shard per group, replicated within), never blindly across processes.
    """
    mesh = sharding.mesh
    from analytics_zoo_tpu.parallel.mesh import mesh_batch_size

    nb = max(1, mesh_batch_size(mesh))
    imap = sharding.devices_indices_map((nb,))
    per_proc = {}
    for d, idx in imap.items():
        sl = idx[0] if idx else slice(None)
        start = sl.start or 0 if isinstance(sl, slice) else 0
        per_proc.setdefault(d.process_index, set()).add(start)
    by_coverage = {}
    for p, blocks in per_proc.items():
        by_coverage.setdefault(tuple(sorted(blocks)), []).append(p)
    ordered = sorted(by_coverage)
    group_of_process = {}
    for gi, cov in enumerate(ordered):
        for p in by_coverage[cov]:
            group_of_process[p] = gi
    gop = [group_of_process.get(p, 0)
           for p in range(max(group_of_process, default=0) + 1)]
    me = jax.process_index()
    return len(ordered), group_of_process.get(me, 0), gop


def with_sharding_constraint(x: Any, spec: P) -> Any:
    """`lax.with_sharding_constraint` that is a no-op outside jit/mesh."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x

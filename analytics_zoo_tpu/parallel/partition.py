"""Partition rules: regex path -> PartitionSpec, plus sharding helpers.

This is the single place parallelism strategy lives.  The reference encoded
its (only) strategy — block-partitioned data-parallel all-reduce — deep in
BigDL's AllReduceParameter (SURVEY.md §2.3); here a model ships a list of
``(param-path-regex, PartitionSpec)`` rules and XLA compiles the matching
collectives.  Data-parallel is the default (params replicated, batch sharded
over dp/fsdp axes); tensor-parallel models add rules for their weight dims.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PartitionRules = Sequence[Tuple[str, P]]

# Rules for plain data-parallel: every param replicated.
DP_RULES: PartitionRules = ((".*", P()),)

# ZeRO-style fully-sharded data parallel: every sizable tensor's leading
# dim sharded over the `fsdp` mesh axis — params AND optimizer state
# (state_sharding applies the rules to the whole TrainState, and adam's
# mu/nu mirror the param paths).  XLA inserts the all-gather before each
# use and reduce-scatters the gradients; tensors whose leading dim does
# not divide the axis fall back to replication (_valid_spec).  The
# reference has no counterpart (SURVEY §2.3: ZeRO absent upstream) —
# this is a TPU-native extension for models larger than one chip's HBM.
FSDP_RULES: PartitionRules = ((r".*", P("fsdp")),)


def _param_path(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _valid_spec(spec: P, leaf: Any, mesh: Optional[Mesh]) -> P:
    """Drop spec entries that don't divide the leaf's shape (or exceed rank).

    Lets one rule set serve many layer sizes: a ('tp'-sharded) rule applied
    to a tensor whose dim isn't divisible by the tp size falls back to
    replication on that dim rather than erroring at jit time.
    """
    shape = getattr(leaf, "shape", ())
    if len(spec) > len(shape):
        spec = P(*spec[: len(shape)])
    if mesh is None:
        return spec
    out = []
    for dim, entry in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if entry is None:
            out.append(None)
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        if any(n not in mesh.shape for n in names):
            out.append(None)  # rule references an axis this mesh lacks
            continue
        size = int(np.prod([mesh.shape[n] for n in names]))
        out.append(entry if dim % size == 0 else None)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def match_partition_rules(
    rules: PartitionRules,
    tree: Any,
    mesh: Optional[Mesh] = None,
) -> Any:
    """Map a pytree of arrays to a pytree of PartitionSpec by regex rules.

    Scalars are always replicated.  First matching rule wins; a tree leaf
    matching no rule is replicated (unlike the reference snippet pattern which
    errors — replication is always correct, just maybe slow).
    """

    def spec_for(path, leaf) -> P:
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            return P()
        name = _param_path(path)
        for pattern, spec in rules:
            if re.search(pattern, name):
                return _valid_spec(spec, leaf, mesh)
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, tree)


def data_sharding(mesh: Mesh, *, extra_batch_axes: Sequence[str] = ()) -> NamedSharding:
    """Sharding for a host batch: leading dim split over all dp-like axes."""
    from analytics_zoo_tpu.parallel.mesh import batch_axes

    axes = tuple(batch_axes(mesh))
    axes += tuple(a for a in extra_batch_axes
                  if a in mesh.axis_names and a not in axes)
    return NamedSharding(mesh, P(axes if axes else None))


def state_sharding(mesh: Mesh, state: Any,
                   rules: PartitionRules = DP_RULES) -> Any:
    """NamedSharding pytree for a TrainState/params pytree under `rules`."""
    specs = match_partition_rules(rules, state, mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def with_sharding_constraint(x: Any, spec: P) -> Any:
    """`lax.with_sharding_constraint` that is a no-op outside jit/mesh."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x

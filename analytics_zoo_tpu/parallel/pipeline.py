"""Pipeline parallelism over the ``pp`` mesh axis (SPMD GPipe).

No reference counterpart (SURVEY.md §2.3 item 6: the reference is a CPU
data-parallel stack); like ring attention this is a TPU-native extension
that makes the mesh's declared ``pp`` axis real.

TPU-first shape of the solution: pipelining is expressed as ONE jitted SPMD
program, not a runtime scheduler.  Stage parameters are stacked on a leading
stage dim sharded ``P("pp")``; inside ``shard_map`` each pp rank holds its
stage's weights, a ``lax.scan`` runs the GPipe tick schedule, and
activations hop rank→rank over ICI via ``lax.ppermute``.  Every rank
computes every tick (bubble ticks compute masked garbage) — the standard
static-SPMD pipeline trade: bubble fraction (S-1)/(M+S-1) for S stages and
M microbatches.  The whole schedule differentiates through scan/ppermute,
so the SAME code is forward and backward pipelining; XLA overlaps the
ppermute hop with the next tick's compute.

Two training schedules: autodiff through ``pipeline_apply`` yields GPipe
(all-forward-then-all-backward, activation residency grows with M), and
``pipeline_value_and_grad`` runs flat 1F1B (interleaved forward/backward
ticks, residency bounded at 2S microbatches per rank via stage-level
remat).  The trade is explicit: the lockstep 1F1B schedule idles
(2S-2)/(M+2S-2) of its slots — about twice GPipe's bubble at equal M —
but its O(S) memory bound is what lets M grow to amortise the bubble
where GPipe's O(M) residency cannot (``pipeline_1f1b_stats``).

Composes with the batch axes: batch stays sharded over dp/fsdp (each pp
rank sees its dp-local batch).  Stage-INTERNAL tensor parallelism does
NOT compose: stages execute inside shard_map, where a tp-sharded weight
is simply all-gathered per tick (at-rest memory, no compute split) — pair
pp with dp/fsdp, and use tp on the non-pipelined parts of the model.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.partition import PartitionRules

StageFn = Callable[[Any, jax.Array], jax.Array]


def sequential_apply(stage_fn: StageFn, stacked_params: Any,
                     x: jax.Array) -> jax.Array:
    """Reference semantics: apply the S stacked stages in order (what the
    pipeline must equal).  Used on meshes without a pp axis."""

    def body(a, p):
        return stage_fn(p, a), None

    out, _ = lax.scan(body, x, stacked_params)
    return out


def pipeline_apply(stage_fn: StageFn, stacked_params: Any, x: jax.Array,
                   mesh: Mesh, n_microbatches: int, *,
                   batch_axes: Sequence[str] = ("dp", "fsdp"),
                   pp_axis: str = "pp") -> jax.Array:
    """Run ``x`` through S pipelined stages; equals ``sequential_apply``.

    stage_fn: ``(one_stage_params, act) -> act`` — shape- and
    dtype-preserving, per-sample (no cross-batch mixing: microbatching
    changes what a batch is).
    stacked_params: pytree with leading dim S on every leaf (S =
    ``mesh.shape[pp_axis]``), to be sharded ``P("pp")``.
    x: global batch ``[B, ...]``; each rank splits its local batch into
    ``gcd(n_microbatches, local_batch)`` microbatches (the knob is
    perf-only — a non-dividing value degrades the bubble, never errors).
    """
    S = int(mesh.shape[pp_axis]) if pp_axis in mesh.axis_names else 1
    if S == 1:
        return sequential_apply(stage_fn, stacked_params, x)
    # Each rank consumes exactly one stage of the stacked params; a stack
    # whose leading dim differs from the pp axis size would silently drop
    # (or wrap) stages after sharding.
    shapes = [jnp.shape(leaf) for leaf in jax.tree.leaves(stacked_params)]
    bad = {s[0] if s else None for s in shapes} - {S}
    if bad:
        raise ValueError(
            f"stacked_params leading dim(s) {sorted(bad, key=str)} != pp "
            f"axis size {S}; every leaf must stack exactly one slice per "
            f"pp rank")
    M = int(n_microbatches)
    batch = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    xspec = P(batch, *([None] * (x.ndim - 1)))
    pspec = jax.tree.map(lambda _: P(pp_axis), stacked_params)

    def ranked(params, xl):
        idx = lax.axis_index(pp_axis)
        p_local = jax.tree.map(lambda a: a[0], params)  # [1,...] -> [...]
        b = xl.shape[0]
        # n_microbatches is a performance knob, never a correctness
        # constraint: when it doesn't divide the per-rank batch (e.g. the
        # Estimator's tiny init batch), fall back to the nearest divisor
        m_eff = math.gcd(M, b)
        mb = xl.reshape((m_eff, b // m_eff) + xl.shape[1:])
        ticks = m_eff + S - 1

        def tick(carry, t):
            state_in, out_buf = carry
            inject = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, m_eff - 1), 0, keepdims=False)
            cur = jnp.where(idx == 0, inject, state_in)
            y = stage_fn(p_local, cur)
            # the last rank finished microbatch t-(S-1) this tick
            w = t - (S - 1)
            valid = (idx == S - 1) & (w >= 0)
            wc = jnp.clip(w, 0, m_eff - 1)
            slot = lax.dynamic_index_in_dim(out_buf, wc, 0, keepdims=False)
            out_buf = lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(valid, y, slot), wc, 0)
            nxt = lax.ppermute(y, pp_axis,
                               [(i, i + 1) for i in range(S - 1)])
            return (nxt, out_buf), None

        # Scan carries must be pp-VARYING from tick 0: the loop writes
        # ppermute/axis_index-derived values into them, and shard_map's
        # vma type system rejects an invariant->varying carry (same
        # constraint ring_attention.py works around).  lax.pvary marks
        # the zeros as device-varying without computing anything.
        def vary(z):
            try:
                return lax.pcast(z, pp_axis, to="varying")
            except (AttributeError, TypeError):
                return z + (idx * 0).astype(z.dtype)
        carry = (vary(jnp.zeros_like(mb[0])), vary(jnp.zeros_like(mb)))
        (_, out_buf), _ = lax.scan(tick, carry, jnp.arange(ticks))
        # outputs live on the last rank only; psum broadcasts them so the
        # result is pp-invariant (loss/metrics compute identically on all
        # ranks — same contract as data parallelism)
        out = lax.psum(jnp.where(idx == S - 1, out_buf, 0.0), pp_axis)
        return out.reshape(xl.shape).astype(xl.dtype)

    return jax.shard_map(ranked, mesh=mesh, in_specs=(pspec, xspec),
                         out_specs=xspec)(stacked_params, x)


def pipeline_1f1b_stats(n_stages: int, n_microbatches: int) -> dict:
    """Static schedule facts for ``pipeline_value_and_grad`` (asserted by
    tests, cited in docs).  The lockstep combined-tick schedule runs
    ``M + 2S - 2`` ticks (each tick does one forward AND one backward
    unit per rank) and keeps at most ``2S`` microbatch activations
    resident per rank — versus the GPipe-autodiff path, whose transposed
    scan stores all ``M``.  Honest accounting: a rank does useful work in
    M of its M+2S-2 forward slots and M of its backward slots, so the
    idle fraction is ``(2S-2)/(M+2S-2)`` — about TWICE GPipe's
    ``(S-1)/(M+S-1)`` at the same M.  This schedule buys the O(S) memory
    bound by paying bubble, and the memory bound is exactly what lets M
    grow to amortise it (``gpipe_bubble_fraction`` included for the
    comparison)."""
    S, M = int(n_stages), int(n_microbatches)
    return {
        "ticks": M + 2 * S - 2,
        "residual_slots": 2 * S,
        "gpipe_resident_microbatches": M,
        "bubble_fraction": (2 * S - 2) / (M + 2 * S - 2),
        "gpipe_bubble_fraction": (S - 1) / (M + S - 1),
    }


def pipeline_value_and_grad(stage_fn: StageFn, loss_fn, stacked_params,
                            x: jax.Array, labels, mesh: Mesh,
                            n_microbatches: int, *,
                            batch_axes: Sequence[str] = ("dp", "fsdp"),
                            pp_axis: str = "pp"):
    """One interleaved-1F1B training tick-schedule: loss AND gradients of
    ``mean(loss_fn(stage_S(...stage_1(x)), labels))`` in a single
    shard_map scan.

    Why not just ``jax.grad(pipeline_apply)``?  Autodiff transposes the
    forward scan into an all-forward-then-all-backward schedule (GPipe):
    every one of the M microbatches' stage activations stays resident
    until its backward runs, so peak memory grows with M — and M is
    exactly the knob one raises to shrink the bubble.  1F1B starts
    microbatch m's backward as soon as its last-stage forward finishes,
    bounding resident activations at 2S per rank regardless of M
    (``pipeline_1f1b_stats``).  The backward unit recomputes its stage
    forward from the saved stage INPUT (stage-level remat — the
    standard trade), so each (microbatch, stage) costs fwd + fwd + vjp
    instead of fwd + vjp.

    Schedule (flat/non-interleaved 1F1B, combined F+B ticks): rank r
    forwards microbatch ``m`` at tick ``m + r`` and backwards it at tick
    ``m + 2S - 2 - r``; the last rank's backward fuses with its forward
    (same tick), activations hop r->r+1 and activation-grads hop r->r-1
    via ``lax.ppermute`` each tick.

    Args mirror ``pipeline_apply`` plus ``labels`` ([B, ...], same
    leading batch dim as x) and ``loss_fn(y_mb, label_mb) -> scalar``
    (MEAN over the microbatch).  Returns ``(loss, grads, dx)`` where
    ``grads`` matches ``stacked_params`` (sharded P(pp) like the
    params) and ``dx`` is the loss gradient w.r.t. ``x`` (feeds
    embedding/pre-trunk backward when composed manually).
    """
    S = int(mesh.shape[pp_axis]) if pp_axis in mesh.axis_names else 1
    if S == 1:
        def seq_loss(p, xx):
            return loss_fn(sequential_apply(stage_fn, p, xx), labels)

        loss, (gp, gx) = jax.value_and_grad(seq_loss, argnums=(0, 1))(
            stacked_params, x)
        return loss, gp, gx
    bad = {jnp.shape(leaf)[0] if jnp.shape(leaf) else None
           for leaf in jax.tree.leaves(stacked_params)} - {S}
    if bad:
        raise ValueError(
            f"stacked_params leading dim(s) {sorted(bad, key=str)} != pp "
            f"axis size {S}")
    M = int(n_microbatches)
    batch = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    xspec = P(batch, *([None] * (x.ndim - 1)))
    lspec = P(batch, *([None] * (jnp.ndim(labels) - 1)))
    pspec = jax.tree.map(lambda _: P(pp_axis), stacked_params)

    def ranked(params, xl, ll):
        idx = lax.axis_index(pp_axis)
        b = xl.shape[0]
        m_eff = math.gcd(M, b)
        mb = xl.reshape((m_eff, b // m_eff) + xl.shape[1:])
        lb = ll.reshape((m_eff, b // m_eff) + ll.shape[1:])
        R = 2 * S                        # residual ring slots
        ticks = m_eff + 2 * S - 2

        def vary(z):
            # Two reasons to mark values device-varying: (1) scan carries
            # pick up pp-varying (ppermute/axis_index) and batch-varying
            # (dp-sharded activations) values, and an invariant->varying
            # carry fails shard_map's vma typecheck; (2) params must be
            # batch-VARYING before jax.vjp, else autodiff auto-psums the
            # param cotangent across dp on EVERY tick (one all-reduce per
            # tick, and it double-counts a later mean) — varied params get
            # per-rank cotangents we reduce ONCE at the end.
            for ax in (pp_axis,) + tuple(batch or ()):
                try:
                    z = lax.pcast(z, ax, to="varying")
                except (AttributeError, TypeError):
                    # no lax.pcast on this JAX: force variance on THIS
                    # axis arithmetically and keep looping — falling out
                    # early would leave params batch-invariant, and the
                    # vjp transpose would then psum param cotangents
                    # across dp every tick (n_dp-scaled grads)
                    z = z + (lax.axis_index(ax) * 0).astype(z.dtype)
                except ValueError:
                    pass        # already varying on ax
            return z

        p_local = jax.tree.map(lambda a: vary(a[0]), params)

        def head(y, lbl):
            """Last rank: per-microbatch loss + dL/dy."""
            return jax.value_and_grad(lambda yy: loss_fn(yy, lbl))(y)

        def tick(carry, t):
            act_in, gract_in, resbuf, gacc, dxbuf, lossbuf = carry
            m_f = t - idx                       # fwd microbatch index
            m_b = t - (2 * S - 2 - idx)         # bwd microbatch index
            valid_f = (m_f >= 0) & (m_f < m_eff)
            valid_b = (m_b >= 0) & (m_b < m_eff)
            mfc = jnp.clip(m_f, 0, m_eff - 1)
            mbc = jnp.clip(m_b, 0, m_eff - 1)
            # ---- forward unit ----
            inject = lax.dynamic_index_in_dim(mb, mfc, 0, keepdims=False)
            cur = jnp.where(idx == 0, inject, act_in)
            y = stage_fn(p_local, cur)
            # save this stage's INPUT for the recompute-backward
            slot_f = mfc % R
            old = lax.dynamic_index_in_dim(resbuf, slot_f, 0,
                                           keepdims=False)
            resbuf = lax.dynamic_update_index_in_dim(
                resbuf, jnp.where(valid_f, cur, old), slot_f, 0)
            # last rank: loss + dL/dy for the microbatch it JUST forwarded
            lbl = lax.dynamic_index_in_dim(lb, mfc, 0, keepdims=False)
            loss_m, gy = head(y, lbl)
            # ---- backward unit (stage-level remat) ----
            a_saved = lax.dynamic_index_in_dim(resbuf, mbc % R, 0,
                                               keepdims=False)
            g_use = jnp.where(idx == S - 1, gy.astype(gract_in.dtype),
                              gract_in)
            _, vjp = jax.vjp(stage_fn, p_local, a_saved)
            dp, da = vjp(g_use.astype(y.dtype))
            gacc = jax.tree.map(
                lambda g, d: g + jnp.where(valid_b, d, 0.0).astype(g.dtype),
                gacc, dp)
            # rank 0's da is dL/dx for microbatch m_b
            dslot = lax.dynamic_index_in_dim(dxbuf, mbc, 0, keepdims=False)
            dxbuf = lax.dynamic_update_index_in_dim(
                dxbuf, jnp.where((idx == 0) & valid_b, da, dslot), mbc, 0)
            lslot = lax.dynamic_index_in_dim(lossbuf, mfc, 0,
                                             keepdims=False)
            lossbuf = lax.dynamic_update_index_in_dim(
                lossbuf, jnp.where((idx == S - 1) & valid_f, loss_m,
                                   lslot), mfc, 0)
            # ---- hops: activations r->r+1, activation-grads r->r-1 ----
            act_out = lax.ppermute(y, pp_axis,
                                   [(i, i + 1) for i in range(S - 1)])
            gract_out = lax.ppermute(da, pp_axis,
                                     [(i + 1, i) for i in range(S - 1)])
            return (act_out, gract_out, resbuf, gacc, dxbuf,
                    lossbuf), None

        z_mb = jnp.zeros_like(mb[0])
        carry = (vary(z_mb), vary(z_mb),
                 vary(jnp.zeros((R,) + z_mb.shape, z_mb.dtype)),
                 jax.tree.map(lambda p: vary(jnp.zeros_like(p)), p_local),
                 vary(jnp.zeros_like(mb)),
                 vary(jnp.zeros((m_eff,), jnp.float32)))
        (_, _, _, gacc, dxbuf, lossbuf), _ = lax.scan(
            tick, carry, jnp.arange(ticks))
        # per-microbatch means -> global mean; grads scale by 1/M
        n_b = 1
        for ax in (batch or ()):
            n_b *= int(mesh.shape[ax])
        loss = lax.psum(jnp.where(idx == S - 1, jnp.sum(lossbuf), 0.0),
                        pp_axis) / m_eff
        # d(global mean)/dx on this rank = (1/n_dp) d(local mean)/dx
        dx = lax.psum(jnp.where(idx == 0, dxbuf, 0.0),
                      pp_axis).reshape(xl.shape) / (m_eff * n_b)
        grads = jax.tree.map(lambda g: g / m_eff, gacc)
        if batch:
            # each data-parallel rank saw its own local batch: the global
            # mean loss/grad is the mean across them (dx stays sharded —
            # it IS per-rank)
            loss = lax.pmean(loss, batch)
            grads = jax.tree.map(lambda g: lax.pmean(g, batch), grads)
        grads = jax.tree.map(lambda g: g[None], grads)
        return loss, grads, dx.astype(xl.dtype)

    loss, grads, dx = jax.shard_map(
        ranked, mesh=mesh, in_specs=(pspec, xspec, lspec),
        out_specs=(P(), pspec, xspec))(stacked_params, x, labels)
    return loss, grads, dx


def pp_stage_rules(inner: PartitionRules = ()) -> PartitionRules:
    """Partition rules for GPipe's stacked stage params: prepend the stage
    dim ``"pp"`` to each stage-internal rule, then shard everything else's
    stage dim.  ``inner`` patterns should be stage-scoped (they are matched
    against paths under ``stages/``)."""
    out = [(pat, P("pp", *tuple(spec))) for (pat, spec) in inner]
    out.append((r"stages/", P("pp")))
    return tuple(out)


class GPipe(nn.Module):
    """Flax wrapper: S copies of a stage module run as a pipeline.

    ``stage`` is a template module whose ``__call__(x)`` is shape- and
    dtype-preserving and per-sample (Dense/LayerNorm/attention fine;
    BatchNorm or dropout belong outside the pipelined trunk — stages run
    without rng/mutable plumbing).  Params are created stacked ``[S, ...]``
    (path prefix ``stages/``) so ``pp_stage_rules`` shards them; on meshes
    without pp > 1 the stages run sequentially — same math, one device.
    """

    stage: nn.Module
    n_stages: int
    n_microbatches: int = 4
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x):
        template = self.stage.clone(parent=None)

        def init_stacked(rng) -> Any:
            keys = jax.random.split(rng, self.n_stages)
            probe = x[:1]
            return jax.vmap(
                lambda k: template.init(k, probe)["params"])(keys)

        params = self.param("stages", init_stacked)

        def fn(p, a):
            return template.apply({"params": p}, a)

        if self.mesh is not None and \
                self.mesh.shape.get("pp", 1) == self.n_stages and \
                self.n_stages > 1:
            return pipeline_apply(fn, params, x, self.mesh,
                                  self.n_microbatches)
        return sequential_apply(fn, params, x)

"""Pipeline parallelism over the ``pp`` mesh axis (SPMD GPipe).

No reference counterpart (SURVEY.md §2.3 item 6: the reference is a CPU
data-parallel stack); like ring attention this is a TPU-native extension
that makes the mesh's declared ``pp`` axis real.

TPU-first shape of the solution: pipelining is expressed as ONE jitted SPMD
program, not a runtime scheduler.  Stage parameters are stacked on a leading
stage dim sharded ``P("pp")``; inside ``shard_map`` each pp rank holds its
stage's weights, a ``lax.scan`` runs the GPipe tick schedule, and
activations hop rank→rank over ICI via ``lax.ppermute``.  Every rank
computes every tick (bubble ticks compute masked garbage) — the standard
static-SPMD pipeline trade: bubble fraction (S-1)/(M+S-1) for S stages and
M microbatches.  The whole schedule differentiates through scan/ppermute,
so the SAME code is forward and backward pipelining; XLA overlaps the
ppermute hop with the next tick's compute.

Composes with the batch axes: batch stays sharded over dp/fsdp (each pp
rank sees its dp-local batch).  Stage-INTERNAL tensor parallelism does
NOT compose: stages execute inside shard_map, where a tp-sharded weight
is simply all-gathered per tick (at-rest memory, no compute split) — pair
pp with dp/fsdp, and use tp on the non-pipelined parts of the model.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.partition import PartitionRules

StageFn = Callable[[Any, jax.Array], jax.Array]


def sequential_apply(stage_fn: StageFn, stacked_params: Any,
                     x: jax.Array) -> jax.Array:
    """Reference semantics: apply the S stacked stages in order (what the
    pipeline must equal).  Used on meshes without a pp axis."""

    def body(a, p):
        return stage_fn(p, a), None

    out, _ = lax.scan(body, x, stacked_params)
    return out


def pipeline_apply(stage_fn: StageFn, stacked_params: Any, x: jax.Array,
                   mesh: Mesh, n_microbatches: int, *,
                   batch_axes: Sequence[str] = ("dp", "fsdp"),
                   pp_axis: str = "pp") -> jax.Array:
    """Run ``x`` through S pipelined stages; equals ``sequential_apply``.

    stage_fn: ``(one_stage_params, act) -> act`` — shape- and
    dtype-preserving, per-sample (no cross-batch mixing: microbatching
    changes what a batch is).
    stacked_params: pytree with leading dim S on every leaf (S =
    ``mesh.shape[pp_axis]``), to be sharded ``P("pp")``.
    x: global batch ``[B, ...]``; each rank splits its local batch into
    ``gcd(n_microbatches, local_batch)`` microbatches (the knob is
    perf-only — a non-dividing value degrades the bubble, never errors).
    """
    S = int(mesh.shape[pp_axis]) if pp_axis in mesh.axis_names else 1
    if S == 1:
        return sequential_apply(stage_fn, stacked_params, x)
    # Each rank consumes exactly one stage of the stacked params; a stack
    # whose leading dim differs from the pp axis size would silently drop
    # (or wrap) stages after sharding.
    shapes = [jnp.shape(leaf) for leaf in jax.tree.leaves(stacked_params)]
    bad = {s[0] if s else None for s in shapes} - {S}
    if bad:
        raise ValueError(
            f"stacked_params leading dim(s) {sorted(bad, key=str)} != pp "
            f"axis size {S}; every leaf must stack exactly one slice per "
            f"pp rank")
    M = int(n_microbatches)
    batch = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    xspec = P(batch, *([None] * (x.ndim - 1)))
    pspec = jax.tree.map(lambda _: P(pp_axis), stacked_params)

    def ranked(params, xl):
        idx = lax.axis_index(pp_axis)
        p_local = jax.tree.map(lambda a: a[0], params)  # [1,...] -> [...]
        b = xl.shape[0]
        # n_microbatches is a performance knob, never a correctness
        # constraint: when it doesn't divide the per-rank batch (e.g. the
        # Estimator's tiny init batch), fall back to the nearest divisor
        m_eff = math.gcd(M, b)
        mb = xl.reshape((m_eff, b // m_eff) + xl.shape[1:])
        ticks = m_eff + S - 1

        def tick(carry, t):
            state_in, out_buf = carry
            inject = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, m_eff - 1), 0, keepdims=False)
            cur = jnp.where(idx == 0, inject, state_in)
            y = stage_fn(p_local, cur)
            # the last rank finished microbatch t-(S-1) this tick
            w = t - (S - 1)
            valid = (idx == S - 1) & (w >= 0)
            wc = jnp.clip(w, 0, m_eff - 1)
            slot = lax.dynamic_index_in_dim(out_buf, wc, 0, keepdims=False)
            out_buf = lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(valid, y, slot), wc, 0)
            nxt = lax.ppermute(y, pp_axis,
                               [(i, i + 1) for i in range(S - 1)])
            return (nxt, out_buf), None

        # Scan carries must be pp-VARYING from tick 0: the loop writes
        # ppermute/axis_index-derived values into them, and shard_map's
        # vma type system rejects an invariant->varying carry (same
        # constraint ring_attention.py works around).  lax.pvary marks
        # the zeros as device-varying without computing anything.
        def vary(z):
            try:
                return lax.pcast(z, pp_axis, to="varying")
            except (AttributeError, TypeError):
                return z + (idx * 0).astype(z.dtype)
        carry = (vary(jnp.zeros_like(mb[0])), vary(jnp.zeros_like(mb)))
        (_, out_buf), _ = lax.scan(tick, carry, jnp.arange(ticks))
        # outputs live on the last rank only; psum broadcasts them so the
        # result is pp-invariant (loss/metrics compute identically on all
        # ranks — same contract as data parallelism)
        out = lax.psum(jnp.where(idx == S - 1, out_buf, 0.0), pp_axis)
        return out.reshape(xl.shape).astype(xl.dtype)

    return jax.shard_map(ranked, mesh=mesh, in_specs=(pspec, xspec),
                         out_specs=xspec)(stacked_params, x)


def pp_stage_rules(inner: PartitionRules = ()) -> PartitionRules:
    """Partition rules for GPipe's stacked stage params: prepend the stage
    dim ``"pp"`` to each stage-internal rule, then shard everything else's
    stage dim.  ``inner`` patterns should be stage-scoped (they are matched
    against paths under ``stages/``)."""
    out = [(pat, P("pp", *tuple(spec))) for (pat, spec) in inner]
    out.append((r"stages/", P("pp")))
    return tuple(out)


class GPipe(nn.Module):
    """Flax wrapper: S copies of a stage module run as a pipeline.

    ``stage`` is a template module whose ``__call__(x)`` is shape- and
    dtype-preserving and per-sample (Dense/LayerNorm/attention fine;
    BatchNorm or dropout belong outside the pipelined trunk — stages run
    without rng/mutable plumbing).  Params are created stacked ``[S, ...]``
    (path prefix ``stages/``) so ``pp_stage_rules`` shards them; on meshes
    without pp > 1 the stages run sequentially — same math, one device.
    """

    stage: nn.Module
    n_stages: int
    n_microbatches: int = 4
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x):
        template = self.stage.clone(parent=None)

        def init_stacked(rng) -> Any:
            keys = jax.random.split(rng, self.n_stages)
            probe = x[:1]
            return jax.vmap(
                lambda k: template.init(k, probe)["params"])(keys)

        params = self.param("stages", init_stacked)

        def fn(p, a):
            return template.apply({"params": p}, a)

        if self.mesh is not None and \
                self.mesh.shape.get("pp", 1) == self.n_stages and \
                self.n_stages > 1:
            return pipeline_apply(fn, params, x, self.mesh,
                                  self.n_microbatches)
        return sequential_apply(fn, params, x)

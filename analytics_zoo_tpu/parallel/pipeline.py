"""Pipeline parallelism over the ``pp`` mesh axis (SPMD GPipe).

No reference counterpart (SURVEY.md §2.3 item 6: the reference is a CPU
data-parallel stack); like ring attention this is a TPU-native extension
that makes the mesh's declared ``pp`` axis real.

TPU-first shape of the solution: pipelining is expressed as ONE jitted SPMD
program, not a runtime scheduler.  Stage parameters are stacked on a leading
stage dim sharded ``P("pp")``; inside ``shard_map`` each pp rank holds its
stage's weights, a ``lax.scan`` runs the GPipe tick schedule, and
activations hop rank→rank over ICI via ``lax.ppermute``.  Every rank
computes every tick (bubble ticks compute masked garbage) — the standard
static-SPMD pipeline trade: bubble fraction (S-1)/(M+S-1) for S stages and
M microbatches.  The whole schedule differentiates through scan/ppermute,
so the SAME code is forward and backward pipelining; XLA overlaps the
ppermute hop with the next tick's compute.

Three training schedules: autodiff through ``pipeline_apply`` yields
GPipe (all-forward-then-all-backward, activation residency grows with
M); ``pipeline_value_and_grad`` / ``pipeline_apply_1f1b`` run flat 1F1B
(combined forward/backward ticks, residency bounded at 2S microbatches
per rank via stage-level remat); and ``n_chunks=v > 1`` /
``pipeline_apply_interleaved`` run INTERLEAVED 1F1B (v virtual model
chunks per rank, round-robin placement, wrap-around ppermute).  The
trades are explicit: flat 1F1B idles (2S-2)/(M+2S-2) of its slots —
about twice GPipe's bubble at equal M — but its O(S) memory bound lets
M grow to amortise the bubble where GPipe's O(M) residency cannot
(``pipeline_1f1b_stats``); interleaving then cuts the flat bubble to
S+(S-2)/v flat-tick equivalents for v× the residual-ring memory and
ppermute traffic (``interleaved_1f1b_stats``).

Composes with the batch axes: batch stays sharded over dp/fsdp (each pp
rank sees its dp-local batch).  Stage-INTERNAL tensor parallelism does
NOT compose: stages execute inside shard_map, where a tp-sharded weight
is simply all-gathered per tick (at-rest memory, no compute split) — pair
pp with dp/fsdp, and use tp on the non-pipelined parts of the model.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.mesh import shard_map as _shard_map
from analytics_zoo_tpu.parallel.partition import PartitionRules

StageFn = Callable[[Any, jax.Array], jax.Array]


def _check_stacked(stacked_params, S: int) -> None:
    """Each rank consumes exactly one stage of the stacked params; a
    stack whose leading dim differs from the pp axis size would silently
    drop (or wrap) stages after sharding.  Shared by every pipelined
    entry point so validation can never drift between them."""
    shapes = [jnp.shape(leaf) for leaf in jax.tree.leaves(stacked_params)]
    bad = {s[0] if s else None for s in shapes} - {S}
    if bad:
        raise ValueError(
            f"stacked_params leading dim(s) {sorted(bad, key=str)} != pp "
            f"axis size {S}; every leaf must stack exactly one slice per "
            f"pp rank")


def sequential_apply(stage_fn: StageFn, stacked_params: Any,
                     x: jax.Array) -> jax.Array:
    """Reference semantics: apply the S stacked stages in order (what the
    pipeline must equal).  Used on meshes without a pp axis."""

    def body(a, p):
        return stage_fn(p, a), None

    out, _ = lax.scan(body, x, stacked_params)
    return out


def pipeline_apply(stage_fn: StageFn, stacked_params: Any, x: jax.Array,
                   mesh: Mesh, n_microbatches: int, *,
                   batch_axes: Sequence[str] = ("dp", "fsdp"),
                   pp_axis: str = "pp") -> jax.Array:
    """Run ``x`` through S pipelined stages; equals ``sequential_apply``.

    stage_fn: ``(one_stage_params, act) -> act`` — shape- and
    dtype-preserving, per-sample (no cross-batch mixing: microbatching
    changes what a batch is).
    stacked_params: pytree with leading dim S on every leaf (S =
    ``mesh.shape[pp_axis]``), to be sharded ``P("pp")``.
    x: global batch ``[B, ...]``; each rank splits its local batch into
    ``gcd(n_microbatches, local_batch)`` microbatches (the knob is
    perf-only — a non-dividing value degrades the bubble, never errors).
    """
    S = int(mesh.shape[pp_axis]) if pp_axis in mesh.axis_names else 1
    if S == 1:
        return sequential_apply(stage_fn, stacked_params, x)
    _check_stacked(stacked_params, S)
    M = int(n_microbatches)
    batch = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    xspec = P(batch, *([None] * (x.ndim - 1)))
    pspec = jax.tree.map(lambda _: P(pp_axis), stacked_params)

    def ranked(params, xl):
        idx = lax.axis_index(pp_axis)
        p_local = jax.tree.map(lambda a: a[0], params)  # [1,...] -> [...]
        b = xl.shape[0]
        # n_microbatches is a performance knob, never a correctness
        # constraint: when it doesn't divide the per-rank batch (e.g. the
        # Estimator's tiny init batch), fall back to the nearest divisor
        m_eff = math.gcd(M, b)
        mb = xl.reshape((m_eff, b // m_eff) + xl.shape[1:])
        ticks = m_eff + S - 1

        def tick(carry, t):
            state_in, out_buf = carry
            inject = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, m_eff - 1), 0, keepdims=False)
            cur = jnp.where(idx == 0, inject, state_in)
            y = stage_fn(p_local, cur)
            # the last rank finished microbatch t-(S-1) this tick
            w = t - (S - 1)
            valid = (idx == S - 1) & (w >= 0)
            wc = jnp.clip(w, 0, m_eff - 1)
            slot = lax.dynamic_index_in_dim(out_buf, wc, 0, keepdims=False)
            out_buf = lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(valid, y, slot), wc, 0)
            nxt = lax.ppermute(y, pp_axis,
                               [(i, i + 1) for i in range(S - 1)])
            return (nxt, out_buf), None

        # Scan carries must be pp-VARYING from tick 0: the loop writes
        # ppermute/axis_index-derived values into them, and shard_map's
        # vma type system rejects an invariant->varying carry (same
        # constraint ring_attention.py works around).  lax.pvary marks
        # the zeros as device-varying without computing anything.
        def vary(z):
            try:
                return lax.pcast(z, pp_axis, to="varying")
            except (AttributeError, TypeError):
                return z + (idx * 0).astype(z.dtype)
        carry = (vary(jnp.zeros_like(mb[0])), vary(jnp.zeros_like(mb)))
        (_, out_buf), _ = lax.scan(tick, carry, jnp.arange(ticks))
        # outputs live on the last rank only; psum broadcasts them so the
        # result is pp-invariant (loss/metrics compute identically on all
        # ranks — same contract as data parallelism)
        out = lax.psum(jnp.where(idx == S - 1, out_buf, 0.0), pp_axis)
        return out.reshape(xl.shape).astype(xl.dtype)

    return _shard_map(ranked, mesh=mesh, in_specs=(pspec, xspec),
                         out_specs=xspec)(stacked_params, x)


def interleaved_1f1b_stats(n_stages: int, n_microbatches: int,
                           n_chunks: int) -> dict:
    """Static schedule facts for ``pipeline_value_and_grad(...,
    n_chunks=v)`` — the interleaved (virtual-stage) 1F1B schedule.

    Each of the S pp ranks holds ``v`` model chunks placed round-robin
    (logical stage ``j = k*S + r`` is chunk ``k`` of rank ``r``), so a
    microbatch crosses every rank ``v`` times.  One combined tick does
    one forward AND one backward unit per rank, but a unit is now a
    CHUNK — 1/v of a rank's model slice — so a tick costs 1/v of a flat
    tick.  Ramp-up/down shrinks accordingly: measured in flat-tick
    equivalents the schedule spends ``M + S + (S-2)/v`` versus flat
    1F1B's ``M + 2S - 2`` — strictly better for S >= 3, v >= 2, and
    approaching HALF the flat bubble as v grows.  The price is v×: the
    residual ring holds ``2*v*S`` chunk inputs per rank (vs 2S), and
    activations hop ranks v times per microbatch (wrap-around ppermute
    traffic) instead of once — the standard interleaved-schedule trade
    (bubble ↓, memory + ICI traffic ↑).  Residency stays M-independent,
    which is what lets M grow to amortise what bubble remains."""
    S, M, v = int(n_stages), int(n_microbatches), int(n_chunks)
    L = v * S
    g_last, q_last = (M - 1) // S, (M - 1) % S
    ticks = g_last * L + q_last + 2 * L - 1        # chunk-sized ticks
    flat = pipeline_1f1b_stats(S, M)
    return {
        "ticks": ticks,
        "flat_tick_equivalents": ticks / v,
        "flat_1f1b_ticks": flat["ticks"],
        "bubble_fraction": (ticks - v * M) / ticks,
        "flat_bubble_fraction": flat["bubble_fraction"],
        "residual_slots": 2 * L,                   # chunk inputs per rank
        "flat_residual_slots": flat["residual_slots"],
    }


def pipeline_1f1b_stats(n_stages: int, n_microbatches: int) -> dict:
    """Static schedule facts for ``pipeline_value_and_grad`` (asserted by
    tests, cited in docs).  The lockstep combined-tick schedule runs
    ``M + 2S - 2`` ticks (each tick does one forward AND one backward
    unit per rank) and keeps at most ``2S`` microbatch activations
    resident per rank — versus the GPipe-autodiff path, whose transposed
    scan stores all ``M``.  Honest accounting: a rank does useful work in
    M of its M+2S-2 forward slots and M of its backward slots, so the
    idle fraction is ``(2S-2)/(M+2S-2)`` — about TWICE GPipe's
    ``(S-1)/(M+S-1)`` at the same M.  This schedule buys the O(S) memory
    bound by paying bubble, and the memory bound is exactly what lets M
    grow to amortise it (``gpipe_bubble_fraction`` included for the
    comparison)."""
    S, M = int(n_stages), int(n_microbatches)
    return {
        "ticks": M + 2 * S - 2,
        "residual_slots": 2 * S,
        "gpipe_resident_microbatches": M,
        "bubble_fraction": (2 * S - 2) / (M + 2 * S - 2),
        "gpipe_bubble_fraction": (S - 1) / (M + S - 1),
    }


def _make_vary(pp_axis, batch):
    """Device-variance marker shared by the 1F1B paths.  Two reasons to
    mark values varying: (1) scan carries pick up pp-varying (ppermute/
    axis_index) and batch-varying (dp-sharded activations) values, and
    an invariant->varying carry fails shard_map's vma typecheck; (2)
    params must be batch-VARYING before jax.vjp, else autodiff
    auto-psums the param cotangent across dp on EVERY tick (one
    all-reduce per tick, and n_dp-scaled grads after a later mean)."""

    def vary(z):
        for ax in (pp_axis,) + tuple(batch or ()):
            try:
                z = lax.pcast(z, ax, to="varying")
            except (AttributeError, TypeError):
                # no lax.pcast on this JAX: force variance on THIS axis
                # arithmetically and keep looping — falling out early
                # would leave params batch-invariant (see (2) above)
                z = z + (lax.axis_index(ax) * 0).astype(z.dtype)
            except ValueError:
                pass        # already varying on ax
        return z

    return vary


def _f1b_ticks(stage_fn, p_local, mb, aux, S, m_eff, idx, pp_axis, vary,
               head):
    """The shared flat-1F1B tick engine (both ``pipeline_value_and_grad``
    and ``pipeline_apply_1f1b``'s backward run it): rank r forwards
    microbatch m at tick m+r and backwards it at tick m+2S-2-r, with
    the last rank's backward fused into its forward tick; activations
    hop r->r+1 and activation-grads r->r-1 via ppermute; backward units
    recompute their stage forward from the saved stage INPUT
    (stage-level remat, residual ring of 2S slots).

    ``aux``: per-microbatch rows consumed by ``head(y, aux_row) ->
    (loss_scalar, gy_seed)`` — the loss head for value_and_grad, or a
    passthrough of the stored output cotangent for the custom-vjp
    backward.  Evaluated at the last rank's fwd microbatch (where
    m_b == m_f, so the seed aligns with the backward unit).

    Returns ``(gacc, dxbuf, lossbuf)``: raw per-rank sums over this
    rank's microbatches — ALL scaling (1/M, dp mean vs sum) belongs to
    the caller."""
    R = 2 * S
    ticks = m_eff + 2 * S - 2

    def tick(carry, t):
        act_in, gract_in, resbuf, gacc, dxbuf, lossbuf = carry
        m_f = t - idx                       # fwd microbatch index
        m_b = t - (2 * S - 2 - idx)         # bwd microbatch index
        valid_f = (m_f >= 0) & (m_f < m_eff)
        valid_b = (m_b >= 0) & (m_b < m_eff)
        mfc = jnp.clip(m_f, 0, m_eff - 1)
        mbc = jnp.clip(m_b, 0, m_eff - 1)
        # ---- forward unit ----
        inject = lax.dynamic_index_in_dim(mb, mfc, 0, keepdims=False)
        cur = jnp.where(idx == 0, inject, act_in)
        y = stage_fn(p_local, cur)
        # save this stage's INPUT for the recompute-backward
        slot_f = mfc % R
        old = lax.dynamic_index_in_dim(resbuf, slot_f, 0, keepdims=False)
        resbuf = lax.dynamic_update_index_in_dim(
            resbuf, jnp.where(valid_f, cur, old), slot_f, 0)
        arow = lax.dynamic_index_in_dim(aux, mfc, 0, keepdims=False)
        loss_m, gy = head(y, arow)
        # ---- backward unit (stage-level remat) ----
        a_saved = lax.dynamic_index_in_dim(resbuf, mbc % R, 0,
                                           keepdims=False)
        g_use = jnp.where(idx == S - 1, gy.astype(gract_in.dtype),
                          gract_in)
        _, vjp = jax.vjp(stage_fn, p_local, a_saved)
        dp, da = vjp(g_use.astype(y.dtype))
        gacc = jax.tree.map(
            lambda g, d: g + jnp.where(valid_b, d, 0.0).astype(g.dtype),
            gacc, dp)
        # rank 0's da is the input cotangent for microbatch m_b
        dslot = lax.dynamic_index_in_dim(dxbuf, mbc, 0, keepdims=False)
        dxbuf = lax.dynamic_update_index_in_dim(
            dxbuf, jnp.where((idx == 0) & valid_b, da, dslot), mbc, 0)
        lslot = lax.dynamic_index_in_dim(lossbuf, mfc, 0, keepdims=False)
        lossbuf = lax.dynamic_update_index_in_dim(
            lossbuf, jnp.where((idx == S - 1) & valid_f, loss_m, lslot),
            mfc, 0)
        # ---- hops: activations r->r+1, activation-grads r->r-1 ----
        act_out = lax.ppermute(y, pp_axis,
                               [(i, i + 1) for i in range(S - 1)])
        gract_out = lax.ppermute(da, pp_axis,
                                 [(i + 1, i) for i in range(S - 1)])
        return (act_out, gract_out, resbuf, gacc, dxbuf, lossbuf), None

    z_mb = jnp.zeros_like(mb[0])
    carry = (vary(z_mb), vary(z_mb),
             vary(jnp.zeros((R,) + z_mb.shape, z_mb.dtype)),
             jax.tree.map(lambda p: vary(jnp.zeros_like(p)), p_local),
             vary(jnp.zeros_like(mb)),
             vary(jnp.zeros((m_eff,), jnp.float32)))
    (_, _, _, gacc, dxbuf, lossbuf), _ = lax.scan(
        tick, carry, jnp.arange(ticks))
    return gacc, dxbuf, lossbuf


def _f1b_ticks_interleaved(stage_fn, p_chunks, mb, aux, S, v, m_eff, idx,
                           pp_axis, vary, head):
    """The interleaved (virtual-stage) 1F1B tick engine.  Rank ``r``
    holds chunks ``k = 0..v-1`` (stacked leading dim of ``p_chunks``);
    logical stage ``j = k*S + r`` — round-robin placement, so the
    rank→rank hop is always one step and wraps S-1 → 0 between chunks.

    Schedule: microbatch ``m = g*S + q`` forwards through logical stage
    ``j`` at tick ``u_f = g*v*S + k*S + q + r`` and backwards at
    ``u_b = u_f + 2*(L-1-j)`` (``L = v*S``); the last logical stage's
    backward fuses with its forward tick.  Both maps are bijections per
    (rank, tick) — ``u_f - r`` decomposes uniquely base-(S, v, ·) and
    ``u_b + r - 2L + 2 = (g*v - k)*S + q`` uniquely too — so every rank
    runs exactly one fwd and one bwd CHUNK unit per tick.  With v = 1
    this is precisely the flat schedule of ``_f1b_ticks``; kept separate
    because the flat engine's non-wrapping ppermute and 2S ring are the
    proven baseline the tests compare against.

    Backward units recompute their chunk forward from the saved chunk
    INPUT (chunk-level remat) held in a ring of ``2L`` slots — slot
    ``(u_f - r) mod 2L`` is collision-free because a saved input lives
    at most ``2(L-1)`` fwd-issues.  Returns raw per-rank ``(gacc [v,...],
    dxbuf, lossbuf)`` sums; all scaling belongs to the caller."""
    L = v * S
    R = 2 * L
    g_last, q_last = (m_eff - 1) // S, (m_eff - 1) % S
    ticks = g_last * L + q_last + 2 * L - 1

    def tick(carry, t):
        act_in, gract_in, resbuf, gacc, dxbuf, lossbuf = carry
        # ---- forward unit (shared bijection: _fwd_wave) ----
        w_f, k_f, m_fc, valid_f = _fwd_wave(t, idx, S, v, m_eff)
        inject = lax.dynamic_index_in_dim(mb, m_fc, 0, keepdims=False)
        cur = jnp.where((idx == 0) & (k_f == 0), inject, act_in)
        y = stage_fn(_chunk_at(p_chunks, k_f), cur)
        slot_f = jnp.mod(w_f, R)
        old = lax.dynamic_index_in_dim(resbuf, slot_f, 0, keepdims=False)
        resbuf = lax.dynamic_update_index_in_dim(
            resbuf, jnp.where(valid_f, cur, old), slot_f, 0)
        arow = lax.dynamic_index_in_dim(aux, m_fc, 0, keepdims=False)
        loss_m, gy = head(y, arow)
        # ---- backward unit: w = t + r - 2L + 2 = (g*v - k)*S + q ----
        w_b = t + idx - 2 * L + 2
        q_b = jnp.mod(w_b, S)
        h = (w_b - q_b) // S
        k_b = jnp.mod(-h, v)
        m_b = ((h + k_b) // v) * S + q_b
        valid_b = (m_b >= 0) & (m_b < m_eff)
        m_bc = jnp.clip(m_b, 0, m_eff - 1)
        # where this bwd unit's forward saved its input:
        # u_f = t - 2*(L-1-j_b), j_b = k_b*S + idx  =>  w = u_f - idx
        w_fb = t + idx + 2 * k_b * S - 2 * L + 2
        a_saved = lax.dynamic_index_in_dim(
            resbuf, jnp.mod(w_fb, R), 0, keepdims=False)
        is_last_b = (idx == S - 1) & (k_b == v - 1)   # fused with fwd tick
        g_use = jnp.where(is_last_b, gy.astype(gract_in.dtype), gract_in)
        _, vjp = jax.vjp(stage_fn, _chunk_at(p_chunks, k_b), a_saved)
        dp, da = vjp(g_use.astype(y.dtype))
        gacc = jax.tree.map(
            lambda g, d: lax.dynamic_update_index_in_dim(
                g,
                lax.dynamic_index_in_dim(g, k_b, 0, keepdims=False)
                + jnp.where(valid_b, d, 0.0).astype(g.dtype),
                k_b, 0),
            gacc, dp)
        dslot = lax.dynamic_index_in_dim(dxbuf, m_bc, 0, keepdims=False)
        dxbuf = lax.dynamic_update_index_in_dim(
            dxbuf,
            jnp.where((idx == 0) & (k_b == 0) & valid_b, da, dslot),
            m_bc, 0)
        lslot = lax.dynamic_index_in_dim(lossbuf, m_fc, 0, keepdims=False)
        lossbuf = lax.dynamic_update_index_in_dim(
            lossbuf,
            jnp.where((idx == S - 1) & (k_f == v - 1) & valid_f,
                      loss_m, lslot),
            m_fc, 0)
        # ---- hops: WRAP-AROUND — rank S-1's chunk-k output is rank 0's
        # chunk-(k+1) input one tick later (and symmetrically backward)
        act_out = lax.ppermute(y, pp_axis,
                               [(i, (i + 1) % S) for i in range(S)])
        gract_out = lax.ppermute(da, pp_axis,
                                 [((i + 1) % S, i) for i in range(S)])
        return (act_out, gract_out, resbuf, gacc, dxbuf, lossbuf), None

    z_mb = jnp.zeros_like(mb[0])
    carry = (vary(z_mb), vary(z_mb),
             vary(jnp.zeros((R,) + z_mb.shape, z_mb.dtype)),
             jax.tree.map(lambda p: vary(jnp.zeros_like(p)), p_chunks),
             vary(jnp.zeros_like(mb)),
             vary(jnp.zeros((m_eff,), jnp.float32)))
    (_, _, _, gacc, dxbuf, lossbuf), _ = lax.scan(
        tick, carry, jnp.arange(ticks))
    return gacc, dxbuf, lossbuf


def pipeline_value_and_grad(stage_fn: StageFn, loss_fn, stacked_params,
                            x: jax.Array, labels, mesh: Mesh,
                            n_microbatches: int, *,
                            batch_axes: Sequence[str] = ("dp", "fsdp"),
                            pp_axis: str = "pp", n_chunks: int = 1):
    """One interleaved-1F1B training tick-schedule: loss AND gradients of
    ``mean(loss_fn(stage_S(...stage_1(x)), labels))`` in a single
    shard_map scan.

    Why not just ``jax.grad(pipeline_apply)``?  Autodiff transposes the
    forward scan into an all-forward-then-all-backward schedule (GPipe):
    every one of the M microbatches' stage activations stays resident
    until its backward runs, so peak memory grows with M — and M is
    exactly the knob one raises to shrink the bubble.  1F1B starts
    microbatch m's backward as soon as its last-stage forward finishes,
    bounding resident activations at 2S per rank regardless of M
    (``pipeline_1f1b_stats``).  The backward unit recomputes its stage
    forward from the saved stage INPUT (stage-level remat — the
    standard trade), so each (microbatch, stage) costs fwd + fwd + vjp
    instead of fwd + vjp.

    Schedule (flat/non-interleaved 1F1B, combined F+B ticks): rank r
    forwards microbatch ``m`` at tick ``m + r`` and backwards it at tick
    ``m + 2S - 2 - r``; the last rank's backward fuses with its forward
    (same tick), activations hop r->r+1 and activation-grads hop r->r-1
    via ``lax.ppermute`` each tick.

    Args mirror ``pipeline_apply`` plus ``labels`` ([B, ...], same
    leading batch dim as x) and ``loss_fn(y_mb, label_mb) -> scalar``
    (MEAN over the microbatch).  Returns ``(loss, grads, dx)`` where
    ``grads`` matches ``stacked_params`` (sharded P(pp) like the
    params) and ``dx`` is the loss gradient w.r.t. ``x`` (feeds
    embedding/pre-trunk backward when composed manually).

    ``n_chunks=v > 1`` selects the INTERLEAVED schedule: stacked_params
    must carry ``v * S`` stages (logical order on the leading dim);
    stage ``j`` is placed on rank ``j % S`` (round-robin), cutting the
    bubble from ``2S - 2`` to ``S + (S-2)/v`` flat-tick equivalents at
    the cost of a ``2vS``-slot residual ring and v× the ppermute
    traffic (``interleaved_1f1b_stats``).  Math is identical — same
    oracle, same tests.
    """
    S = int(mesh.shape[pp_axis]) if pp_axis in mesh.axis_names else 1
    if S == 1:
        def seq_loss(p, xx):
            return loss_fn(sequential_apply(stage_fn, p, xx), labels)

        loss, (gp, gx) = jax.value_and_grad(seq_loss, argnums=(0, 1))(
            stacked_params, x)
        return loss, gp, gx
    v = int(n_chunks)
    if v < 1:
        raise ValueError(f"n_chunks must be >= 1, got {n_chunks}")
    _check_stacked(stacked_params, v * S)
    M = int(n_microbatches)
    batch = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    xspec = P(batch, *([None] * (x.ndim - 1)))
    lspec = P(batch, *([None] * (jnp.ndim(labels) - 1)))
    if v > 1:
        return _value_and_grad_interleaved(
            stage_fn, loss_fn, stacked_params, x, labels, mesh, M, S, v,
            batch, xspec, lspec, pp_axis)
    pspec = jax.tree.map(lambda _: P(pp_axis), stacked_params)

    def ranked(params, xl, ll):
        idx = lax.axis_index(pp_axis)
        b = xl.shape[0]
        m_eff = math.gcd(M, b)
        mb = xl.reshape((m_eff, b // m_eff) + xl.shape[1:])
        lb = ll.reshape((m_eff, b // m_eff) + ll.shape[1:])
        vary = _make_vary(pp_axis, batch)
        p_local = jax.tree.map(lambda a: vary(a[0]), params)

        def head(y, lbl):
            """Last rank: per-microbatch loss + dL/dy."""
            return jax.value_and_grad(lambda yy: loss_fn(yy, lbl))(y)

        gacc, dxbuf, lossbuf = _f1b_ticks(
            stage_fn, p_local, mb, lb, S, m_eff, idx, pp_axis, vary, head)
        # per-microbatch means -> global mean; grads scale by 1/M
        n_b = 1
        for ax in (batch or ()):
            n_b *= int(mesh.shape[ax])
        loss = lax.psum(jnp.where(idx == S - 1, jnp.sum(lossbuf), 0.0),
                        pp_axis) / m_eff
        # d(global mean)/dx on this rank = (1/n_dp) d(local mean)/dx
        dx = lax.psum(jnp.where(idx == 0, dxbuf, 0.0),
                      pp_axis).reshape(xl.shape) / (m_eff * n_b)
        grads = jax.tree.map(lambda g: g / m_eff, gacc)
        if batch:
            # each data-parallel rank saw its own local batch: the global
            # mean loss/grad is the mean across them (dx stays sharded —
            # it IS per-rank)
            loss = lax.pmean(loss, batch)
            grads = jax.tree.map(lambda g: lax.pmean(g, batch), grads)
        grads = jax.tree.map(lambda g: g[None], grads)
        return loss, grads, dx.astype(xl.dtype)

    loss, grads, dx = _shard_map(
        ranked, mesh=mesh, in_specs=(pspec, xspec, lspec),
        out_specs=(P(), pspec, xspec))(stacked_params, x, labels)
    return loss, grads, dx


def _chunk_params(stacked_params, v: int, S: int):
    """[L, ...] logical-order stack -> [v, S, ...] so ``P(None, pp)``
    realises round-robin placement (leaf[k, r] = logical stage k*S+r —
    C-order reshape is exactly that map)."""
    return jax.tree.map(
        lambda a: a.reshape((v, S) + a.shape[1:]), stacked_params)


def _chunk_at(p, k):
    """Select chunk ``k`` from a [v, ...]-stacked local param tree."""
    return jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, k, 0, keepdims=False), p)


def _fwd_wave(t, idx, S, v, m_eff):
    """The interleaved (rank, tick) -> forward-unit bijection, shared by
    the combined and forward-only engines: ``w = t - r`` decomposes
    base-(S, v, ·) into (q, k, g); microbatch m = g*S + q.  Returns
    ``(w_f, k_f, m_fc, valid_f)`` with m clipped for safe indexing."""
    L = v * S
    w_f = t - idx
    q_f = jnp.mod(w_f, S)
    k_f = jnp.mod((w_f - q_f) // S, v)
    m_f = (w_f // L) * S + q_f
    valid_f = (w_f >= 0) & (m_f < m_eff)
    return w_f, k_f, jnp.clip(m_f, 0, m_eff - 1), valid_f


def _fwd_ticks_interleaved(stage_fn, p_chunks, mb, S, v, m_eff, idx,
                           pp_axis, vary):
    """Forward-only interleaved schedule: ``(v*M + S - 1)/v`` flat-tick
    equivalents versus GPipe's ``M + S - 1`` — the ramp shrinks v× for
    inference too.  Same (rank, tick) -> (chunk, microbatch) bijection
    as the combined engine."""
    L = v * S
    g_last, q_last = (m_eff - 1) // S, (m_eff - 1) % S
    ticks = g_last * L + (v - 1) * S + q_last + S

    def tick(carry, t):
        act_in, out_buf = carry
        w_f, k_f, m_fc, valid_f = _fwd_wave(t, idx, S, v, m_eff)
        inject = lax.dynamic_index_in_dim(mb, m_fc, 0, keepdims=False)
        cur = jnp.where((idx == 0) & (k_f == 0), inject, act_in)
        y = stage_fn(_chunk_at(p_chunks, k_f), cur)
        write = (idx == S - 1) & (k_f == v - 1) & valid_f
        slot = lax.dynamic_index_in_dim(out_buf, m_fc, 0, keepdims=False)
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(write, y, slot), m_fc, 0)
        act_out = lax.ppermute(y, pp_axis,
                               [(i, (i + 1) % S) for i in range(S)])
        return (act_out, out_buf), None

    carry = (vary(jnp.zeros_like(mb[0])), vary(jnp.zeros_like(mb)))
    (_, out_buf), _ = lax.scan(tick, carry, jnp.arange(ticks))
    return out_buf


def pipeline_apply_interleaved(stage_fn: StageFn, stacked_params,
                               x: jax.Array, mesh: Mesh,
                               n_microbatches: int, n_chunks: int, *,
                               batch_axes: Sequence[str] = ("dp", "fsdp"),
                               pp_axis: str = "pp",
                               chunked: bool = False) -> jax.Array:
    """Interleaved-schedule forward with an O(S)-residency interleaved
    BACKWARD, composable with ordinary autodiff (the ``GPipe`` module's
    ``schedule="interleaved"`` path — same contract as
    ``pipeline_apply_1f1b``, smaller bubble on both passes).

    ``stacked_params``: [L, ...] logical-order stages (L = n_chunks *
    pp size), or already [v, S, ...]-chunked when ``chunked=True`` (the
    module stores them chunked so the round-robin placement is the
    at-rest sharding — no per-step reshard)."""
    S = int(mesh.shape[pp_axis]) if pp_axis in mesh.axis_names else 1
    v = int(n_chunks)
    if S == 1:
        if chunked:
            stacked_params = jax.tree.map(
                lambda a: a.reshape((-1,) + a.shape[2:]), stacked_params)
        return sequential_apply(stage_fn, stacked_params, x)
    M = int(n_microbatches)
    batch = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    if chunked:
        bad = {jnp.shape(leaf)[:2] for leaf in
               jax.tree.leaves(stacked_params)} - {(v, S)}
        if bad:
            raise ValueError(
                f"chunked=True expects [n_chunks={v}, pp={S}, ...] "
                f"leading dims on every leaf, got {sorted(bad)}; pass "
                f"the flat [L, ...] logical-order stack with "
                f"chunked=False to have it chunked here")
        p_chunked = stacked_params
    else:
        _check_stacked(stacked_params, v * S)
        p_chunked = _chunk_params(stacked_params, v, S)
    pspec = jax.tree.map(lambda _: P(None, pp_axis), p_chunked)

    @jax.custom_vjp
    def apply(params, xx):
        xspec = P(batch, *([None] * (xx.ndim - 1)))

        def ranked(p, xl):
            idx = lax.axis_index(pp_axis)
            b = xl.shape[0]
            m_eff = math.gcd(M, b)
            mb = xl.reshape((m_eff, b // m_eff) + xl.shape[1:])
            vary = _make_vary(pp_axis, batch)
            p_chunks = jax.tree.map(lambda a: vary(a[:, 0]), p)
            out_buf = _fwd_ticks_interleaved(
                stage_fn, p_chunks, mb, S, v, m_eff, idx, pp_axis, vary)
            out = lax.psum(jnp.where(idx == S - 1, out_buf, 0.0), pp_axis)
            return out.reshape(xl.shape).astype(xl.dtype)

        return _shard_map(ranked, mesh=mesh, in_specs=(pspec, xspec),
                             out_specs=xspec)(params, xx)

    def fwd(params, xx):
        return apply(params, xx), (params, xx)

    def bwd(res, gy):
        params, xx = res
        xspec = P(batch, *([None] * (xx.ndim - 1)))

        def ranked(p, xl, gl):
            idx = lax.axis_index(pp_axis)
            b = xl.shape[0]
            m_eff = math.gcd(M, b)
            mb = xl.reshape((m_eff, b // m_eff) + xl.shape[1:])
            gb = gl.reshape((m_eff, b // m_eff) + gl.shape[1:])
            vary = _make_vary(pp_axis, batch)
            p_chunks = jax.tree.map(lambda a: vary(a[:, 0]), p)

            def head(y, g_seed):
                # bwd seeds from the STORED output cotangent (no loss)
                return jnp.float32(0.0), g_seed

            gacc, dxbuf, _ = _f1b_ticks_interleaved(
                stage_fn, p_chunks, mb, gb, S, v, m_eff, idx, pp_axis,
                vary, head)
            # gy carries the outer scaling; dparams is the raw SUM over
            # microbatches and dp ranks (params are dp-replicated)
            if batch:
                gacc = jax.tree.map(lambda g: lax.psum(g, batch), gacc)
            grads = jax.tree.map(lambda g: g[:, None], gacc)
            dx = lax.psum(jnp.where(idx == 0, dxbuf, 0.0),
                          pp_axis).reshape(xl.shape)
            return grads, dx.astype(xl.dtype)

        # cotangents match apply's inputs: the CHUNKED tree (autodiff of
        # the outer _chunk_params reshape maps them back to [L, ...])
        return _shard_map(
            ranked, mesh=mesh, in_specs=(pspec, xspec, xspec),
            out_specs=(pspec, xspec))(params, xx, gy)

    apply.defvjp(fwd, bwd)
    return apply(p_chunked, x)


def _value_and_grad_interleaved(stage_fn, loss_fn, stacked_params, x,
                                labels, mesh, M, S, v, batch, xspec,
                                lspec, pp_axis):
    """Interleaved-schedule body of ``pipeline_value_and_grad``: params
    [L, ...] reshape to [v, S, ...] so ``P(None, pp)`` realises the
    round-robin placement (leaf[k, r] = logical stage k*S + r); each
    rank sees its own [v, ...] chunk stack inside shard_map.  Scaling
    contract is identical to the flat path."""
    p_resh = _chunk_params(stacked_params, v, S)
    pspec = jax.tree.map(lambda _: P(None, pp_axis), p_resh)

    def ranked(params, xl, ll):
        idx = lax.axis_index(pp_axis)
        b = xl.shape[0]
        m_eff = math.gcd(M, b)
        mb = xl.reshape((m_eff, b // m_eff) + xl.shape[1:])
        lb = ll.reshape((m_eff, b // m_eff) + ll.shape[1:])
        vary = _make_vary(pp_axis, batch)
        p_chunks = jax.tree.map(lambda a: vary(a[:, 0]), params)

        def head(y, lbl):
            return jax.value_and_grad(lambda yy: loss_fn(yy, lbl))(y)

        gacc, dxbuf, lossbuf = _f1b_ticks_interleaved(
            stage_fn, p_chunks, mb, lb, S, v, m_eff, idx, pp_axis, vary,
            head)
        n_b = 1
        for ax in (batch or ()):
            n_b *= int(mesh.shape[ax])
        loss = lax.psum(jnp.where(idx == S - 1, jnp.sum(lossbuf), 0.0),
                        pp_axis) / m_eff
        dx = lax.psum(jnp.where(idx == 0, dxbuf, 0.0),
                      pp_axis).reshape(xl.shape) / (m_eff * n_b)
        grads = jax.tree.map(lambda g: g / m_eff, gacc)
        if batch:
            loss = lax.pmean(loss, batch)
            grads = jax.tree.map(lambda g: lax.pmean(g, batch), grads)
        grads = jax.tree.map(lambda g: g[:, None], grads)
        return loss, grads, dx.astype(xl.dtype)

    loss, grads, dx = _shard_map(
        ranked, mesh=mesh, in_specs=(pspec, xspec, lspec),
        out_specs=(P(), pspec, xspec))(p_resh, x, labels)
    grads = jax.tree.map(lambda g, a: g.reshape(a.shape), grads,
                         stacked_params)
    return loss, grads, dx


def pipeline_apply_1f1b(stage_fn: StageFn, stacked_params, x: jax.Array,
                        mesh: Mesh, n_microbatches: int, *,
                        batch_axes: Sequence[str] = ("dp", "fsdp"),
                        pp_axis: str = "pp") -> jax.Array:
    """``pipeline_apply`` with an O(S)-residency BACKWARD, composable
    with ordinary autodiff (``jax.grad`` through models that embed the
    pipelined trunk, e.g. the Estimator's train step).

    custom_vjp shape: the forward is the plain forward pipeline and
    saves ONLY ``(stacked_params, x)`` across the autodiff boundary —
    no per-microbatch activations.  The backward replays the forward
    interleaved with backward units (the ``pipeline_value_and_grad``
    tick schedule, seeded by the incoming output cotangent instead of a
    loss head), so resident activations stay bounded at 2S microbatches
    per rank while autodiff through ``pipeline_apply`` would hold all
    M.  Compute cost: one extra forward per (microbatch, stage) versus
    the stored-activation path — the remat trade, paid where M is large
    precisely because memory no longer scales with it."""
    S = int(mesh.shape[pp_axis]) if pp_axis in mesh.axis_names else 1
    if S == 1:
        return sequential_apply(stage_fn, stacked_params, x)
    M = int(n_microbatches)
    batch = tuple(a for a in batch_axes if a in mesh.axis_names) or None

    @jax.custom_vjp
    def apply(params, xx):
        return pipeline_apply(stage_fn, params, xx, mesh, M,
                              batch_axes=batch_axes, pp_axis=pp_axis)

    def fwd(params, xx):
        return apply(params, xx), (params, xx)

    def bwd(res, gy):
        params, xx = res
        xspec = P(batch, *([None] * (xx.ndim - 1)))
        pspec = jax.tree.map(lambda _: P(pp_axis), params)

        def ranked(p_stk, xl, gl):
            idx = lax.axis_index(pp_axis)
            b = xl.shape[0]
            m_eff = math.gcd(M, b)
            mb = xl.reshape((m_eff, b // m_eff) + xl.shape[1:])
            gb = gl.reshape((m_eff, b // m_eff) + gl.shape[1:])
            vary = _make_vary(pp_axis, batch)
            p_local = jax.tree.map(lambda a: vary(a[0]), p_stk)

            def head(y, g_seed):
                # the last rank seeds its backward from the STORED output
                # cotangent of the microbatch it just forwarded (m_b ==
                # m_f there); no loss is computed in the bwd pass
                return jnp.float32(0.0), g_seed

            gacc, dxbuf, _ = _f1b_ticks(
                stage_fn, p_local, mb, gb, S, m_eff, idx, pp_axis, vary,
                head)
            # gy already carries the outer scaling (e.g. the loss mean):
            # dparams is the raw SUM of contributions — across this
            # rank's microbatches, and across dp ranks for the
            # dp-replicated params
            if batch:
                gacc = jax.tree.map(lambda g: lax.psum(g, batch), gacc)
            grads = jax.tree.map(lambda g: g[None], gacc)
            dx = lax.psum(jnp.where(idx == 0, dxbuf, 0.0),
                          pp_axis).reshape(xl.shape)
            return grads, dx.astype(xl.dtype)

        return _shard_map(
            ranked, mesh=mesh, in_specs=(pspec, xspec, xspec),
            out_specs=(pspec, xspec))(params, xx, gy)

    apply.defvjp(fwd, bwd)
    return apply(stacked_params, x)


def pp_stage_rules(inner: PartitionRules = (), *,
                   n_chunks: int = 1) -> PartitionRules:
    """Partition rules for GPipe's stacked stage params: prepend the stage
    dim ``"pp"`` to each stage-internal rule, then shard everything else's
    stage dim.  ``inner`` patterns should be stage-scoped (they are matched
    against paths under ``stages/``).  ``n_chunks > 1`` matches the
    interleaved layout ([v, S, ...]-chunked leaves): the pp shard moves to
    dim 1 so each rank holds its round-robin chunks at rest."""
    if n_chunks > 1:
        out = [(pat, P(None, "pp", *tuple(spec))) for (pat, spec) in inner]
        out.append((r"stages/", P(None, "pp")))
        return tuple(out)
    out = [(pat, P("pp", *tuple(spec))) for (pat, spec) in inner]
    out.append((r"stages/", P("pp")))
    return tuple(out)


class GPipe(nn.Module):
    """Flax wrapper: S copies of a stage module run as a pipeline.

    ``stage`` is a template module whose ``__call__(x)`` is shape- and
    dtype-preserving and per-sample (Dense/LayerNorm/attention fine;
    BatchNorm or dropout belong outside the pipelined trunk — stages run
    without rng/mutable plumbing).  Params are created stacked ``[S, ...]``
    (path prefix ``stages/``) so ``pp_stage_rules`` shards them; on meshes
    without pp > 1 the stages run sequentially — same math, one device.
    """

    stage: nn.Module
    n_stages: int
    n_microbatches: int = 4
    mesh: Optional[Mesh] = None
    # "gpipe": autodiff through the forward scan (activation residency
    # grows with n_microbatches); "1f1b": custom-vjp interleaved
    # backward, residency bounded at 2S microbatches per rank at one
    # extra recompute-forward per (microbatch, stage); "interleaved":
    # 1f1b with n_stages/pp virtual chunks per rank (round-robin
    # placement, bubble S+(S-2)/v vs 2S-2 — interleaved_1f1b_stats)
    schedule: str = "gpipe"

    def _n_chunks(self) -> int:
        """Chunks per rank for the interleaved schedule: pipelined when
        the pp axis divides n_stages (v = n_stages / S), sequential
        otherwise (same fallback contract as the other schedules)."""
        S = self.mesh.shape.get("pp", 1) if self.mesh is not None else 1
        if self.schedule == "interleaved" and S > 1 \
                and self.n_stages % S == 0 and self.n_stages > S:
            return self.n_stages // S
        return 1

    @nn.compact
    def __call__(self, x):
        if self.schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"schedule must be 'gpipe', '1f1b' or 'interleaved', "
                f"got {self.schedule!r}")
        template = self.stage.clone(parent=None)
        v = self._n_chunks()

        def init_stacked(rng) -> Any:
            keys = jax.random.split(rng, self.n_stages)
            probe = x[:1]
            st = jax.vmap(
                lambda k: template.init(k, probe)["params"])(keys)
            if v > 1:       # chunked-at-rest: round-robin placement IS
                #             the sharding (pp_stage_rules(n_chunks=v))
                st = jax.tree.map(
                    lambda a: a.reshape(
                        (v, self.n_stages // v) + a.shape[1:]), st)
            return st

        params = self.param("stages", init_stacked)

        def fn(p, a):
            return template.apply({"params": p}, a)

        if v > 1:
            return pipeline_apply_interleaved(
                fn, params, x, self.mesh, self.n_microbatches, v,
                chunked=True)
        if self.mesh is not None and \
                self.mesh.shape.get("pp", 1) == self.n_stages and \
                self.n_stages > 1:
            # interleaved with v == 1 chunk per rank IS flat 1f1b
            run = (pipeline_apply_1f1b
                   if self.schedule in ("1f1b", "interleaved")
                   else pipeline_apply)
            return run(fn, params, x, self.mesh, self.n_microbatches)
        return sequential_apply(fn, params, x)

from analytics_zoo_tpu.parallel.mesh import (
    make_mesh,
    single_device_mesh,
    resolve_axis_sizes,
    batch_axes,
    mesh_batch_size,
    CANONICAL_AXES,
)
from analytics_zoo_tpu.parallel.partition import (
    match_partition_rules,
    data_sharding,
    state_sharding,
    with_sharding_constraint,
)
from analytics_zoo_tpu.parallel.pipeline import (
    GPipe,
    pipeline_apply,
    pipeline_apply_1f1b,
    pipeline_apply_interleaved,
    pipeline_value_and_grad,
    pipeline_1f1b_stats,
    interleaved_1f1b_stats,
    sequential_apply,
    pp_stage_rules,
)

__all__ = [
    "make_mesh",
    "single_device_mesh",
    "resolve_axis_sizes",
    "batch_axes",
    "mesh_batch_size",
    "CANONICAL_AXES",
    "match_partition_rules",
    "data_sharding",
    "state_sharding",
    "with_sharding_constraint",
    "GPipe",
    "pipeline_apply",
    "pipeline_apply_1f1b",
    "pipeline_apply_interleaved",
    "pipeline_value_and_grad",
    "pipeline_1f1b_stats",
    "interleaved_1f1b_stats",
    "sequential_apply",
    "pp_stage_rules",
]

"""Config tree for analytics_zoo_tpu.

The reference has no central flag library — configuration is layered across
SparkConf keys, the ``OrcaContext`` python singleton, per-Estimator ``config``
dicts, and Cluster Serving's ``config.yaml`` (SURVEY.md §5 "Config/flag
system", ref: pyzoo/zoo/orca/common.py, serving ClusterServingHelper).

Here that collapses into one dataclass tree, YAML-loadable for serving
parity.  Everything is plain-python (no jax imports) so configs can be built
before device initialisation.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple


@dataclass
class MeshConfig:
    """Device-mesh layout.

    ``axes`` maps axis name -> size; -1 means "fill with remaining devices".
    Axis-name conventions (used by partition rules across the codebase):

    - ``dp``: data parallel (batch dim)
    - ``fsdp``: fully-sharded data parallel (params sharded over this too)
    - ``tp``: tensor/model parallel
    - ``sp``: sequence/context parallel (ring attention)
    - ``ep``: expert parallel
    - ``pp``: pipeline parallel
    """

    axes: Dict[str, int] = field(default_factory=lambda: {"dp": -1})
    allow_split_physical_axes: bool = False

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.axes.keys())


@dataclass
class DataConfig:
    """Input-pipeline knobs (the FeatureSet/DRAM-vs-PMEM tier analog)."""

    batch_size: int = 32  # global batch size
    shuffle_buffer: int = 10_000
    prefetch_depth: int = 2  # double-buffered HBM staging by default
    drop_remainder: bool = True
    num_host_threads: int = 4
    use_native_reader: bool = False  # C++ data plane (native/)


@dataclass
class TrainConfig:
    """Estimator training knobs."""

    epochs: int = 1
    log_every_steps: int = 50
    checkpoint_dir: Optional[str] = None
    checkpoint_every_steps: int = 0  # 0 = only at epoch end when dir set
    keep_checkpoints: int = 3
    seed: int = 0
    dtype: str = "bfloat16"  # compute dtype on the MXU
    param_dtype: str = "float32"
    remat: bool = False  # jax.checkpoint the model apply
    # split each global batch into N sequentially-scanned microbatches and
    # apply ONE averaged-gradient update — same math as the full batch (for
    # mean losses) at 1/N the activation memory
    accum_steps: int = 1
    # ship each training batch as ONE packed uint8 buffer (one device_put
    # per step instead of one per column) with on-device bitcast unpack;
    # bitwise-identical data, k fixed transfer costs collapsed into one
    pack_transfer: bool = True
    donate_state: bool = True
    # observability (SURVEY §5: TrainSummary/TensorBoard + jsonl analogs)
    tensorboard_dir: Optional[str] = None
    metrics_jsonl: Optional[str] = None
    # jax.profiler trace: (logdir, start_global_step, n_steps)
    profile: Optional[tuple] = None
    # fault-injection hook (SURVEY §5 failure-recovery testing): raise at
    # this global step to exercise checkpoint-resume paths
    fault_inject_step: int = 0
    # debug mode (SURVEY §5 sanitizer analog: jax_debug_nans + deterministic
    # data order).  debug_nans re-runs the faulting jitted step op-by-op and
    # raises at the op that produced the NaN; implies donate_state=False so
    # the re-run still owns its input buffers.  deterministic fixes the data
    # order (no shuffling) so a faulting step is reproducible.
    debug_nans: bool = False
    deterministic: bool = False


@dataclass
class ServingConfig:
    """Cluster-Serving-parity config (config.yaml analog)."""

    model_path: str = ""
    queue_host: str = "localhost"
    queue_port: int = 6379
    batch_size: int = 32  # max micro-batch
    batch_timeout_ms: float = 5.0
    bucket_sizes: Sequence[int] = (1, 2, 4, 8, 16, 32)  # padded-shape buckets
    num_threads: int = 4


@dataclass
class ZooConfig:
    mesh: MeshConfig = field(default_factory=MeshConfig)
    data: DataConfig = field(default_factory=DataConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ZooConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        sub = {"mesh": MeshConfig, "data": DataConfig, "train": TrainConfig,
               "serving": ServingConfig}
        kwargs: Dict[str, Any] = {}
        for k, v in d.items():
            if k in sub and isinstance(v, dict):
                kwargs[k] = sub[k](**v)
            elif k == "extra" and isinstance(v, dict):
                kwargs.setdefault("extra", {})
                kwargs["extra"].update(v)
            elif k in known:
                kwargs[k] = v
            else:
                kwargs.setdefault("extra", {})
                kwargs["extra"][k] = v
        return cls(**kwargs)

    @classmethod
    def from_yaml(cls, path: str) -> "ZooConfig":
        import yaml  # pyyaml is in the base image

        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f) or {})

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

"""Context bootstrap — the ``init_orca_context`` analog.

Reference behavior (SURVEY.md §3.1, ref: pyzoo/zoo/orca/common.py,
pyzoo/zoo/common/nncontext.py, pyzoo/zoo/ray/raycontext.py): one call builds
the whole cluster substrate — SparkContext with BigDL engine config, plus
optionally a Ray cluster bootstrapped inside the Spark executors.

TPU-native inversion: there is no JVM and no subprocess zoo.  One call

- (multihost) runs ``jax.distributed.initialize`` so all TPU-VM hosts join a
  coordinator (this replaces spark-submit + RayOnSpark barrier launch), and
- builds the global device `Mesh` (this replaces executor allocation),
- installs a process-wide ``OrcaContext`` singleton carrying config, mesh and
  RNG seed (this replaces the ZooContext/OrcaContext config singletons).

`cluster_mode` parity:
  reference: local | yarn-client | yarn-cluster | k8s | standalone | spark-submit
  here:      local (this process's devices) | multihost (TPU pod slice)
Other reference modes are provisioning concerns that do not exist on TPU VMs;
they raise with a pointer to `multihost`.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh

from analytics_zoo_tpu.common.config import MeshConfig, ZooConfig
from analytics_zoo_tpu.parallel import mesh as mesh_lib

logger = logging.getLogger("analytics_zoo_tpu")


class ZooContext:
    """Process-wide state: config, mesh, seed.  Created by `init_context`."""

    def __init__(self, config: ZooConfig, mesh: Mesh):
        self.config = config
        self.mesh = mesh
        self.seed = config.train.seed

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def num_processes(self) -> int:
        return jax.process_count()

    def __repr__(self):
        return (f"ZooContext(mesh={dict(self.mesh.shape)}, "
                f"devices={self.num_devices}, "
                f"process={self.process_index}/{self.num_processes})")


class _OrcaContextMeta(type):
    """Config singleton with attribute-style access, matching the reference's
    ``OrcaContext`` (ref: pyzoo/zoo/orca/common.py OrcaContextMeta):
    ``OrcaContext.pandas_read_backend``-style global knobs."""

    _ctx: Optional[ZooContext] = None
    _lock = threading.Lock()
    # reference-parity global knobs
    pandas_read_backend: str = "pandas"
    serialize_data_creator: bool = False
    log_output: bool = True

    def get_context(cls) -> ZooContext:
        if cls._ctx is None:
            raise RuntimeError(
                "No context initialised — call init_orca_context() first")
        return cls._ctx


class OrcaContext(metaclass=_OrcaContextMeta):
    pass


def init_context(
    cluster_mode: str = "local",
    *,
    config: Optional[ZooConfig] = None,
    mesh_axes: Optional[Dict[str, int]] = None,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    num_devices: Optional[int] = None,
    seed: Optional[int] = None,
    **extra: Any,
) -> ZooContext:
    """Initialise the framework context. Returns a :class:`ZooContext`.

    Args:
      cluster_mode: "local" (devices visible to this process) or "multihost"
        (join/initialise a jax.distributed coordinator across TPU-VM hosts
        first — the RayOnSpark-launch analog).
      mesh_axes: e.g. ``{"dp": -1}`` (default), ``{"dp": -1, "tp": 4}``.
      coordinator_address/num_processes/process_id: multihost bootstrap; when
        omitted, jax auto-detects from the TPU metadata server.
    """
    import copy
    import os as _os_cache

    # Persistent XLA compilation cache: every entry-point process (bench
    # subprocesses, serving workers, elastic restarts) re-lowers the same
    # programs; caching compiled executables on disk turns the 20-40s
    # first-compile into a file read on every process after the first.
    # Opt out with ZOO_COMPILATION_CACHE=0 / point elsewhere with a path.
    # CPU is excluded: XLA:CPU AOT reuse is machine-feature-pinned (the
    # loader warns of SIGILL on feature drift) and CPU compiles are fast
    # enough not to need it.  jax.config.jax_platforms is readable
    # without initialising a backend — critical when the TPU tunnel is
    # unreachable and backend init would block.
    cache_dir = _os_cache.environ.get("ZOO_COMPILATION_CACHE", "")
    platforms = str(jax.config.jax_platforms
                    or _os_cache.environ.get("JAX_PLATFORMS", "")).lower()
    # enable only for an EXPLICIT accelerator platform: when unset, jax
    # auto-detects — which on an accelerator-less host means XLA:CPU,
    # and probing the backend here could block on an unreachable tunnel
    accel = any(p and p != "cpu" for p in platforms.split(","))
    if (cache_dir != "0" and accel
            and jax.config.jax_compilation_cache_dir is None):
        if not cache_dir:
            cache_dir = _os_cache.path.join(
                _os_cache.path.expanduser("~"), ".cache",
                "analytics_zoo_tpu_xla")
        try:
            _os_cache.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        except OSError:
            pass                    # read-only home: cache stays off

    cfg = copy.deepcopy(config) if config is not None else ZooConfig()
    if mesh_axes is not None:
        cfg.mesh = MeshConfig(axes=dict(mesh_axes))
    if seed is not None:
        cfg.train.seed = seed
    cfg.extra.update(extra)

    if cluster_mode in ("multihost", "tpu-pod", "distributed"):
        # Replaces: conda-pack + spark-submit + barrier-mode `ray start`
        # (SURVEY.md §3.1). One collective handshake, no subprocesses.
        # Explicit args > ZOO_* env (set by scripts/run_elastic.py so
        # training scripts stay supervisor-agnostic) > jax autodetect
        # from the TPU metadata server.
        import os as _os

        if coordinator_address is None:
            coordinator_address = _os.environ.get("ZOO_COORDINATOR")
        if num_processes is None and "ZOO_NUM_PROCESSES" in _os.environ:
            num_processes = int(_os.environ["ZOO_NUM_PROCESSES"])
        if process_id is None and "ZOO_PROCESS_ID" in _os.environ:
            process_id = int(_os.environ["ZOO_PROCESS_ID"])
        kwargs: Dict[str, Any] = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        try:
            jax.distributed.initialize(**kwargs)
        except RuntimeError as e:  # already initialised is fine
            if "already" not in str(e).lower():
                raise
    elif cluster_mode != "local":
        raise ValueError(
            f"cluster_mode={cluster_mode!r}: Spark-era modes (yarn/k8s/"
            f"standalone) have no TPU equivalent; use 'local' or 'multihost'")

    devices = None
    if num_devices is not None:
        avail = jax.devices()
        if num_devices > len(avail):
            raise ValueError(
                f"num_devices={num_devices} but only {len(avail)} devices "
                f"are available")
        devices = avail[:num_devices]
    m = mesh_lib.make_mesh(cfg.mesh, devices=devices)
    ctx = ZooContext(cfg, m)
    with _OrcaContextMeta._lock:
        _OrcaContextMeta._ctx = ctx
    logger.info("initialised %r", ctx)
    return ctx


def init_orca_context(cluster_mode: str = "local", **kwargs) -> ZooContext:
    """Reference-parity alias (ref: zoo.orca.init_orca_context)."""
    return init_context(cluster_mode, **kwargs)


def stop_orca_context() -> None:
    """Tear down the context (ref: zoo.orca.stop_orca_context).

    On TPU there are no executor processes to kill; we just drop the
    singleton and (if we initialised it) leave jax.distributed running —
    shutting it down mid-process is unsafe for later re-init.
    """
    with _OrcaContextMeta._lock:
        _OrcaContextMeta._ctx = None


# ---------------------------------------------------------------------------
# process-local execution scope (distributed HPO trial isolation)
# ---------------------------------------------------------------------------

# Deliberately PROCESS-wide, not thread-local: the scope must be visible
# to worker threads the scoped code spawns (device_prefetch's H2D thread
# calls make_global_batch, whose multihost branch keys on
# effective_process_count()).  Distributed HPO runs one scoped trial at a
# time per process, so a process-wide flag cannot leak across trials.
_LOCAL_SCOPE = {"on": False}


def in_local_process_scope() -> bool:
    return _LOCAL_SCOPE["on"]


def effective_process_count() -> int:
    """``jax.process_count()``, except inside :func:`local_process_scope`
    where it is 1 — multihost code paths (data splitting, row-count
    allgathers, early-stop agreement) must treat a scoped trial as a
    single-host program or concurrent per-process trials would issue
    mismatched cross-process collectives and deadlock."""
    return 1 if in_local_process_scope() else jax.process_count()


def effective_process_index() -> int:
    return 0 if in_local_process_scope() else jax.process_index()


@contextlib.contextmanager
def local_process_scope(mesh_axes: Optional[Dict[str, int]] = None):
    """Re-scope the framework to THIS process for the duration: the
    context mesh covers only ``jax.local_devices()`` and every
    process-count-dependent branch acts single-host.

    This is the trial-isolation analog of the reference giving each Ray
    Tune trial its own actor + resources (ref: SURVEY §3.6
    RayTuneSearchEngine): during distributed HPO each process trains a
    DIFFERENT config concurrently, so nothing inside a trial may
    synchronise with peers.  File-path conventions (``{host}`` shard
    naming) intentionally keep the REAL process index."""
    ctx = OrcaContext.get_context()
    old_mesh = ctx.mesh
    from analytics_zoo_tpu.common.config import MeshConfig as _MC

    local = mesh_lib.make_mesh(_MC(axes=dict(mesh_axes or {"dp": -1})),
                               devices=jax.local_devices())
    _LOCAL_SCOPE["on"] = True
    ctx.mesh = local
    try:
        yield ctx
    finally:
        ctx.mesh = old_mesh
        _LOCAL_SCOPE["on"] = False

"""Scheme-dispatch filesystem layer: remote URIs for the data plane.

Reference parity (SURVEY.md §2.2): the reference's data layer read
HDFS/S3 natively through Spark — ``read_csv("hdfs://...")`` just worked
on a cluster (ref: pyzoo/zoo/orca/data/pandas/preprocessing.py).  The
TPU rebuild's hosts feed from cloud object stores instead (TPU-VM
training reads GCS), so every ingestion surface (readers, DiskFeatureSet
shards, ImageSet folders) accepts ``gs://``, ``s3://``, ``hdfs://``,
``file://`` and ``memory://`` URIs through fsspec, while PLAIN local
paths keep the native fast paths (C++ CSV parser, mmap ZREC reader)
untouched.

Design rules:
  * scheme detection is syntactic (``scheme://``) — no fsspec import,
    no network touch, for local paths;
  * a missing cloud driver (gcsfs / s3fs / pyarrow-hdfs) fails LOUDLY at
    first use with fsspec's own install guidance — never a silent local
    fallback that would read an empty dir as "no files";
  * native code needs real local files (mmap, C stdio) — ``local_copy``
    materialises a remote file into a per-process cache dir, and
    ``upload`` pushes a locally-written artifact out.  Streaming IO uses
    ``open`` directly.
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile
from typing import List, Optional, Tuple

_SCHEME = re.compile(r"^[A-Za-z][A-Za-z0-9+.-]*://")


def is_remote(path) -> bool:
    """True for scheme:// URIs (gs://, s3://, hdfs://, memory://, ...).

    ``file://`` counts as remote-syntax (routed through fsspec, which
    resolves it locally) so that URI-shaped config values behave
    uniformly. Windows drive letters can't false-positive: ``C:/`` has
    no ``//``."""
    return isinstance(path, str) and _SCHEME.match(path) is not None


def _fs_for(path: str):
    """fsspec filesystem for a URI. Loud ImportError (with fsspec's
    install hint) when the scheme's driver isn't in the image."""
    import fsspec

    scheme = path.split("://", 1)[0]
    try:
        return fsspec.filesystem(scheme)
    except (ImportError, OSError) as e:
        # ImportError: driver package absent (s3fs, adlfs, ...);
        # OSError: driver present but its native dep is (hdfs→libjvm).
        # Either way: loud, with the fix named — never a local fallback.
        raise ImportError(
            f"accessing {path!r} needs a working fsspec driver for "
            f"{scheme!r}: {e}") from e


def open(path: str, mode: str = "rb"):  # noqa: A001 - deliberate shadow
    """Open local path or remote URI for streaming IO."""
    if not is_remote(path):
        import builtins

        return builtins.open(path, mode)
    import fsspec

    return fsspec.open(path, mode).open()


def exists(path: str) -> bool:
    if not is_remote(path):
        return os.path.exists(path)
    return _fs_for(path).exists(path)


def isdir(path: str) -> bool:
    if not is_remote(path):
        return os.path.isdir(path)
    return _fs_for(path).isdir(path)


def makedirs(path: str, exist_ok: bool = True) -> None:
    if not is_remote(path):
        os.makedirs(path, exist_ok=exist_ok)
        return
    _fs_for(path).makedirs(path, exist_ok=exist_ok)


def _with_scheme(fs, paths: List[str]) -> List[str]:
    """fsspec strips the scheme from listing results; put it back so
    every path in the pipeline stays openable by plain ``fs_open``."""
    return [fs.unstrip_protocol(p) for p in paths]


def listdir(path: str) -> List[str]:
    """Names (not full paths) of entries directly under a directory."""
    if not is_remote(path):
        return sorted(os.listdir(path))
    fs = _fs_for(path)
    return sorted(p.rstrip("/").rsplit("/", 1)[-1]
                  for p in fs.ls(path, detail=False))


def glob(pattern: str) -> List[str]:
    """Expand a glob; remote results keep their scheme prefix."""
    if not is_remote(pattern):
        import glob as _glob

        return sorted(_glob.glob(pattern))
    fs = _fs_for(pattern)
    return sorted(_with_scheme(fs, fs.glob(pattern)))


def walk(path: str) -> List[Tuple[str, List[str], List[str]]]:
    """os.walk-shaped traversal (root, dirnames, filenames), sorted."""
    if not is_remote(path):
        return sorted(os.walk(path))
    fs = _fs_for(path)
    out = []
    for root, dirs, files in fs.walk(path):
        out.append((fs.unstrip_protocol(root), sorted(dirs), sorted(files)))
    return sorted(out)


def join(base: str, *parts: str) -> str:
    """Path join that keeps remote URIs forward-slashed."""
    if not is_remote(base):
        return os.path.join(base, *parts)
    return "/".join([base.rstrip("/"), *[p.strip("/") for p in parts]])


_CACHE_DIR: Optional[str] = None


def _cache_dir() -> str:
    global _CACHE_DIR
    if _CACHE_DIR is None:
        _CACHE_DIR = tempfile.mkdtemp(prefix="zoo_fs_cache_")
    return _CACHE_DIR


def local_copy(path: str) -> str:
    """A real local file for native readers (mmap / C stdio).

    Local paths return unchanged (zero copies — the fast path stays
    fast).  Remote URIs download once into a per-process cache keyed by
    the full URI; repeated opens of the same URI reuse the copy."""
    if not is_remote(path):
        return path
    dst = _cache_key_path(path)
    if not os.path.exists(dst):
        fs = _fs_for(path)
        tmp = dst + ".part"
        fs.get_file(path, tmp)
        os.replace(tmp, dst)    # atomic: concurrent readers never see a
        #                         truncated download
    return dst


def _cache_key_path(path: str) -> str:
    import hashlib

    key = hashlib.blake2b(path.encode(), digest_size=10).hexdigest()
    return os.path.join(_cache_dir(), f"{key}_{path.rsplit('/', 1)[-1]}")


def prime_cache(local_path: str, remote_path: str) -> None:
    """Record ``local_path`` as the cached copy of ``remote_path`` so a
    writer that just uploaded an artifact doesn't immediately re-download
    it through ``local_copy``."""
    if not is_remote(remote_path):
        return
    dst = _cache_key_path(remote_path)
    if os.path.abspath(local_path) != os.path.abspath(dst):
        # same atomicity contract as local_copy: a concurrent reader that
        # sees dst exist must never see a partial copy
        shutil.copyfile(local_path, dst + ".part")
        os.replace(dst + ".part", dst)


def upload(local_path: str, remote_path: str) -> None:
    """Push a locally-written artifact to its remote destination."""
    if not is_remote(remote_path):
        if os.path.abspath(local_path) != os.path.abspath(remote_path):
            shutil.copyfile(local_path, remote_path)
        return
    fs = _fs_for(remote_path)
    parent = remote_path.rsplit("/", 1)[0]
    if parent and parent != remote_path:
        try:
            fs.makedirs(parent, exist_ok=True)
        except Exception:
            pass        # object stores have no real directories
    fs.put_file(local_path, remote_path)

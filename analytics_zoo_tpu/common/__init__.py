from analytics_zoo_tpu.common.context import (
    init_context,
    init_orca_context,
    stop_orca_context,
    OrcaContext,
    ZooContext,
)
from analytics_zoo_tpu.common.config import ZooConfig

__all__ = [
    "init_context",
    "init_orca_context",
    "stop_orca_context",
    "OrcaContext",
    "ZooContext",
    "ZooConfig",
]

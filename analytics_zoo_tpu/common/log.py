"""Logging + metrics sinks.

Reference observability (SURVEY.md §5): BigDL TrainSummary/ValidationSummary
to TensorBoard, per-iteration "records/sec" throughput logs, per-epoch stats
dicts from Orca runners.  Here: a MetricLogger that fans out step records to
stderr logging, a JSONL file, and (if `tensorboardX`/`tf` available) an
event-file writer — plus a `jax.profiler` trace toggle, which the reference
never had.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("analytics_zoo_tpu")
if not logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter(
        "[%(asctime)s %(name)s %(levelname)s] %(message)s", "%H:%M:%S"))
    logger.addHandler(_h)
    logger.setLevel(os.environ.get("ZOO_TPU_LOGLEVEL", "INFO"))


class MetricLogger:
    """Fans out {step, **metrics} records; tracks throughput."""

    def __init__(self, jsonl_path: Optional[str] = None,
                 tensorboard_dir: Optional[str] = None,
                 log_every: int = 50):
        self._jsonl = open(jsonl_path, "a") if jsonl_path else None
        self._tb = None
        if tensorboard_dir:
            try:
                from torch.utils.tensorboard import SummaryWriter  # cpu torch in image
                self._tb = SummaryWriter(tensorboard_dir)
            except Exception:
                logger.warning("tensorboard writer unavailable; skipping")
        self.log_every = max(1, log_every)
        self._t0 = time.perf_counter()
        self._samples_since = 0
        self._step_of_last_log = 0

    def log(self, step: int, metrics: Dict[str, Any],
            n_samples: int = 0) -> None:
        self._samples_since += n_samples
        rec = {"step": step}
        rec.update({k: float(v) for k, v in metrics.items()})
        if self._jsonl:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        if self._tb:
            for k, v in rec.items():
                if k != "step":
                    self._tb.add_scalar(k, v, step)
        if step - self._step_of_last_log >= self.log_every:
            dt = time.perf_counter() - self._t0
            tput = self._samples_since / dt if dt > 0 else 0.0
            msg = " ".join(f"{k}={v:.5g}" for k, v in rec.items() if k != "step")
            logger.info("step %d %s samples/sec=%.1f", step, msg, tput)
            self._t0 = time.perf_counter()
            self._samples_since = 0
            self._step_of_last_log = step

    def close(self):
        if self._jsonl:
            self._jsonl.close()
        if self._tb:
            self._tb.close()


def start_profiler_trace(logdir: str) -> None:
    import jax

    jax.profiler.start_trace(logdir)


def stop_profiler_trace() -> None:
    import jax

    jax.profiler.stop_trace()

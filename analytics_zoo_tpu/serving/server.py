"""Cluster Serving — continuous-batching TPU inference service.

Reference surface (SURVEY.md §2.6, §3.5; ref: serving/ClusterServing.scala,
serving/engine/ClusterServingInference.scala, ClusterServingHelper.scala):
a Flink job XREADGROUPs the Redis input stream, micro-batches by size/
timeout, runs InferenceModel, XADDs results; config.yaml drives model path,
batch size, redis address.

TPU re-design: no Flink — ONE host thread owns the serving loop (queue →
micro-batcher → bucketed-pad → jitted forward → result hashes). The TPU's
own pipelining replaces Flink operator parallelism: while step N computes
on device, step N+1 is being batched on host. Backpressure = stream length
(the reference's de-facto backlog metric, SURVEY §5); fixed jit shapes come
from InferenceModel's bucket cache.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import traceback
from typing import Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.common.log import logger
from analytics_zoo_tpu.learn.inference_model import InferenceModel
from analytics_zoo_tpu.serving.flight import (SLO_METRICS, AnomalyMonitor,
                                              FlightRecorder, SloPolicy,
                                              SloWatchdog, dump_bundle,
                                              install_flight_logging,
                                              prune_bundles)
from analytics_zoo_tpu.serving.frontdoor import (PRIORITIES, QosPolicy,
                                                 TokenEmitter,
                                                 decode_deadline,
                                                 decode_priority,
                                                 decode_str_field)
from analytics_zoo_tpu.serving.fault import FaultInjector, InjectedFault
from analytics_zoo_tpu.serving.kv_store import PrefixDirectory
from analytics_zoo_tpu.serving.paged_cache import chain_hashes
from analytics_zoo_tpu.serving.policy import (REPLICA_ROLES,
                                                BrownoutPolicy,
                                                BrownoutState,
                                                ReplicaSignals,
                                                pick_retry_target,
                                                plan_brownout,
                                                plan_handoff_recovery,
                                                plan_redispatch,
                                                replica_dead,
                                                route_request)
from analytics_zoo_tpu.serving.queues import (
    CANCEL_STREAM, IMG_MAGIC, INPUT_STREAM, RESULT_PREFIX, SIGNAL_PREFIX,
    TOKEN_PREFIX, OutputQueue, decode_ndarray, encode_ndarray)
from analytics_zoo_tpu.serving.resp import RespClient, RespServer
from analytics_zoo_tpu.serving.telemetry import Telemetry


@dataclasses.dataclass
class ServingConfig:
    """config.yaml parity (ref: ClusterServingHelper field names)."""

    model_path: str = ""
    redis_host: str = "127.0.0.1"
    redis_port: int = 6379
    batch_size: int = 32            # micro-batch cap
    batch_timeout_ms: float = 5.0   # flush partial batch after this wait
    workers: int = 1                # parallel serving-loop consumers in
    #                                 one shared consumer group (ref: Flink
    #                                 source parallelism; >1 overlaps host
    #                                 decode/batching across workers, and N
    #                                 ClusterServing PROCESSES on one broker
    #                                 scale out the same way)
    input_cols: Optional[List[str]] = None  # None: infer from request
    image_shape: Optional[List[int]] = None  # (H, W): resize decoded
    #                                          image payloads to the model
    #                                          input (ref: serving image
    #                                          resize per model config)
    result_ttl_s: float = 300.0     # abandoned results pruned after this
    core_number: Optional[int] = None   # ref: host CPU cores per serving
    #                                     task — here it caps concurrent
    #                                     host staging (InferenceModel
    #                                     semaphore), NOT batch; None keeps
    #                                     the model's own concurrent_num
    # Generative serving (LM generate): requests in `prompt_col` are
    # RAGGED 1-D token arrays; the batcher right-pads them to a common
    # width with `prompt_pad_id` and appends each request's true length
    # as an extra model input (InferenceModel.load_flax_generator's
    # (prompts, lengths) contract).  None = ordinary fixed-shape serving.
    prompt_col: Optional[str] = None
    prompt_pad_id: int = 0
    # Continuous batching (generative only): in-flight joining over a
    # fixed-slot KV arena (serving/continuous.py) instead of convoying
    # whole generations per micro-batch.  engine_slots co-resident
    # requests; eos_id frees a slot early when the model emits it.
    continuous_batching: bool = False
    engine_slots: int = 8
    # engine replicas (continuous mode): N engines, each owning its own
    # pump thread, telemetry registry and flight ring, behind ONE
    # broker/front door — a router thread (serving/policy.py
    # route_request) places each request on live per-replica signals
    # (pool pressure, queue depth, per-class SLO goodput), falling back
    # to least-loaded round-robin.  1 keeps the single-pump layout
    # bit-identical to previous releases.
    n_replicas: int = 1
    # Prefill/decode disaggregation (docs/serving_memory.md
    # "Disaggregation & elastic pools"): one role string per replica,
    # "prefill" or "decode".  Prefill-heavy replicas run prompts to
    # their first token, export the KV block chain, and a decode-heavy
    # replica adopts it (route_request ranks role match FIRST, so
    # either side still absorbs the other's overflow).  Requires
    # n_replicas > 1, continuous_batching, engine_paged, and no draft
    # model.  None keeps every replica symmetric — bit-identical to
    # role-less routing.
    replica_roles: Optional[List[str]] = None
    # Elastic per-replica block pools: after weights load, each paged
    # engine probes free HBM for a grow ceiling and resizes n_blocks
    # in block-granular steps at the eviction boundary, driven by pool
    # pressure and per-class goodput (policy.plan_pool_resize).  Off =
    # static pools, bit-identical to previous releases.
    engine_elastic_pool: bool = False
    # Tiered KV memory (serving/kv_store.py, docs/serving_memory.md
    # "Tiered KV memory"): a host-RAM second tier per paged engine —
    # evicted prefix chains spill there and re-admit at admission as a
    # host->HBM copy instead of a re-prefill.  0 = tier off,
    # bit-identical to single-tier serving.
    engine_kv_host_store_bytes: int = 0
    # Fleet-wide prefix index: every replica publishes which chain
    # hashes it holds (HBM index or host store) into one shared
    # PrefixDirectory, and the router ranks candidates by estimated
    # reuse depth (the prefix-locality term of route_request, between
    # role match and pool pressure).  Off = locality-blind routing,
    # bit-identical ranks.
    prefix_directory: bool = False
    eos_id: Optional[int] = None
    # tokens decoded per device call: >1 trades admission-latency
    # granularity for fewer host round-trips (tunneled-device win)
    engine_ticks: int = 1
    # narrow the KV arena ("bfloat16" under an f32 model = 2x slots)
    engine_cache_dtype: Optional[str] = None
    # Paged KV cache (serving/paged_cache.py): block-pool memory
    # instead of a per-slot arena — residents hold only the blocks
    # they've filled, shared prompt prefixes attach to the same blocks
    # copy-free, and a dry pool preempts-to-queue instead of OOMing.
    engine_paged: bool = False
    engine_block_size: int = 16
    # Paged-attention read kernel: "gather" (materialising jnp.take
    # reference — the CPU/interpret-safe default) or "fused" (Pallas
    # kernel streaming KV blocks HBM->VMEM).  Paged-only.
    engine_kernel: str = "gather"
    # Paged KV block storage: None follows engine_cache_dtype, "bf16"
    # forces a bfloat16 pool, "int8" stores quantized blocks with
    # per-row scales (~1.9x n_blocks at equal HBM).  Paged-only.
    engine_kv_dtype: Optional[str] = None
    # pool size: engine_blocks wins when set; else engine_hbm_fraction
    # of device HBM (where the backend reports it); else arena-
    # equivalent (every slot can run full-length)
    engine_blocks: Optional[int] = None
    engine_hbm_fraction: Optional[float] = None
    engine_prefix_cache: bool = True
    # Chunked prefill (serving/continuous.py token-budget scheduler):
    # joiners' prompts stream into the cache in chunks fused with
    # active decodes under engine_tick_token_budget tokens per tick —
    # long prompts stop spiking residents' inter-token latency.  None
    # budget = engine default (about one decode bucket of work).
    engine_chunked: bool = False
    engine_tick_token_budget: Optional[int] = None
    # Speculative decoding depth override (proposals per round).  Only
    # meaningful when the model was loaded with a draft
    # (load_flax_generator(draft_model=...)); composes with paged and
    # chunked.  None keeps the depth stored at model load.
    engine_speculation_k: Optional[int] = None
    # QoS front door (serving/frontdoor.py; default OFF for parity):
    # admission + prefill-grant order become a weighted fair share over
    # (priority class, tenant) with aging as the starvation bound.
    qos_enabled: bool = False
    qos_weight_interactive: float = 8.0
    qos_weight_standard: float = 4.0
    qos_weight_batch: float = 1.0
    qos_aging_s: float = 30.0
    # bounded admission: the HTTP frontend's InputQueues reject past
    # this backlog with 429 + Retry-After (0 disables the cap)
    max_backlog: int = 10000
    # SLO watchdog (serving/flight.py): per-priority-class latency
    # targets, seconds.  A finished request is GOOD when none of its
    # queue-wait / TTFT / mean-TPOT exceeded its class target;
    # zoo_slo_* gauges and breach counters keep the score.  A target
    # of 0 disables that dimension for that class.
    slo_ttft_s_interactive: float = 1.0
    slo_ttft_s_standard: float = 2.5
    slo_ttft_s_batch: float = 10.0
    slo_tpot_s_interactive: float = 0.25
    slo_tpot_s_standard: float = 0.5
    slo_tpot_s_batch: float = 2.0
    slo_queue_wait_s_interactive: float = 0.5
    slo_queue_wait_s_standard: float = 2.0
    slo_queue_wait_s_batch: float = 30.0
    # flight recorder: per-tick snapshots retained for diagnostic
    # bundles and GET /debug/flight (0 disables the recorder)
    flight_capacity: int = 2048
    # anomaly-triggered diagnostic bundles (docs/debugging.md): where
    # they land, how often at most, how many survive pruning
    diag_dir: str = "diagnostics"
    diag_min_interval_s: float = 30.0
    diag_max_bundles: int = 8
    # triggers: >= anomaly_breach_burst SLO breaches inside
    # anomaly_breach_window_s; >= anomaly_alloc_streak consecutive
    # ticks with a block-pool allocation failure; any compile after
    # the first anomaly_steady_ticks ticks (0 disables a trigger)
    anomaly_breach_burst: int = 8
    anomaly_breach_window_s: float = 10.0
    anomaly_alloc_streak: int = 8
    anomaly_steady_ticks: int = 500
    # Fleet crash-tolerance (serving/fault.py + the broker supervisor;
    # docs/debugging.md "Crash recovery runbook").  fault_injection is
    # a deterministic chaos schedule — a list of fault-spec dicts
    # (fault.FaultSpec fields: kind / replica / at_tick / at_handoff /
    # count / duration_s).  None = injection OFF, every serving path
    # bit-identical to previous releases.
    fault_injection: Optional[List[dict]] = None
    fault_seed: int = 0
    # Supervisor: a pump silent for supervisor_miss_s seconds is
    # declared dead (policy.replica_dead) and its lost in-flight
    # requests re-dispatch to survivors; 0 disables heartbeat-based
    # death (an exception ESCAPING a pump thread always declares it).
    supervisor_miss_s: float = 0.0
    # At-least-once recovery: max total placements one request may
    # consume (first submit counts as attempt 1); past the budget the
    # supervisor publishes a terminal error instead of re-dispatching.
    retry_budget: int = 2
    # Two-phase handoff: the prefill source retains the exported state
    # until the decode side acks adoption; un-acked entries this old
    # re-dispatch to an alternate decode replica (0 = fire-and-forget,
    # the pre-supervisor behavior).
    handoff_ack_timeout_s: float = 5.0
    # A request the router cannot place (zero live replicas) parks for
    # at most this long before a terminal error — bounded wait, never
    # forever.
    unrouted_ttl_s: float = 5.0
    # Optional end-to-end deadline: a lost request older than this is
    # errored instead of re-dispatched (0 = no deadline; the
    # result_ttl_s prune remains the backstop).
    request_deadline_s: float = 0.0
    # Brownout ladder (docs/serving_qos.md "Overload & brownout"): a
    # broker-level controller walks policy.plan_brownout over the
    # fleet's aggregated signals (min per-class windowed goodput, max
    # queue depth, max alloc-fail streak, recent tick trend) and
    # pushes the resulting level into every engine — level 1 stops
    # admitting batch, 2 clamps standard max_new, 3 disables
    # speculative rounds, 4 serves interactive only.  Off (the
    # default) = controller never runs, every decision bit-identical
    # to previous releases.
    brownout: bool = False
    brownout_goodput_floor: float = 0.9
    brownout_queue_high: int = 64
    brownout_enter_ticks: int = 3
    brownout_exit_ticks: int = 6
    brownout_standard_max_new: int = 16
    # tick-duration breach threshold, seconds (0 disables that signal)
    brownout_tick_s_high: float = 0.0
    brownout_interval_s: float = 0.25

    @staticmethod
    def from_yaml(path: str) -> "ServingConfig":
        import yaml

        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        params = raw.get("params") or {}
        redis_raw = raw.get("redis") or {}
        redis = redis_raw.get("src", redis_raw.get("url", ""))
        cfg = ServingConfig()
        model = raw.get("model", {})
        if isinstance(model, dict):
            cfg.model_path = model.get("path", "")
        if isinstance(redis, str) and ":" in redis:
            host, port = redis.rsplit(":", 1)
            cfg.redis_host, cfg.redis_port = host, int(port)
        # reference config.yaml semantics: core_number is CPU cores (a
        # resource knob), batch_size is the micro-batch — never conflate
        cfg.batch_size = int(params.get("batch_size", 32))
        if "core_number" in params:
            cfg.core_number = int(params["core_number"])
        if "image_shape" in params:
            cfg.image_shape = [int(v) for v in params["image_shape"]]
        if "workers" in params:
            cfg.workers = int(params["workers"])
        if "prompt_col" in params:
            cfg.prompt_col = str(params["prompt_col"])
        if "prompt_pad_id" in params:
            cfg.prompt_pad_id = int(params["prompt_pad_id"])
        if "continuous_batching" in params:
            cfg.continuous_batching = bool(params["continuous_batching"])
        if "engine_slots" in params:
            cfg.engine_slots = int(params["engine_slots"])
        if "n_replicas" in params:
            cfg.n_replicas = int(params["n_replicas"])
        if "replica_roles" in params:
            v = params["replica_roles"]
            cfg.replica_roles = (None if v is None
                                 else [str(x) for x in v])
        if "engine_elastic_pool" in params:
            cfg.engine_elastic_pool = bool(
                params["engine_elastic_pool"])
        if "engine_kv_host_store_bytes" in params:
            cfg.engine_kv_host_store_bytes = int(
                params["engine_kv_host_store_bytes"])
        if "prefix_directory" in params:
            cfg.prefix_directory = bool(params["prefix_directory"])
        if "eos_id" in params:
            cfg.eos_id = int(params["eos_id"])
        if "engine_ticks" in params:
            cfg.engine_ticks = int(params["engine_ticks"])
        if "engine_cache_dtype" in params:
            cfg.engine_cache_dtype = str(params["engine_cache_dtype"])
        if "engine_paged" in params:
            cfg.engine_paged = bool(params["engine_paged"])
        if "engine_block_size" in params:
            cfg.engine_block_size = int(params["engine_block_size"])
        if "engine_kernel" in params:
            cfg.engine_kernel = str(params["engine_kernel"])
        if "engine_kv_dtype" in params:
            v = params["engine_kv_dtype"]
            cfg.engine_kv_dtype = None if v is None else str(v)
        if "engine_blocks" in params:
            cfg.engine_blocks = int(params["engine_blocks"])
        if "engine_hbm_fraction" in params:
            cfg.engine_hbm_fraction = float(params["engine_hbm_fraction"])
        if "engine_prefix_cache" in params:
            cfg.engine_prefix_cache = bool(params["engine_prefix_cache"])
        if "engine_chunked" in params:
            cfg.engine_chunked = bool(params["engine_chunked"])
        if "engine_tick_token_budget" in params:
            cfg.engine_tick_token_budget = int(
                params["engine_tick_token_budget"])
        if "engine_speculation_k" in params:
            cfg.engine_speculation_k = int(
                params["engine_speculation_k"])
        if "qos_enabled" in params:
            cfg.qos_enabled = bool(params["qos_enabled"])
        if "qos_weight_interactive" in params:
            cfg.qos_weight_interactive = float(
                params["qos_weight_interactive"])
        if "qos_weight_standard" in params:
            cfg.qos_weight_standard = float(
                params["qos_weight_standard"])
        if "qos_weight_batch" in params:
            cfg.qos_weight_batch = float(params["qos_weight_batch"])
        if "qos_aging_s" in params:
            cfg.qos_aging_s = float(params["qos_aging_s"])
        if "max_backlog" in params:
            cfg.max_backlog = int(params["max_backlog"])
        for cls in PRIORITIES:
            for dim in SLO_METRICS:
                key = f"slo_{dim}_s_{cls}"
                if key in params:
                    setattr(cfg, key, float(params[key]))
        for key, cast in (("flight_capacity", int), ("diag_dir", str),
                          ("diag_min_interval_s", float),
                          ("diag_max_bundles", int),
                          ("anomaly_breach_burst", int),
                          ("anomaly_breach_window_s", float),
                          ("anomaly_alloc_streak", int),
                          ("anomaly_steady_ticks", int),
                          ("fault_seed", int),
                          ("supervisor_miss_s", float),
                          ("retry_budget", int),
                          ("handoff_ack_timeout_s", float),
                          ("unrouted_ttl_s", float),
                          ("request_deadline_s", float),
                          ("brownout", bool),
                          ("brownout_goodput_floor", float),
                          ("brownout_queue_high", int),
                          ("brownout_enter_ticks", int),
                          ("brownout_exit_ticks", int),
                          ("brownout_standard_max_new", int),
                          ("brownout_tick_s_high", float),
                          ("brownout_interval_s", float)):
            if key in params:
                setattr(cfg, key, cast(params[key]))
        if "fault_injection" in params:
            v = params["fault_injection"]
            cfg.fault_injection = (None if v is None
                                   else [dict(d) for d in v])
        return cfg

    def slo_policy(self) -> SloPolicy:
        """The per-class target table the ``slo_*`` knobs resolve to."""
        return SloPolicy(targets={
            cls: {dim: float(getattr(self, f"slo_{dim}_s_{cls}"))
                  for dim in SLO_METRICS}
            for cls in PRIORITIES})


class ClusterServing:
    """The serving job. Optionally owns an embedded RESP broker.

    Usage:
      serving = ClusterServing(model, config, embedded_broker=True).start()
      InputQueue(port=serving.port).enqueue(...)
    """

    def __init__(self, inference_model: InferenceModel,
                 config: Optional[ServingConfig] = None,
                 embedded_broker: bool = False,
                 engine_mesh=None, engine_partition_rules=None):
        self.model = inference_model
        self.config = config or ServingConfig()
        # continuous batching on a tp mesh (models beyond one chip's
        # HBM); Python-API only — a mesh is not a config.yaml value
        self.engine_mesh = engine_mesh
        self.engine_partition_rules = engine_partition_rules
        self._check_pad_agreement(inference_model)
        if self.config.core_number is not None:
            inference_model.set_concurrency(self.config.core_number)
        self.engine = None      # continuous-batching engine (start())
        self.broker: Optional[RespServer] = None
        if embedded_broker:
            self.broker = RespServer(port=0).start()
            self.config.redis_host = "127.0.0.1"
            self.config.redis_port = self.broker.port
        self.port = self.config.redis_port
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stats_lock = threading.Lock()
        # (uri, written_at) of results not yet known consumed — abandoned
        # ones (client timed out / died) are pruned after result_ttl_s so
        # broker memory stays bounded in long-lived deployments
        self._written: collections.deque = collections.deque()
        # continuous mode: uri -> (submit_time, stream entry id) of
        # requests still inside the engine.  A row older than the ttl
        # has no client left to collect it — _prune_abandoned aborts it
        # so its KV blocks (both pool tenants under speculation) free
        # instead of finishing dead work
        self._inflight: "collections.OrderedDict[str, tuple]" = \
            collections.OrderedDict()
        self.stats = {"requests": 0, "batches": 0, "batch_fill": 0.0,
                      "predict_ms": 0.0}
        # job-level telemetry; continuous mode hands this same facade
        # to the engine, so one registry carries zoo_serving_* AND
        # zoo_engine_* metrics and the event ring interleaves engine
        # spans with serving-side terminal events (abandonment)
        self.telemetry = Telemetry()
        self._register_serving_gauges()
        # ---- incident pillar (serving/flight.py) -----------------------
        # SLO watchdog fed from the shared telemetry's request hooks;
        # its zoo_slo_* families land in the same registry a /metrics
        # scrape merges.  The flight recorder is created HERE (not by
        # the engine) so a crash bundle can still ship the ring after
        # the engine is gone; start() hands it to the engine.
        self.watchdog = SloWatchdog(self.config.slo_policy(),
                                    registry=self.telemetry.metrics)
        self.telemetry.watchdog = self.watchdog
        self.flight = (FlightRecorder(self.config.flight_capacity)
                       if self.config.flight_capacity > 0 else None)
        self.log_ring = install_flight_logging()
        self.anomalies = AnomalyMonitor(
            self._dump_bundle,
            min_interval_s=self.config.diag_min_interval_s,
            breach_burst=self.config.anomaly_breach_burst,
            breach_window_s=self.config.anomaly_breach_window_s,
            alloc_streak=self.config.anomaly_alloc_streak,
            steady_after_ticks=self.config.anomaly_steady_ticks)
        # ---- replica set (continuous mode scale-out) -------------------
        # replica 0 owns the job-level telemetry/watchdog/flight above
        # (single-replica deployments stay bit-identical); each further
        # replica gets its OWN registry, watchdog, flight ring and
        # anomaly monitor, so one replica's incident never muddies a
        # neighbour's trace and the router can read per-replica SLO
        # goodput.  /metrics merges every registry (distinct engines
        # share metric names, so multi-replica scrapes read replica 0's
        # registry plus the zoo_router_* families for the fleet view;
        # per-replica registries feed bundles and the router).
        self.n_replicas = max(1, int(getattr(self.config,
                                             "n_replicas", 1)))
        if self.n_replicas > 1 and not self.config.continuous_batching:
            raise ValueError(
                "n_replicas > 1 needs continuous_batching: true — the "
                "micro-batch path already scales with `workers` "
                "consumers on the shared group; replicas exist to "
                "multiply continuous engines")
        # replica roles (prefill/decode disaggregation): validated
        # eagerly so a bad fleet layout fails at assembly, not from a
        # pump thread mid-request
        roles = getattr(self.config, "replica_roles", None)
        self.replica_roles: Optional[List[str]] = None
        if roles is not None:
            roles = [str(x) for x in roles]
            if len(roles) != self.n_replicas:
                raise ValueError(
                    f"replica_roles needs one role per replica: got "
                    f"{len(roles)} roles for n_replicas="
                    f"{self.n_replicas}")
            bad = [x for x in roles if x not in REPLICA_ROLES]
            if bad:
                raise ValueError(
                    f"replica_roles entries must be one of "
                    f"{REPLICA_ROLES}, got {bad}")
            if self.n_replicas < 2:
                raise ValueError(
                    "replica_roles needs n_replicas > 1: a sole "
                    "replica must both prefill and decode")
            if not self.config.engine_paged:
                raise ValueError(
                    "replica_roles requires engine_paged: true — the "
                    "handoff wire format is a KV block chain")
            self.replica_roles = roles
        if self.config.engine_elastic_pool and \
                not self.config.engine_paged:
            raise ValueError(
                "engine_elastic_pool requires engine_paged: true — "
                "the arena has no block pool to resize")
        # tiered KV memory (serving/kv_store.py): validated eagerly
        # like the knobs above
        if getattr(self.config, "engine_kv_host_store_bytes", 0) > 0 \
                and not self.config.engine_paged:
            raise ValueError(
                "engine_kv_host_store_bytes requires engine_paged: "
                "true — the host tier spills and re-admits KV block "
                "chains")
        if getattr(self.config, "prefix_directory", False):
            if not self.config.engine_paged:
                raise ValueError(
                    "prefix_directory requires engine_paged: true — "
                    "the directory indexes KV block chain hashes")
            if not self.config.continuous_batching:
                raise ValueError(
                    "prefix_directory requires continuous_batching: "
                    "true — only continuous engines publish prefix "
                    "residency")
        self._prefix_directory = (
            PrefixDirectory()
            if getattr(self.config, "prefix_directory", False)
            else None)
        # disaggregation counters (under _rq_cond like the router's
        # other placement state)
        self._role_handoffs = 0
        self._role_prefill_routed = 0
        self._role_decode_routed = 0
        self._h_handoff = None      # set by _register_router_gauges
        self.engines: list = []
        self.telemetries = [self.telemetry]
        self.watchdogs = [self.watchdog]
        self.flights = [self.flight]
        self.anomaly_monitors = [self.anomalies]
        for r in range(1, self.n_replicas):
            tm = Telemetry()
            wd = SloWatchdog(self.config.slo_policy(),
                             registry=tm.metrics)
            tm.watchdog = wd
            fl = (FlightRecorder(self.config.flight_capacity)
                  if self.config.flight_capacity > 0 else None)
            mon = AnomalyMonitor(
                (lambda reason, detail, _r=r:
                 self._dump_bundle(reason, dict(detail, replica=_r))),
                min_interval_s=self.config.diag_min_interval_s,
                breach_burst=self.config.anomaly_breach_burst,
                breach_window_s=self.config.anomaly_breach_window_s,
                alloc_streak=self.config.anomaly_alloc_streak,
                steady_after_ticks=self.config.anomaly_steady_ticks)
            self.telemetries.append(tm)
            self.watchdogs.append(wd)
            self.flights.append(fl)
            self.anomaly_monitors.append(mon)
        # router state: per-replica routed-entry queues + cancel sets
        # under ONE condition (the router appends, pumps pop, kills
        # notify), round-robin cursor, uri->replica map for cancel
        # fan-out, per-replica routed counters
        self._rq_cond = threading.Condition()
        self._rqueues: List[collections.deque] = [
            collections.deque() for _ in range(self.n_replicas)]
        self._rcancels: List[set] = [set()
                                     for _ in range(self.n_replicas)]
        self._pump_live = [False] * self.n_replicas
        self._pump_stops = [threading.Event()
                            for _ in range(self.n_replicas)]
        self._rr_cursor = 0
        self._uri_replica: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()
        self._router_cancelled: set = set()
        self._routed_counts = [0] * self.n_replicas
        self._rerouted_count = 0
        # ---- supervisor state (fleet crash-tolerance) ------------------
        # heartbeats: each pump stamps its slot once per loop pass;
        # the router's liveness sweep reads them through
        # replica_signals -> policy.replica_dead.  Death bookkeeping,
        # per-request attempt counters, parked-unrouted entries and
        # pending (un-acked) two-phase handoffs all live under
        # _rq_cond with the rest of the placement state.
        self._beats = [0.0] * self.n_replicas
        self._death_reasons: List[Optional[str]] = \
            [None] * self.n_replicas
        self._dead_unswept: set = set()
        self._deaths = 0
        self._redispatched = 0
        self._unrouted_expired = 0
        # uri -> total placements so far (absent = 1, the first submit)
        self._attempts: Dict[str, int] = {}
        # (fields, eid, parked_at) the router could not place anywhere
        self._unrouted: collections.deque = collections.deque()
        # uri -> {state, src, dst, sent_at, retries} exported prefills
        # whose decode-side adoption has not acked yet — the retained
        # reference that makes the handoff two-phase
        self._pending_handoffs: Dict[str, dict] = {}
        self._handoff_acks = 0
        self._handoff_timeouts = 0
        self._handoff_retries = 0
        # ---- brownout controller (docs/serving_qos.md) -----------------
        # The POLICY object exists only when the knob is on: with
        # `brownout: false` _brownout_eval never runs, no engine ever
        # sees set_brownout, and every decision stays bit-identical.
        self._brownout_policy = (BrownoutPolicy(
            goodput_floor=float(self.config.brownout_goodput_floor),
            queue_high=int(self.config.brownout_queue_high),
            enter_ticks=int(self.config.brownout_enter_ticks),
            exit_ticks=int(self.config.brownout_exit_ticks),
            standard_max_new=int(self.config.brownout_standard_max_new),
            tick_s_high=float(self.config.brownout_tick_s_high))
            if getattr(self.config, "brownout", False) else None)
        self._brownout_state = BrownoutState()
        self._brownout_transitions = 0
        # chaos harness: parse the schedule eagerly so a bad spec
        # fails at assembly, not from a pump thread mid-request.
        # None/empty = injection off — every path bit-identical.
        faults = getattr(self.config, "fault_injection", None)
        self._fault = (FaultInjector(
            faults, seed=getattr(self.config, "fault_seed", 0))
            if faults else None)
        if self.n_replicas > 1:
            self._register_router_gauges()
        self._img_resize = None
        from concurrent.futures import ThreadPoolExecutor
        import os as _os

        self._decode_pool = ThreadPoolExecutor(
            max_workers=min(8, _os.cpu_count() or 4),
            thread_name_prefix="zoo-serving-decode")

    def _register_serving_gauges(self) -> None:
        """Expose the ``stats`` dict through the metrics registry:
        callbacks read under the stats lock at scrape time, so the
        Prometheus view and ``stats`` can never disagree."""

        def _stat(key, default=0):
            def read():
                with self._stats_lock:
                    return self.stats.get(key, default)
            return read

        m = self.telemetry.metrics
        m.gauge("zoo_serving_requests_total",
                "requests whose results were published",
                fn=_stat("requests"), kind="counter")
        m.gauge("zoo_serving_batches_total", "device dispatches",
                fn=_stat("batches"), kind="counter")
        m.gauge("zoo_serving_batch_fill",
                "fill fraction of the last dispatch (continuous: "
                "arena occupancy)", fn=_stat("batch_fill"))
        m.gauge("zoo_serving_predict_ms",
                "last dispatch latency, ms (continuous: last "
                "request's submit-to-publish)", fn=_stat("predict_ms"))
        m.gauge("zoo_serving_pending_results",
                "published results not yet known consumed",
                fn=lambda: len(self._written))
        # pre-register so the counters are scrapeable at zero, not born
        # on the first event (rate() needs the initial sample)
        m.counter("zoo_serving_requests_abandoned_total",
                  "published results pruned uncollected after the ttl")
        m.counter("zoo_serving_requests_cancelled_total",
                  "requests aborted by live cancellation (explicit "
                  "cancel or mid-stream disconnect)")
        m.counter("zoo_serving_stream_disconnects_total",
                  "streaming clients that disconnected mid-response")
        m.counter("zoo_serving_backpressure_rejections_total",
                  "admissions refused with 429 under a full backlog")
        # brownout families (docs/serving_qos.md "Overload & brownout"):
        # registered unconditionally so dashboards see stable names
        # whether or not the ladder is enabled — all zero when off
        m.gauge("zoo_brownout_level",
                "current brownout ladder level (0 = normal service)",
                fn=lambda: self._brownout_state.level)
        m.counter("zoo_brownout_transitions_total",
                  "brownout ladder level changes (either direction)")
        for cls in PRIORITIES:
            m.counter(f"zoo_brownout_shed_total_{cls}",
                      f"admissions refused with 429 because the "
                      f"brownout ladder browned the {cls} class out")
        m.gauge("zoo_brownout_deadline_shed_total",
                "requests shed at admission fleet-wide because their "
                "deadline had already passed (never reached prefill)",
                fn=lambda: sum(
                    getattr(e, "deadline_sheds", 0)
                    for e in getattr(self, "engines", ()) or ()),
                kind="counter")

    def _register_router_gauges(self) -> None:
        """The ``zoo_router_*`` families (docs/observability.md): fleet
        liveness plus per-replica placement counters and queue depths —
        the serve-smoke 2-replica leg asserts traffic spread on these."""
        m = self.telemetry.metrics
        m.gauge("zoo_router_replicas", "configured engine replicas",
                fn=lambda: self.n_replicas)
        m.gauge("zoo_router_replicas_live",
                "replicas with a live pump thread",
                fn=lambda: sum(1 for v in self._pump_live if v))
        m.gauge("zoo_router_rerouted_total",
                "entries drained from a dead replica's queue and "
                "re-placed on survivors",
                fn=lambda: self._rerouted_count, kind="counter")
        for r in range(self.n_replicas):
            m.gauge(f"zoo_router_routed_total_r{r}",
                    f"requests the router placed on replica {r}",
                    fn=(lambda _r=r: self._routed_counts[_r]),
                    kind="counter")
            m.gauge(f"zoo_router_queue_depth_r{r}",
                    f"replica {r} routed-but-unclaimed entries",
                    fn=(lambda _r=r: len(self._rqueues[_r])))
        # disaggregation families: registered for every multi-replica
        # fleet (zero without replica_roles) so dashboards see stable
        # names whether or not roles are configured
        m.gauge("zoo_router_role_handoffs_total",
                "prefill->decode KV chain handoffs completed",
                fn=lambda: self._role_handoffs, kind="counter")
        m.gauge("zoo_router_role_prefill_routed_total",
                "new requests placed on a prefill-role replica",
                fn=lambda: self._role_prefill_routed, kind="counter")
        m.gauge("zoo_router_role_decode_routed_total",
                "exported prefills placed on a decode-role replica",
                fn=lambda: self._role_decode_routed, kind="counter")
        self._h_handoff = m.histogram(
            "zoo_router_handoff_seconds",
            "wall seconds from prefill export to decode-side "
            "adoption enqueue (route + chain ship)")
        # crash-tolerance families (docs/debugging.md "Crash recovery
        # runbook"): stable names whether or not faults ever fire
        m.gauge("zoo_router_replica_deaths_total",
                "replicas the supervisor declared dead (escaped pump "
                "exception or missed heartbeats)",
                fn=lambda: self._deaths, kind="counter")
        m.gauge("zoo_router_requests_redispatched_total",
                "lost in-flight requests re-dispatched to survivors "
                "(at-least-once recovery)",
                fn=lambda: self._redispatched, kind="counter")
        m.gauge("zoo_engine_handoff_acks_total",
                "two-phase handoffs whose decode-side adoption acked "
                "(the source's retained state released)",
                fn=lambda: self._handoff_acks, kind="counter")
        m.gauge("zoo_engine_handoff_timeouts_total",
                "pending handoffs that hit the ack timeout",
                fn=lambda: self._handoff_timeouts, kind="counter")
        m.gauge("zoo_engine_handoff_retries_total",
                "timed-out handoffs re-dispatched to an alternate "
                "decode replica",
                fn=lambda: self._handoff_retries, kind="counter")

    # ---- lifecycle ----------------------------------------------------

    GROUP = b"serving"

    @classmethod
    def from_config(cls, config_path: str,
                    embedded_broker: bool = False) -> "ClusterServing":
        """ref-parity: the ``cluster-serving-start`` entry — one
        config.yaml names the broker, the knobs, and a SELF-DESCRIBING
        model artifact; the serving job assembles itself from it.

        ``model.path`` routes by artifact type: ``*.xml`` loads an
        OpenVINO IR, a SavedModel directory (local or remote gs://,
        s3://, hdfs:// — TF's filesystem layer resolves those) loads
        through TFNet, and ``*.pt``/``*.pth`` loads a torch module.
        (Flax/orbax exports need their module class and therefore the
        Python API — ``ClusterServing(InferenceModel().load_flax(...),
        cfg)``.)"""
        import os

        from analytics_zoo_tpu.net import _is_local_path

        cfg = ServingConfig.from_yaml(config_path)
        if cfg.continuous_batching:
            # none of the config-routable artifacts (IR / SavedModel /
            # torch) is a generator; fail at assembly time, pointing at
            # the knob, instead of from deep inside start()
            raise ValueError(
                f"{config_path}: continuous_batching: true requires a "
                f"generative model loaded via the Python API "
                f"(InferenceModel().load_flax_generator(...) + "
                f"ClusterServing(model, cfg)); config-file artifacts "
                f"(.xml IR / SavedModel / .pt) cannot serve in "
                f"continuous mode")
        path = cfg.model_path
        if not path:
            raise ValueError(
                f"{config_path}: model.path is required (a .xml IR, a "
                f"SavedModel dir, or a .pt torch module)")
        # existence FIRST for local paths: a typo'd path of ANY
        # extension must read as a typo, not as 'cannot infer the
        # format' or a derived-file error from deeper in a loader
        if _is_local_path(path) and not os.path.exists(path):
            raise FileNotFoundError(
                f"{config_path}: model.path {path!r} does not exist")
        im = InferenceModel()
        if path.endswith(".xml"):
            im.load_openvino(path)
        elif path.endswith((".pt", ".pth")):
            im.load_torch(path)
        elif not _is_local_path(path) or os.path.isdir(path):
            im.load_tf(path)
        else:
            raise ValueError(
                f"cannot infer the model format of {path!r}: expected "
                f"an OpenVINO .xml, a TF SavedModel directory, or a "
                f"torch .pt/.pth")
        return cls(im, cfg, embedded_broker=embedded_broker)

    def start(self) -> "ClusterServing":
        self.client = RespClient(self.config.redis_host,
                                 self.config.redis_port)
        # one shared consumer group: every worker (thread here; other
        # ClusterServing PROCESSES on the same broker too) claims disjoint
        # entries atomically — the Flink-source-parallelism analog
        try:
            # MKSTREAM: a real redis-server refuses to create a group on a
            # stream that has no entries yet (the embedded broker
            # auto-creates either way)
            self.client.execute("XGROUP", "CREATE", INPUT_STREAM,
                                self.GROUP, "0-0", "MKSTREAM")
        except Exception as e:
            if "BUSYGROUP" not in str(e):
                raise
        self._threads = []
        if self.config.continuous_batching:
            # each pump thread owns ONE engine's device state; with
            # n_replicas > 1 a router thread claims from the shared
            # group and places requests on replicas (least-loaded /
            # pressure/SLO-aware), so horizontal scale inside one
            # process is more replicas — more ClusterServing PROCESSES
            # on the same broker still compose on top
            qos = None
            if self.config.qos_enabled:
                qos = QosPolicy(
                    weights={
                        "interactive":
                            float(self.config.qos_weight_interactive),
                        "standard":
                            float(self.config.qos_weight_standard),
                        "batch": float(self.config.qos_weight_batch)},
                    aging_s=float(self.config.qos_aging_s))
            self.engines = [self.model.make_continuous_engine(
                max_slots=self.config.engine_slots,
                eos_id=self.config.eos_id,
                ticks_per_step=self.config.engine_ticks,
                cache_dtype=self.config.engine_cache_dtype,
                mesh=self.engine_mesh,
                partition_rules=self.engine_partition_rules,
                kernel=self.config.engine_kernel,
                kv_dtype=self.config.engine_kv_dtype,
                paged=self.config.engine_paged,
                block_size=self.config.engine_block_size,
                n_blocks=self.config.engine_blocks,
                hbm_fraction=self.config.engine_hbm_fraction,
                enable_prefix_cache=self.config.engine_prefix_cache,
                chunked=self.config.engine_chunked,
                tick_token_budget=self.config.engine_tick_token_budget,
                speculation_k=self.config.engine_speculation_k,
                elastic_pool=self.config.engine_elastic_pool,
                kv_host_store_bytes=getattr(
                    self.config, "engine_kv_host_store_bytes", 0),
                prefix_directory=self._prefix_directory,
                replica_id=r,
                fault_injector=self._fault,
                telemetry=self.telemetries[r],
                qos=qos,
                flight=self.flights[r],
                flight_capacity=self.config.flight_capacity)
                for r in range(self.n_replicas)]
            self.engine = self.engines[0]   # back-compat attribute
            for r in range(self.n_replicas):
                self._pump_live[r] = True
                t = threading.Thread(target=self._loop_continuous,
                                     args=(f"w{r}", r), daemon=True,
                                     name=f"zoo-serving-cb-{r}")
                t.start()
                self._threads.append(t)
            if self.n_replicas > 1:
                rt = threading.Thread(target=self._loop_router,
                                      daemon=True,
                                      name="zoo-serving-router")
                rt.start()
                self._threads.append(rt)
        else:
            for w in range(max(1, self.config.workers)):
                t = threading.Thread(target=self._loop, args=(f"w{w}",),
                                     daemon=True, name=f"zoo-serving-{w}")
                t.start()
                self._threads.append(t)
        self._thread = self._threads[0]     # back-compat attribute
        logger.info("ClusterServing up (redis %s:%d, batch<=%d, "
                    "workers=%d%s)", self.config.redis_host,
                    self.config.redis_port, self.config.batch_size,
                    len(self._threads),
                    ", continuous" if self.config.continuous_batching
                    else "")
        return self

    def register_prefix(self, tokens) -> int:
        """Register a shared prompt prefix (system prompt) with the
        continuous engine; clients then send ``prefix=np.int32(id)``
        alongside a suffix-only prompt.  Python API, after ``start()``
        (the engine owns device state)."""
        if self.engine is None:
            raise RuntimeError(
                "register_prefix needs a RUNNING continuous engine: "
                "enable continuous_batching and call start() first")
        # every replica prefills the prefix into ITS pool/arena; the
        # id counters advance in lockstep (registrations are serialised
        # here), so one id is valid fleet-wide
        ids = [e.register_prefix(tokens) for e in self.engines]
        if len(set(ids)) != 1:
            raise RuntimeError(
                f"prefix ids diverged across replicas: {ids}")
        return ids[0]

    def unregister_prefix(self, pid: int) -> None:
        if self.engine is None:
            raise RuntimeError("no continuous engine running")
        for e in self.engines:
            e.unregister_prefix(pid)

    def stop(self):
        self._stop.set()
        for t in getattr(self, "_threads", []):
            t.join(timeout=5)
        if self.broker is not None:
            self.broker.stop()
        self._decode_pool.shutdown(wait=False)

    def reload_model(self, inference_model: InferenceModel
                     ) -> "ClusterServing":
        """Hot-swap the served model without stopping the loop (ref:
        ClusterServingHelper model hot-load from config).  The swap is one
        attribute assignment — the loop reads ``self.model`` once per
        dispatch, so in-flight batches finish on the old model and the
        next batch runs the new one; no request is dropped."""
        if self.config.continuous_batching:
            raise NotImplementedError(
                "hot reload under continuous batching would orphan the "
                "in-flight KV arena; drain and restart the serving job "
                "to swap models")
        self._check_pad_agreement(inference_model)
        if self.config.core_number is not None:
            inference_model.set_concurrency(self.config.core_number)
        self.model = inference_model
        logger.info("ClusterServing model hot-reloaded")
        return self

    def _check_pad_agreement(self, inference_model: InferenceModel):
        """A generator infers prompt lengths from ITS ``pad_id`` when no
        explicit lengths arrive; the batcher pads ragged prompts with
        ``config.prompt_pad_id``.  The dispatch path always threads
        explicit lengths, so a disagreement is harmless HERE — but the
        same model served outside this batcher (direct ``predict``) would
        miscount, so surface the inconsistency loudly without failing a
        live reload."""
        model_pad = getattr(inference_model, "prompt_pad_id", None)
        if self.config.prompt_col and model_pad is not None and \
                model_pad != self.config.prompt_pad_id:
            logger.warning(
                "serving prompt_pad_id %d != generator pad_id %d; batched "
                "serving threads explicit lengths so this is safe here, "
                "but direct predict() on this model would infer lengths "
                "from the generator's pad id — configure both the same",
                self.config.prompt_pad_id, model_pad)

    # ---- serving loop -------------------------------------------------

    def _read_batch(self, client: RespClient, consumer: str,
                    block_ms: int = 200) -> List[Dict[str, bytes]]:
        """Micro-batch via the shared consumer group: XREADGROUP claims
        entries ATOMICALLY for this consumer (no worker ever sees another
        worker's requests), blocking up to block_ms for the first one and
        topping up within batch_timeout_ms.  With a batch already in
        flight on the device the loop passes a tiny block_ms so finished
        results are written promptly instead of waiting out a full idle
        poll."""
        cfg = self.config

        def claim(count, wait_ms):
            return client.execute(
                "XREADGROUP", "GROUP", self.GROUP, consumer,
                "COUNT", count, "BLOCK", wait_ms, "STREAMS",
                INPUT_STREAM, ">")

        first = claim(cfg.batch_size, block_ms)
        if not first:
            return [], []
        entries = first[0][1]
        deadline = time.monotonic() + cfg.batch_timeout_ms / 1000.0
        while len(entries) < cfg.batch_size:
            wait_ms = int(max(0, (deadline - time.monotonic()) * 1000))
            if wait_ms <= 0:
                break
            more = claim(cfg.batch_size - len(entries), wait_ms)
            if not more:
                break
            entries.extend(more[0][1])
        out = []
        for eid, flat in entries:
            fields = {flat[i].decode(): flat[i + 1]
                      for i in range(0, len(flat), 2)}
            out.append(fields)
        # NOT acked here: entries stay pending (and XLEN counts them)
        # until their results are published, so XPENDING shows the true
        # in-flight window — _finish_entries acks+deletes after publish
        return out, [eid for eid, _ in entries]

    def _loop(self, consumer: str = "w0"):
        """Pipelined serving loop (one per worker): while batch N computes
        on the TPU, batch N+1 is read from the stream and decoded on the
        host (XLA dispatch is async; blocking happens only when N's
        results are written).  Each worker owns its RESP connection."""
        try:
            client = RespClient(self.config.redis_host,
                                self.config.redis_port)
        except OSError:
            logger.exception("serving worker %s could not connect to the "
                             "broker — worker not started", consumer)
            return
        pending = None      # (requests, ids, waiter, dispatched_at)
        try:
            while not self._stop.is_set():
                try:
                    # with work in flight, poll briefly so finished results
                    # are published as soon as the device is done
                    requests, ids = self._read_batch(
                        client, consumer, 2 if pending else 200)
                except (ConnectionError, OSError):
                    if self._stop.is_set():
                        break
                    time.sleep(0.05)
                    continue
                nxt = None
                if requests:
                    try:
                        nxt = self._dispatch_batch(client, requests, ids)
                    except Exception:
                        logger.exception("serving dispatch failed")
                        self._finish_entries(client, ids)
                if pending is not None:
                    try:
                        self._publish_batch(client, *pending)
                    except Exception:
                        logger.exception("serving publish failed")
                        self._finish_entries(client, pending[1])
                pending = nxt
            if pending is not None:
                try:
                    self._publish_batch(client, *pending)
                except Exception:
                    logger.exception("serving publish failed")
                    self._finish_entries(client, pending[1])
        finally:
            client.close()

    def _loop_continuous(self, consumer: str, replica: int = 0):
        """Continuous-batching pump: requests stream into the slot-arena
        engine as they arrive (in-flight joining); each request publishes
        the moment IT finishes, so a 2-token request never convoys behind
        a 32-token neighbour admitted earlier.

        With one replica the pump claims straight from the broker's
        consumer group (the historical path, bit-identical).  With
        ``n_replicas > 1`` a router thread owns the claiming and this
        pump pops its replica's routed queue; a ``kill_pump`` stops the
        claiming but the pump keeps stepping until ITS engine drains,
        so no admitted request is dropped by a graceful kill."""
        try:
            client = RespClient(self.config.redis_host,
                                self.config.redis_port)
        except OSError:
            logger.exception("continuous serving pump could not connect "
                             "to the broker — not started")
            self._pump_live[replica] = False
            return
        engine = self.engines[replica]
        routed = self.n_replicas > 1
        stop_ev = self._pump_stops[replica]
        pcol = self.config.prompt_col or "prompt"
        role = (self.replica_roles[replica]
                if self.replica_roles is not None else None)
        elastic = bool(self.config.engine_elastic_pool)
        next_resize = time.monotonic() + 0.25
        # brownout controller cadence: evaluated from replica 0's pump
        # (the same throttled-control-step pattern as elastic resize);
        # the single evaluation pushes the level to EVERY engine so the
        # fleet walks the ladder together
        brownout_every = max(0.05, float(
            getattr(self.config, "brownout_interval_s", 0.25)))
        next_brownout = time.monotonic() + brownout_every
        # streaming state is PUMP-THREAD-ONLY (on_done/on_token fire
        # inside engine.step() on this thread): the emitter buffers
        # per-token events between steps; one pipeline per step ships
        # them — never a per-token broker round-trip
        emitter = TokenEmitter(max_events=engine.max_new_tokens + 4)
        streaming: set = set()              # uris with a live tok: stream
        cancelled_pending: set = set()      # cancels that beat admission

        def publish(uri: str, toks: np.ndarray, eid: bytes, t0: float,
                    req):
            if uri in streaming:
                # terminal marker rides the emitter BEHIND the final
                # tokens, so the flush preserves emission order
                streaming.discard(uri)
                emitter.finish(uri)
            att = self._attempts.get(uri, 1)
            try:
                cmds = [
                    ("HSET", RESULT_PREFIX + uri, "value",
                     encode_ndarray(toks)),
                    ("XADD", SIGNAL_PREFIX + uri, "*", "ok", "1"),
                    ("SADD", "__result_keys__", uri)]
                if att > 1:
                    # at-least-once: surface how many placements this
                    # request took (clients and the chaos smoke read it)
                    cmds.insert(1, ("HSET", RESULT_PREFIX + uri,
                                    "attempts", str(att)))
                client.pipeline(cmds)
            except Exception as e:
                # the slot is already freed: a swallowed publish failure
                # would be a silent vanish (client blocks to timeout).
                # Fall back to an error result on the OTHER connection so
                # the client fails fast; finish the entry either way.
                logger.exception("continuous publish failed for %r", uri)
                try:
                    self._publish_error(req, f"publish failed: {e!r}")
                except Exception:
                    logger.exception("error-publish also failed for %r",
                                     uri)
            self._finish_entries(client, [eid])
            dt = (time.perf_counter() - t0) * 1000
            cache = engine.cache_metrics()
            with self._stats_lock:
                self.stats["requests"] += 1
                self.stats["batches"] += 1
                # continuous mode: predict_ms is the last request's
                # submit-to-publish latency; fill is arena occupancy
                self.stats["predict_ms"] = dt
                self.stats["batch_fill"] = engine.n_active / max(
                    1, self.config.engine_slots)
                # KV-memory counters (paged mode adds pool occupancy /
                # prefix hit rate / evictions; both modes report
                # preemptions + peak co-residency)
                self.stats["cache"] = cache
                self._written.append((uri, time.monotonic()))
                self._inflight.pop(uri, None)
                self._uri_replica.pop(uri, None)
                self._attempts.pop(uri, None)

        # the continuous pump must prune too (the micro-batch path
        # prunes per publish): time-gated so the idle poll loop isn't
        # taking the stats lock hundreds of times a second.  The cadence
        # re-reads result_ttl_s (it is runtime-tunable) and caps at 5s
        # so a shortened ttl takes effect promptly.
        def _prune_cadence():
            return min(max(1.0, self.config.result_ttl_s / 4.0), 5.0)

        next_prune = time.monotonic() + _prune_cadence()

        def fail(u, exc, eid, ureq):
            self._drop_inflight(u)
            self._publish_error(ureq, f"admission failed: {exc!r}")
            if u in streaming:
                streaming.discard(u)
                emitter.error(u, f"admission failed: {exc!r}"[:200])
            self._finish_entries(client, [eid])

        try:
            while not self._stop.is_set():
                now = time.monotonic()
                # heartbeat: the supervisor's liveness input.  Stamped
                # every pass (busy or idle) so a healthy-but-quiet pump
                # never looks dead; only a wedged/crashed one does.
                self._beats[replica] = now
                if self._fault is not None:
                    act = self._fault.pump_action(replica)
                    if act == "kill":
                        # planned retirement: same path an operator's
                        # /admin/kill_pump takes (graceful drain)
                        try:
                            self.kill_pump(replica)
                        except Exception:
                            logger.exception(
                                "injected kill_pump refused "
                                "(replica %d)", replica)
                    elif act == "crash":
                        # unplanned death: escapes the pump loop and
                        # exercises the supervisor's redispatch path
                        raise InjectedFault(
                            f"injected pump crash (replica {replica})")
                if replica == 0 and now >= next_prune:
                    next_prune = now + _prune_cadence()
                    self._prune_abandoned(client, now)
                if routed:
                    self._drain_routed_cancels(client, replica, emitter,
                                               streaming,
                                               cancelled_pending)
                else:
                    self._drain_cancels(client, emitter, streaming,
                                        cancelled_pending)
                busy = engine.n_active > 0 or engine.n_waiting > 0
                if routed:
                    requests, ids = self._pop_routed(
                        replica, 0.001 if busy else 0.2)
                    if stop_ev.is_set() and not requests and not busy:
                        break       # killed + drained: graceful exit
                else:
                    try:
                        requests, ids = self._read_batch(
                            client, consumer, 1 if busy else 200)
                    except (ConnectionError, OSError):
                        if self._stop.is_set():
                            break
                        time.sleep(0.05)
                        continue
                for r, eid in zip(requests, ids):
                    t0 = time.perf_counter()
                    try:
                        uri = r["uri"].decode()
                        prompt = self._decode_value(r[pcol])
                        # optional per-request generation controls (a
                        # capability the whole-batch path cannot offer:
                        # its one scan runs every row identically)
                        kw = {}
                        if "max_new" in r:
                            kw["max_new"] = int(np.asarray(
                                self._decode_value(r["max_new"])))
                        if "temperature" in r:
                            kw["temperature"] = float(np.asarray(
                                self._decode_value(r["temperature"])))
                        if "seed" in r:
                            kw["rng_seed"] = int(np.asarray(
                                self._decode_value(r["seed"])))
                        if "top_p" in r:
                            kw["top_p"] = float(np.asarray(
                                self._decode_value(r["top_p"])))
                        if "prefix" in r:
                            # prefix-cached request: the id from
                            # ClusterServing.register_prefix
                            kw["prefix"] = int(np.asarray(
                                self._decode_value(r["prefix"])))
                        # front-door control fields (frontdoor.py wire
                        # codecs: the input queue transports ndarrays,
                        # so priority is an index and tenant a byte
                        # array)
                        if "priority" in r:
                            kw["priority"] = decode_priority(
                                self._decode_value(r["priority"]))
                        if "tenant" in r:
                            kw["tenant"] = decode_str_field(
                                self._decode_value(r["tenant"]))
                        if "deadline" in r:
                            # wire deadline (absolute wall-clock ms,
                            # frontdoor.encode_deadline) -> this pump's
                            # monotonic domain; an already-passed one
                            # still submits — admission sheds it with a
                            # terminal deadline_exceeded, never prefill
                            kw["deadline_t"] = decode_deadline(
                                self._decode_value(r["deadline"]))
                        stream = "stream" in r and bool(int(np.asarray(
                            self._decode_value(r["stream"])
                        ).reshape(-1)[0]))
                        if uri in cancelled_pending:
                            # the cancel raced ahead of admission:
                            # never enters the engine
                            cancelled_pending.discard(uri)
                            self._publish_error(
                                {"uri": r["uri"]}, "cancelled")
                            if stream:
                                emitter.cancelled(uri)
                            self._finish_entries(client, [eid])
                            continue
                        if stream:
                            kw["on_token"] = emitter.emit
                            streaming.add(uri)
                        # capture only the uri, not the whole request
                        # dict (it holds the encoded prompt payload —
                        # a needless second copy for the generation's
                        # lifetime)
                        ureq = {"uri": r["uri"]}
                        if role == "prefill" and not stream and \
                                kw.get("temperature", 0.0) <= 0.0:
                            # prefill replica: export at first token
                            # and ship to a decode replica.  Streaming
                            # and sampled rows decode HERE — the
                            # emitter is pump-local and the handoff
                            # contract is greedy-only.
                            kw["handoff_cb"] = (
                                lambda state, _rep=replica:
                                self._handoff_request(_rep, state))
                        engine.submit(
                            uri, prompt,
                            on_done=(lambda u, toks, _eid=eid, _t0=t0,
                                     _r=ureq: publish(u, toks, _eid,
                                                      _t0, _r)),
                            on_error=(lambda u, exc, _eid=eid, _r=ureq:
                                      fail(u, exc, _eid, _r)),
                            **kw)
                        with self._stats_lock:
                            self._inflight[uri] = (time.monotonic(), eid)
                    except Exception as e:
                        try:
                            u = r["uri"].decode()
                            if u in streaming:
                                streaming.discard(u)
                                emitter.error(
                                    u, f"submit failed: {e!r}"[:200])
                        except Exception:
                            pass
                        self._publish_error(r, f"submit failed: {e!r}")
                        self._finish_entries(client, [eid])
                try:
                    engine.step()
                except Exception:
                    # a device/engine error must not silently kill the
                    # sole pump thread — every queued client would hang
                    # to timeout with no log.  Log, breathe, keep
                    # serving (admission of new work may still succeed;
                    # a persistent fault keeps logging loudly).
                    logger.exception("continuous engine step failed "
                                     "(replica %d)", replica)
                    # the flight ring holds the ticks leading here —
                    # exactly what a post-mortem needs; dump now (rate-
                    # limited, failure-isolated) while the state is hot
                    self.anomaly_monitors[replica].crash(
                        traceback.format_exc())
                    time.sleep(0.2)
                else:
                    self._diag_poll(engine, replica)
                    if elastic and time.monotonic() >= next_resize:
                        # throttled elastic-pool control step (pump
                        # thread — the arenas are donated through the
                        # step programs, so resizes interleave with
                        # ticks, never race them)
                        next_resize = time.monotonic() + 0.25
                        try:
                            per_class = self.watchdogs[replica].status(
                            )["per_class"]
                            engine.maybe_autoresize(
                                {c: d["goodput"]
                                 for c, d in per_class.items()})
                        except Exception:
                            logger.exception(
                                "elastic pool autoresize failed "
                                "(replica %d)", replica)
                    if replica == 0 \
                            and self._brownout_policy is not None \
                            and time.monotonic() >= next_brownout:
                        next_brownout = (time.monotonic()
                                         + brownout_every)
                        try:
                            self._brownout_eval()
                        except Exception:
                            logger.exception(
                                "brownout controller step failed")
                self._flush_emitter(client, emitter)
        except Exception:
            # an exception escaping the pump loop used to die silently
            # in the thread, leaving a zombie entry in the router's
            # live set and stranding every admitted request.  Dump a
            # flight bundle (the ring holds the ticks leading here)
            # and declare the replica dead so the supervisor
            # re-dispatches its in-flight work to survivors.
            logger.exception("continuous pump crashed (replica %d)",
                             replica)
            try:
                self.anomaly_monitors[replica].crash(
                    traceback.format_exc())
            except Exception:
                logger.exception("crash bundle dump failed "
                                 "(replica %d)", replica)
            self._declare_dead(replica, "pump_exception")
        finally:
            self._pump_live[replica] = False
            with self._rq_cond:
                self._rq_cond.notify_all()   # wake the router's sweep
            client.close()

    def _diag_poll(self, engine, replica: int = 0) -> None:
        """One cheap anomaly check per pump iteration: three counter
        reads and a deque scan — the monitor only gets expensive when
        it actually triggers a bundle.  Each replica polls ITS monitor
        against ITS telemetry/watchdog, so one replica's pathology
        never hides behind a healthy fleet average."""
        tm = self.telemetries[replica]
        self.anomaly_monitors[replica].poll(
            alloc_fail_streak=engine.alloc_fail_streak,
            ticks=tm.c_ticks.value,
            compiles=(tm.c_jit_builds.value + tm.c_retraces.value),
            watchdog=self.watchdogs[replica])

    def _brownout_eval(self) -> None:
        """One broker-level brownout controller step (replica 0's pump,
        every ``brownout_interval_s``): aggregate the WORST signal on
        every axis across the fleet — min per-class windowed goodput,
        max effective queue depth, max alloc-fail streak, replica 0's
        recent tick trend from the flight ring — hand them to the pure
        ``plan_brownout``, and on a level change push the new level
        into every engine and leave a trace instant.  One controller,
        one ladder: the fleet degrades (and recovers) together, so a
        client never sees replica-dependent admission."""
        pol = self._brownout_policy
        if pol is None:
            return
        goodput = {
            cls: min(self.watchdogs[r].windowed_goodput(cls)
                     for r in range(self.n_replicas))
            for cls in PRIORITIES}
        queue_depth = 0
        streak = 0
        for r in range(self.n_replicas):
            eng = self.engines[r]
            queue_depth = max(queue_depth,
                              len(self._rqueues[r]) + eng.n_waiting)
            streak = max(streak, eng.alloc_fail_streak)
        tick_s = 0.0
        if self.flight is not None and len(self.flight):
            tail = self.flight.snapshot(last=8)
            tick_s = sum(t.get("dur_ms", 0.0) for t in tail) \
                / len(tail) / 1e3
        prev = self._brownout_state
        state = plan_brownout(pol, prev, goodput=goodput,
                              queue_depth=queue_depth,
                              alloc_fail_streak=streak, tick_s=tick_s)
        self._brownout_state = state
        if state.level == prev.level:
            return
        self._brownout_transitions += 1
        self.telemetry.brownout_transition(state.level, prev.level)
        log = (logger.warning if state.level > prev.level
               else logger.info)
        log("brownout level %d -> %d (goodput=%s queue=%d streak=%d "
            "tick_s=%.3f)", prev.level, state.level,
            {c: round(g, 3) for c, g in goodput.items()}, queue_depth,
            streak, tick_s)
        clamp = int(getattr(self.config, "brownout_standard_max_new",
                            0))
        for e in self.engines:
            e.set_brownout(state.level, standard_max_new=clamp)

    def brownout_level(self) -> int:
        """The fleet's current brownout ladder level (0 = normal) —
        the HTTP front door's per-class admission gate and /healthz
        read it here."""
        return self._brownout_state.level

    def _dump_bundle(self, reason: str, detail: dict) -> str:
        """AnomalyMonitor's dump callback: one self-contained bundle
        directory under ``diag_dir`` (docs/debugging.md), then prune
        to ``diag_max_bundles``."""
        engine = getattr(self, "engine", None)
        spec_acceptance = (engine.spec_acceptance()
                           if engine is not None
                           and hasattr(engine, "spec_acceptance")
                           else None)
        path = dump_bundle(
            self.config.diag_dir, reason=reason, detail=detail,
            flight=self.flight, telemetries=(self.telemetry,),
            config=dataclasses.asdict(self.config),
            logs=self.log_ring.snapshot(),
            slo=self.watchdog.status(),
            spec_acceptance=spec_acceptance)
        prune_bundles(self.config.diag_dir,
                      max(1, self.config.diag_max_bundles))
        return path

    def _flush_emitter(self, client: RespClient,
                       emitter: TokenEmitter) -> None:
        """Publish every token/terminal event buffered since the last
        engine step in ONE pipeline — per-step, never per-token."""
        batch = emitter.drain()
        if not batch:
            return
        cmds = []
        for uri, events in batch:
            key = TOKEN_PREFIX + uri
            for kind, idx, val in events:
                if kind == "tok":
                    cmds.append(("XADD", key, "*", "i", idx, "t", val))
                elif kind == "done":
                    cmds.append(("XADD", key, "*", "done", "1"))
                elif kind == "cancelled":
                    cmds.append(("XADD", key, "*", "cancelled", "1"))
                else:
                    cmds.append(("XADD", key, "*", "error",
                                 str(val)[:500]))
        try:
            client.pipeline(cmds)
        except Exception:
            logger.exception("token-stream publish failed")

    def _drain_cancels(self, client: RespClient, emitter: TokenEmitter,
                       streaming: set, cancelled_pending: set) -> int:
        """Serve ``serving_cancel`` entries on the pump thread (the
        engine's ``abort`` contract): free the row's slot + BOTH pool
        tenants' blocks immediately, publish a fail-fast "cancelled"
        result, and terminate any live token stream.  Cancels that
        arrive before their request was claimed from the input stream
        park in ``cancelled_pending`` so admission skips them."""
        try:
            entries = client.execute("XRANGE", CANCEL_STREAM, "-", "+")
        except Exception:
            return 0
        if not entries:
            return 0
        ids = []
        for eid, flat in entries:
            ids.append(eid)
            f = {flat[i].decode(): flat[i + 1]
                 for i in range(0, len(flat), 2)}
            uri = f.get("uri", b"").decode()
            if uri:
                self._cancel_one(client, uri, emitter, streaming,
                                 cancelled_pending)
        try:
            client.execute("XDEL", CANCEL_STREAM, *ids)
        except Exception:
            logger.exception("cancel-stream trim failed")
        return len(ids)

    def _cancel_one(self, client: RespClient, uri: str,
                    emitter: TokenEmitter, streaming: set,
                    cancelled_pending: set, engine=None) -> None:
        engine = engine if engine is not None else self.engine
        with self._stats_lock:
            info = self._inflight.pop(uri, None)
        aborted = engine.abort(uri)
        if not aborted and info is None:
            # not in the engine and not tracked: either it already
            # published (don't clobber the result) or it is still in
            # the input stream — park the uri so admission skips it
            if uri not in streaming:
                if len(cancelled_pending) < 4096:
                    cancelled_pending.add(uri)
                return
        if uri in streaming:
            streaming.discard(uri)
            emitter.cancelled(uri)
        self.telemetry.req_cancelled(uri)
        # fail-fast error result so a blocked query() client returns
        # now instead of riding out its timeout
        self._publish_error({"uri": uri.encode()}, "cancelled")
        if info is not None:
            self._finish_entries(client, [info[1]])

    # ---- multi-replica router (serving/policy.py route_request) -------

    def _pop_routed(self, replica: int, wait_s: float):
        """A pump's claim path in multi-replica mode: pop up to
        batch_size routed entries from THIS replica's queue.  A killed
        pump claims nothing more — its unclaimed queue becomes the
        router's to re-place (``_reroute_dead``)."""
        cap = self.config.batch_size
        out = []
        with self._rq_cond:
            if self._pump_stops[replica].is_set():
                return [], []
            q = self._rqueues[replica]
            if not q:
                self._rq_cond.wait(wait_s)
                if self._pump_stops[replica].is_set():
                    return [], []
            while q and len(out) < cap:
                out.append(q.popleft())
        if not out:
            return [], []
        return [f for f, _ in out], [e for _, e in out]

    def _drain_routed_cancels(self, client: RespClient, replica: int,
                              emitter: TokenEmitter, streaming: set,
                              cancelled_pending: set) -> int:
        """Multi-replica cancel leg: the router already fanned the
        cancel stream out to owning replicas (``_route_cancels``); each
        pump serves its own share against ITS engine."""
        with self._rq_cond:
            if not self._rcancels[replica]:
                return 0
            uris = list(self._rcancels[replica])
            self._rcancels[replica].clear()
        for uri in uris:
            self._cancel_one(client, uri, emitter, streaming,
                             cancelled_pending,
                             engine=self.engines[replica])
        return len(uris)

    def replica_signals(self, replica: int) -> ReplicaSignals:
        """Snapshot one replica's live routing signals: effective load
        (routed-but-unclaimed + queued-in-engine + resident), pool
        pressure (paged engines only — arena replicas report no block
        counts and are never 'pressured' on that leg), and per-class
        SLO goodput from the replica's own watchdog."""
        eng = self.engines[replica]
        pool = getattr(eng, "_pool", None)
        per_class = self.watchdogs[replica].status()["per_class"]
        beat = self._beats[replica]
        return ReplicaSignals(
            replica=replica,
            live=self._pump_live[replica],
            queue_depth=(len(self._rqueues[replica])
                         + eng.n_waiting + eng.n_active),
            allocatable_blocks=(pool.allocatable()
                                if pool is not None else None),
            alloc_fail_streak=eng.alloc_fail_streak,
            goodput={c: d["goodput"] for c, d in per_class.items()},
            role=(self.replica_roles[replica]
                  if self.replica_roles is not None else None),
            heartbeat_age_s=((time.monotonic() - beat)
                             if beat > 0.0 else None))

    def router_status(self) -> dict:
        """Live routing view — the observability surface behind the
        ``zoo_router_*`` families and the serve-smoke 2-replica leg's
        assertions."""
        status = {
            "n_replicas": self.n_replicas,
            "live": list(self._pump_live),
            "routed": list(self._routed_counts),
            "rerouted": self._rerouted_count,
            "queue_depths": [len(q) for q in self._rqueues],
            "roles": (list(self.replica_roles)
                      if self.replica_roles is not None else None),
            "handoffs": self._role_handoffs,
            # supervisor view (docs/debugging.md § Crash recovery)
            "deaths": self._deaths,
            "death_reasons": list(self._death_reasons),
            "redispatched": self._redispatched,
            "handoff_acks": self._handoff_acks,
            "handoff_timeouts": self._handoff_timeouts,
            "handoff_retries": self._handoff_retries,
            "unrouted": len(self._unrouted),
            "unrouted_expired": self._unrouted_expired,
        }
        if self._fault is not None:
            status["faults"] = self._fault.snapshot()
        if self.engines:
            status["signals"] = [
                dataclasses.asdict(self.replica_signals(r))
                for r in range(self.n_replicas)]
        return status

    def kill_pump(self, replica: int) -> None:
        """Gracefully retire one replica: the router stops placing new
        work there at once, the pump claims nothing more but keeps
        stepping until every request already admitted to its engine
        has published, then exits; the replica's routed-but-unclaimed
        entries are swept onto survivors by the router.  The drain
        test and the serve-smoke 2-replica leg drive this path."""
        if not 0 <= replica < self.n_replicas:
            raise ValueError(f"no replica {replica} "
                             f"(n_replicas={self.n_replicas})")
        if self.n_replicas == 1:
            raise ValueError(
                "kill_pump on the sole pump would stop serving "
                "entirely — that is stop()")
        self._pump_live[replica] = False
        self._pump_stops[replica].set()
        with self._rq_cond:
            self._rq_cond.notify_all()

    def _route_one(self, client: RespClient, fields: Dict[str, bytes],
                   eid) -> None:
        """Place ONE claimed entry: cancel-raced entries die here
        without touching any engine; otherwise route_request ranks the
        live replicas on (pressure, SLO degradation, depth, round-
        robin distance) and the entry lands in the winner's queue."""
        try:
            uri = fields["uri"].decode()
        except Exception:
            uri = ""
        if uri and uri in self._router_cancelled:
            self._router_cancelled.discard(uri)
            self._publish_error({"uri": fields["uri"]}, "cancelled")
            self._finish_entries(client, [eid])
            return
        priority = None
        if "priority" in fields:
            try:
                priority = decode_priority(
                    self._decode_value(fields["priority"]))
            except Exception:
                priority = None
        sigs = [self.replica_signals(r)
                for r in range(self.n_replicas)]
        if self._prefix_directory is not None:
            # prefix locality: hash the prompt's full blocks exactly
            # like paged admission will and ask the fleet directory
            # which replica already holds the deepest leading run
            # (HBM index or host store).  Advisory only — a failed
            # decode leaves prefix_blocks at 0, never blocks routing.
            try:
                pcol = self.config.prompt_col or "prompt"
                if pcol in fields:
                    toks = np.asarray(self._decode_value(
                        fields[pcol])).reshape(-1)
                    bs = self.config.engine_block_size
                    # admission caps the usable match at (plen-1)//bs
                    # blocks (the last prompt token always recomputes)
                    hashes = chain_hashes(
                        [int(t) for t in toks],
                        bs)[: max(0, (len(toks) - 1) // bs)]
                    if hashes:
                        depths = self._prefix_directory.match_depths(
                            hashes)
                        sigs = [dataclasses.replace(
                                    s, prefix_blocks=depths.get(
                                        s.replica, 0))
                                for s in sigs]
            except Exception:
                logger.exception(
                    "prefix-locality probe failed; routing "
                    "locality-blind")
        # a NEW request always enters at its prefill phase; without
        # replica_roles every signal's role is None and the rank is
        # bit-identical to role-less routing
        r = route_request(sigs, priority, self._rr_cursor,
                          phase=("prefill" if self.replica_roles
                                 else None))
        if r is None:
            # no live pump anywhere: park the entry — the fleet may be
            # mid-recovery (a replica restarting, a supervisor sweep in
            # flight).  The router's unrouted sweep re-places it when a
            # pump returns, or expires it to a terminal error after
            # unrouted_ttl_s so no client waits forever.
            self._unrouted.append((fields, eid, time.monotonic()))
            return
        with self._rq_cond:
            self._rqueues[r].append((fields, eid))
            if uri:
                self._uri_replica[uri] = r
                while len(self._uri_replica) > 65536:
                    self._uri_replica.popitem(last=False)
            self._routed_counts[r] += 1
            if self.replica_roles is not None and \
                    self.replica_roles[r] == "prefill":
                self._role_prefill_routed += 1
            self._rr_cursor = (r + 1) % self.n_replicas
            self._rq_cond.notify_all()

    def _handoff_request(self, src: int, state: dict) -> None:
        """Place one exported prefill on a decode-heavy replica — runs
        on the SOURCE pump thread, inside the engine's ``handoff_cb``,
        the tick the prompt's first token lands.  ``route_request``
        ranks the fleet with ``phase="decode"``: decode-role replicas
        win, a pressured/degraded decode tier falls back across roles,
        and the source itself is the last resort (self-adoption — the
        request decodes where it prefilled; a beat slower, never
        wrong).  ``submit_handoff`` only enqueues host state under the
        destination's engine lock, so calling straight into another
        replica's engine from this thread is safe; all device writes
        happen later on the destination pump at admission.  The
        ``kill_pump`` drain contract holds unchanged: an exported
        request counts as admitted work on its DESTINATION, whose pump
        keeps stepping until its engine drains.

        Two-phase delivery (``handoff_ack_timeout_s > 0``): the state
        dict — which holds the exported chain's host tensors, keeping
        them referenced — is retained in ``_pending_handoffs`` until
        the destination's ``_admit_handoff`` fires the ``on_adopt``
        ack; the router's ``_sweep_handoffs`` re-dispatches a delivery
        whose ack never lands (dropped transfer, destination died
        mid-adoption) to an alternate replica, giving the handoff leg
        the same at-least-once contract as fresh admissions."""
        t0 = time.monotonic()
        uri = state.get("uri", "")
        sigs = [self.replica_signals(r)
                for r in range(self.n_replicas)]
        r = route_request(sigs, state.get("priority"),
                          self._rr_cursor, phase="decode")
        if r is None:
            r = src
        ack_timeout = getattr(self.config, "handoff_ack_timeout_s", 0.0)
        two_phase = bool(uri) and ack_timeout > 0 and r != src
        if two_phase:
            state = dict(state)
            state["on_adopt"] = self._ack_handoff
            self._pending_handoffs[uri] = {
                "state": state, "src": src, "dst": r,
                "sent_at": time.monotonic(), "retries": 0}
        deliver = True
        if self._fault is not None and r != src:
            act = self._fault.handoff_action()
            if act is not None:
                kind, delay = act
                if kind == "drop" and two_phase:
                    # swallowed delivery: the pending entry stays; the
                    # router's ack-timeout sweep recovers the request
                    deliver = False
                    logger.warning("fault injection dropped handoff "
                                   "of %r to replica %d", uri, r)
                elif kind == "drop":
                    logger.warning(
                        "drop_handoff fired but two-phase ack is off "
                        "(handoff_ack_timeout_s=0) — delivering "
                        "anyway, a drop would strand %r", uri)
                elif kind == "delay":
                    # a slow DCN transfer: the source pump stalls for
                    # the transfer time (ack sweep may beat it)
                    time.sleep(delay)
        if deliver:
            try:
                self.engines[r].submit_handoff(state)
            except Exception:
                if r == src:
                    if two_phase:
                        self._pending_handoffs.pop(uri, None)
                    # _handoff_slot catches this and error-publishes
                    # the request through its on_error
                    raise
                logger.exception(
                    "handoff of %r to replica %d failed; self-adopting "
                    "on replica %d", uri, r, src)
                if two_phase:
                    self._pending_handoffs.pop(uri, None)
                r = src
                self.engines[r].submit_handoff(state)
        with self._rq_cond:
            self._role_handoffs += 1
            if self.replica_roles is not None and \
                    self.replica_roles[r] == "decode":
                self._role_decode_routed += 1
            if uri:
                # cancels/abandonment now belong to the decode side
                self._uri_replica[uri] = r
            self._rq_cond.notify_all()   # wake an idle decode pump
        if self._h_handoff is not None:
            self._h_handoff.record(time.monotonic() - t0)

    def _ack_handoff(self, uri: str, dst: int) -> None:
        """Adoption ack — fired by the DESTINATION engine's
        ``_admit_handoff`` under its lock, so this must stay record-
        only (no locks, no engine calls): pop the pending entry (its
        drop releases the source-side chain references) and count the
        ack.  ``pop`` with a default keeps a late duplicate ack (a
        retried delivery whose first copy survived after all)
        harmless."""
        if self._pending_handoffs.pop(uri, None) is not None:
            self._handoff_acks += 1

    def _sweep_handoffs(self, client: RespClient) -> None:
        """Router-side ack-timeout sweep: a pending handoff whose
        adoption never acked within ``handoff_ack_timeout_s`` is
        re-dispatched to an alternate replica (``pick_retry_target``
        excludes the unresponsive destination; the source itself is
        the last resort), bounded by ``retry_budget`` — beyond it the
        request error-terminates rather than ping-ponging forever."""
        timeout = getattr(self.config, "handoff_ack_timeout_s", 0.0)
        if timeout <= 0 or not self._pending_handoffs:
            return
        now = time.monotonic()
        budget = int(getattr(self.config, "retry_budget", 2))
        for uri in list(self._pending_handoffs):
            info = self._pending_handoffs.get(uri)
            if info is None:        # acked while we swept
                continue
            verdict = plan_handoff_recovery(
                age_s=now - info["sent_at"], timeout_s=timeout,
                retries=info["retries"], retry_budget=budget)
            if verdict == "wait":
                continue
            self._handoff_timeouts += 1
            if verdict == "give_up":
                self._pending_handoffs.pop(uri, None)
                logger.error("handoff of %r never adopted after %d "
                             "retries — error-terminating", uri,
                             info["retries"])
                self._publish_error(
                    {"uri": uri.encode()},
                    f"handoff adoption failed after "
                    f"{info['retries']} retries")
                with self._stats_lock:
                    held = self._inflight.pop(uri, None)
                self._uri_replica.pop(uri, None)
                self._attempts.pop(uri, None)
                if held is not None:
                    self._finish_entries(client, [held[1]])
                continue
            sigs = [self.replica_signals(r)
                    for r in range(self.n_replicas)]
            r = pick_retry_target(
                sigs, info["state"].get("priority"), self._rr_cursor,
                exclude=(info["dst"],), phase="decode")
            if r is None:
                r = info["src"]
            logger.warning("handoff of %r to replica %d timed out "
                           "(no adoption ack in %.1fs) — retrying on "
                           "replica %d", uri, info["dst"], timeout, r)
            info["retries"] += 1
            info["dst"] = r
            info["sent_at"] = now
            self._handoff_retries += 1
            try:
                self.engines[r].submit_handoff(info["state"])
            except Exception:
                logger.exception("handoff retry of %r to replica %d "
                                 "failed; next sweep retries", uri, r)
                continue
            with self._rq_cond:
                self._uri_replica[uri] = r
                self._rq_cond.notify_all()

    def _route_cancels(self, client: RespClient) -> int:
        """Router-side cancel fan-out: owning replicas get the uri in
        their cancel set; uris the router never placed park in
        ``_router_cancelled`` so a late-claimed entry dies at routing
        time (the single-pump path's ``cancelled_pending``, lifted to
        the router)."""
        try:
            entries = client.execute("XRANGE", CANCEL_STREAM, "-", "+")
        except Exception:
            return 0
        if not entries:
            return 0
        ids = []
        with self._rq_cond:
            for eid, flat in entries:
                ids.append(eid)
                f = {flat[i].decode(): flat[i + 1]
                     for i in range(0, len(flat), 2)}
                uri = f.get("uri", b"").decode()
                if not uri:
                    continue
                r = self._uri_replica.get(uri)
                if r is not None:
                    self._rcancels[r].add(uri)
                elif len(self._router_cancelled) < 4096:
                    self._router_cancelled.add(uri)
            self._rq_cond.notify_all()
        try:
            client.execute("XDEL", CANCEL_STREAM, *ids)
        except Exception:
            logger.exception("cancel-stream trim failed")
        return len(ids)

    def _reroute_dead(self, client: RespClient) -> None:
        """Sweep dead replicas' unclaimed queues onto survivors — the
        other half of the graceful-kill contract: admitted work drains
        in place, unclaimed work moves."""
        moved = []
        with self._rq_cond:
            for r in range(self.n_replicas):
                if self._pump_live[r] or not self._rqueues[r]:
                    continue
                while self._rqueues[r]:
                    moved.append(self._rqueues[r].popleft())
        for fields, eid in moved:
            self._rerouted_count += 1
            self._route_one(client, fields, eid)

    # ---- supervisor: liveness, death, at-least-once redispatch --------

    def _declare_dead(self, replica: int, reason: str) -> None:
        """UNPLANNED death: mark the replica dead (idempotent), stop
        routing to it, and queue it for the router's redispatch sweep.
        Distinct from ``kill_pump`` — a graceful kill drains admitted
        work in place and never lands here; a declared death's
        in-flight requests are lost and must be re-placed."""
        with self._rq_cond:
            if self._death_reasons[replica] is not None:
                return
            self._death_reasons[replica] = reason
            self._deaths += 1
            self._pump_live[replica] = False
            self._pump_stops[replica].set()
            self._dead_unswept.add(replica)
            self._rq_cond.notify_all()
        logger.error("replica %d declared dead (%s) — its in-flight "
                     "requests will be re-dispatched", replica, reason)

    def _supervise(self, client: RespClient) -> None:
        """One router-loop supervision pass: (a) heartbeat-miss death
        (opt-in via ``supervisor_miss_s``; escaped pump exceptions
        declare themselves regardless), (b) redispatch of dead
        replicas' lost in-flight requests, (c) handoff ack-timeout
        sweep, (d) parked-unrouted TTL sweep.  Every DECISION here is
        a pure ``policy.py`` function (replica_dead / plan_redispatch
        / pick_retry_target / plan_handoff_recovery) that the sim's
        ``FleetModel`` exercises identically."""
        miss = float(getattr(self.config, "supervisor_miss_s", 0.0))
        if miss > 0.0:
            now = time.monotonic()
            for r in range(self.n_replicas):
                if (self._pump_live[r]
                        and not self._pump_stops[r].is_set()
                        and self._beats[r] > 0.0
                        and replica_dead(now - self._beats[r], miss)):
                    self._declare_dead(r, "heartbeat_miss")
        while True:
            with self._rq_cond:
                if not self._dead_unswept:
                    break
                dead = self._dead_unswept.pop()
            self._redispatch_replica(client, dead)
        self._sweep_handoffs(client)
        self._sweep_unrouted(client)

    def _reread_entry(self, client: RespClient,
                      eid) -> Optional[Dict[str, bytes]]:
        """Re-read one UNACKED input-stream entry by id — the broker
        retains every claimed entry until ``_finish_entries`` acks it,
        which is exactly what makes at-least-once redispatch possible:
        the original request fields survive the replica that was
        serving them."""
        try:
            if isinstance(eid, bytes):
                eid = eid.decode()
            entries = client.execute("XRANGE", INPUT_STREAM, eid, eid)
        except Exception:
            logger.exception("redispatch re-read failed for entry %r",
                             eid)
            return None
        want = eid.encode() if isinstance(eid, str) else eid
        for got, flat in entries or []:
            # trust nothing: a broker with sloppy range semantics must
            # not make us resurrect the WRONG request N times while the
            # real lost one stays stranded
            if got == want:
                return {flat[i].decode(): flat[i + 1]
                        for i in range(0, len(flat), 2)}
        return None

    def _redispatch_replica(self, client: RespClient,
                            dead: int) -> None:
        """Re-place a dead replica's lost in-flight requests on
        survivors with at-least-once semantics: ``plan_redispatch``
        decides retry / terminal-error (budget or deadline exhausted)
        / terminal-cancelled per request; a retry re-reads the
        original entry from the unacked stream, bumps the attempt
        counter, and XADDs a ``restart`` marker on the token stream so
        streaming clients see the emitted-token index reset instead of
        a silent splice."""
        with self._stats_lock:
            lost = [(uri, info) for uri, info in self._inflight.items()
                    if self._uri_replica.get(uri) == dead]
        budget = int(getattr(self.config, "retry_budget", 2))
        deadline = float(getattr(self.config, "request_deadline_s",
                                 0.0))
        now = time.monotonic()
        for uri, (t_submit, eid) in lost:
            with self._stats_lock:
                if self._inflight.pop(uri, None) is None:
                    continue        # published while we swept
            attempt = self._attempts.get(uri, 1)
            was_cancelled = (uri in self._rcancels[dead]
                             or uri in self._router_cancelled)
            verdict = plan_redispatch(
                attempt=attempt, retry_budget=budget,
                cancelled=was_cancelled, age_s=now - t_submit,
                deadline_s=deadline)
            if verdict == "cancel":
                self._rcancels[dead].discard(uri)
                self._router_cancelled.discard(uri)
                self._publish_error({"uri": uri.encode()}, "cancelled")
                self._finish_entries(client, [eid])
                self._uri_replica.pop(uri, None)
                self._attempts.pop(uri, None)
                continue
            if verdict == "error":
                why = ("deadline" if deadline > 0.0
                       and now - t_submit > deadline else "retry budget")
                self._publish_error(
                    {"uri": uri.encode()},
                    f"replica {dead} died; {why} exhausted "
                    f"(attempts={attempt})")
                self._finish_entries(client, [eid])
                self._uri_replica.pop(uri, None)
                self._attempts.pop(uri, None)
                continue
            fields = self._reread_entry(client, eid)
            if fields is None:
                self._publish_error(
                    {"uri": uri.encode()},
                    f"replica {dead} died; original request entry "
                    f"lost — cannot redispatch")
                self._finish_entries(client, [eid])
                self._uri_replica.pop(uri, None)
                self._attempts.pop(uri, None)
                continue
            self._attempts[uri] = attempt + 1
            self._redispatched += 1
            logger.warning("re-dispatching %r (attempt %d/%d) after "
                           "replica %d died", uri, attempt + 1,
                           max(1, budget), dead)
            if "stream" in fields:
                # client-visible restart: the consumer resets its
                # emitted-token index to 0 (queues.stream_events /
                # the SSE leg surface it as a `restart` event)
                try:
                    client.execute("XADD", TOKEN_PREFIX + uri, "*",
                                   "restart", str(attempt + 1))
                except Exception:
                    logger.exception("restart marker publish failed "
                                     "for %r", uri)
            self._route_one(client, fields, eid)
            r2 = self._uri_replica.get(uri)
            if r2 is not None:
                try:
                    self.telemetries[r2].req_redispatched(
                        uri, attempt + 1)
                except Exception:
                    pass
        # the dead replica's pending cancels follow their requests:
        # re-placed uris move to the new owner's cancel set, the rest
        # park router-side so a late-claimed entry still dies
        with self._rq_cond:
            orphans = list(self._rcancels[dead])
            self._rcancels[dead].clear()
            for uri in orphans:
                r = self._uri_replica.get(uri)
                if r is not None and r != dead:
                    self._rcancels[r].add(uri)
                elif len(self._router_cancelled) < 4096:
                    self._router_cancelled.add(uri)
            self._rq_cond.notify_all()

    def _sweep_unrouted(self, client: RespClient) -> None:
        """Parked-unrouted sweep: entries ``_route_one`` could not
        place (zero live replicas) wait bounded — re-placed the moment
        a pump is live again, error-terminated after
        ``unrouted_ttl_s`` so no client waits forever (the HTTP front
        door additionally 503s new submits while the fleet is dead)."""
        if not self._unrouted:
            return
        ttl = float(getattr(self.config, "unrouted_ttl_s", 5.0))
        now = time.monotonic()
        any_live = any(self._pump_live)
        for _ in range(len(self._unrouted)):
            fields, eid, parked = self._unrouted.popleft()
            if any_live:
                self._route_one(client, fields, eid)
            elif ttl > 0 and now - parked > ttl:
                self._unrouted_expired += 1
                self._publish_error(
                    {"uri": fields.get("uri", b"")},
                    f"no live replicas for {ttl:.1f}s — request "
                    f"expired unplaced")
                self._finish_entries(client, [eid])
            else:
                self._unrouted.append((fields, eid, parked))

    def _loop_router(self) -> None:
        """Router thread (``n_replicas > 1``): the SOLE claimer of the
        broker's consumer group — XREADGROUP as consumer "router" —
        placing each entry via ``_route_one``.  Short claim blocks keep
        the cancel fan-out and the dead-replica sweep responsive."""
        try:
            client = RespClient(self.config.redis_host,
                                self.config.redis_port)
        except OSError:
            logger.exception("router could not connect to the broker "
                             "— multi-replica serving not started")
            return
        try:
            while not self._stop.is_set():
                self._route_cancels(client)
                self._reroute_dead(client)
                self._supervise(client)
                try:
                    requests, ids = self._read_batch(client, "router",
                                                     20)
                except (ConnectionError, OSError):
                    if self._stop.is_set():
                        break
                    time.sleep(0.05)
                    continue
                for fields, eid in zip(requests, ids):
                    self._route_one(client, fields, eid)
        finally:
            client.close()

    def _finish_entries(self, client: RespClient, ids):
        """Ack + delete consumed stream entries (after their results —
        value or error — are published); one pipeline round-trip."""
        if not ids:
            return
        try:
            client.pipeline([("XACK", INPUT_STREAM, self.GROUP, *ids),
                             ("XDEL", INPUT_STREAM, *ids)])
        except Exception:
            logger.exception("serving ack failed")

    def _decode_value(self, v: bytes) -> np.ndarray:
        """One request field -> ndarray.  IMG! payloads are compressed
        image bytes: native C++ decode (GIL released, RGB-normalised) +
        optional resize to the configured model input shape; everything
        else is a dense tensor (b64 npy)."""
        if not v.startswith(IMG_MAGIC):
            arr = decode_ndarray(v)
            if arr.dtype.kind in "SUO":
                # a byte/object tensor can never feed a jitted model;
                # fail THIS request with the cause named instead of
                # crashing the whole batch at dispatch
                raise ValueError(
                    f"request field decodes to dtype {arr.dtype} — send "
                    f"numeric ndarrays, or ImageBytes/enqueue_image for "
                    f"encoded images")
            return arr
        from analytics_zoo_tpu.data.image import decode_image_bytes

        img = decode_image_bytes(v[len(IMG_MAGIC):])
        if self.config.image_shape:
            if self._img_resize is None:
                from analytics_zoo_tpu.data.image import ImageResize

                h, w = self.config.image_shape
                self._img_resize = ImageResize(int(h), int(w))
            img = self._img_resize(img)
        return img

    def _publish_error(self, req: Dict[str, bytes], msg: str):
        """One request failed decode/shape checks: publish an error result
        so its client fails fast instead of blocking to timeout.  (The
        stream entry is already consumed — without this the request would
        vanish.)"""
        try:
            uri = req["uri"].decode()
            self.client.pipeline([
                ("HSET", RESULT_PREFIX + uri, "error", msg[:500]),
                ("XADD", SIGNAL_PREFIX + uri, "*", "ok", "0"),
                # index it like a normal result so dequeue()-only clients
                # still observe (and consume) the failure
                ("SADD", "__result_keys__", uri)])
            with self._stats_lock:
                self._written.append((uri, time.monotonic()))
        except Exception:
            logger.exception("failed to publish serving error")

    def _dispatch_batch(self, client: RespClient,
                        requests: List[Dict[str, bytes]], ids: List[bytes]):
        """Decode + enqueue the forward on the device; returns the in-flight
        handle without blocking on the result.  Image payloads decode on a
        thread pool — the native decoder releases the GIL, so a batch of
        JPEGs decodes in parallel while the previous batch computes.
        A request that fails to decode (or whose shape disagrees with the
        batch) gets an ERROR result published and its entry finished; the
        rest of the batch still runs — one bad payload must never
        black-hole its batchmates."""
        # control fields are NEVER model inputs: discovered columns
        # treating e.g. a stray `prefix` id as a second input would make
        # pre_pad read it as per-row prompt lengths — silently wrong
        # generations.  The continuous pump honors these fields; the
        # batch path cannot (its one scan runs every row identically),
        # so a request carrying any of them error-publishes rather than
        # silently serving different semantics than asked for.
        control = {"prefix", "max_new", "temperature", "seed", "top_p"}
        cols = self.config.input_cols or \
            [k for k in requests[0] if k != "uri" and k not in control]
        # a model may LEGITIMATELY have an input named e.g.
        # "temperature" (explicit input_cols); only fields that are not
        # inputs count as controls here
        reject = control - set(cols)
        per_req: List[Optional[List[np.ndarray]]] = [None] * len(requests)

        def decode_req(i_req):
            i, r = i_req
            try:
                present = sorted(reject & set(r))
                if present:
                    raise ValueError(
                        f"per-request controls {present} need "
                        f"continuous_batching: true (the batch path "
                        f"runs every row identically)")
                per_req[i] = [self._decode_value(r[c]) for c in cols]
            except Exception as e:
                self._publish_error(r, f"decode failed: {e!r}")

        heavy = any(r.get(c, b"").startswith(IMG_MAGIC)
                    for r in requests for c in cols)
        items = list(enumerate(requests))
        if heavy and len(requests) >= 4:
            list(self._decode_pool.map(decode_req, items))
        else:
            for it in items:
                decode_req(it)
        # generative serving: ragged prompts right-pad to the batch max
        # BEFORE the shape check, and their true lengths ride along as an
        # extra model input (load_flax_generator contract)
        req_lengths: List[Optional[int]] = [None] * len(requests)
        prompts_active = bool(self.config.prompt_col) and \
            self.config.prompt_col in cols
        if prompts_active:
            ci = cols.index(self.config.prompt_col)
            # per-request bounds check FIRST — an over-long or empty
            # prompt must error alone, not (via the shared pad width)
            # black-hole its batchmates at dispatch
            limit = getattr(self.model, "max_prompt_width", None)
            for i, (r, v) in enumerate(zip(requests, per_req)):
                if v is None:
                    continue
                if np.asarray(v[ci]).ndim != 1:
                    # error it here, not via the generic shape check — a
                    # malformed prompt as the batch's first request would
                    # otherwise set ref_shapes and fail valid batchmates
                    self._publish_error(
                        r, f"prompt must be a 1-D token array, got shape "
                           f"{np.asarray(v[ci]).shape}")
                    per_req[i] = None
                    continue
                n = len(v[ci])
                if n < 1 or (limit is not None and n > limit):
                    self._publish_error(
                        r, f"prompt length {n} outside [1, {limit}]")
                    per_req[i] = None
            # every surviving row passed the 1-D check above, so each one
            # gets a recorded length here — dispatch relies on that
            widths = [len(v[ci]) for v in per_req if v is not None]
            wmax = max(widths) if widths else 0
            for i, v in enumerate(per_req):
                if v is None:
                    continue
                arr = np.asarray(v[ci])
                req_lengths[i] = len(arr)
                if len(arr) < wmax:
                    v[ci] = np.concatenate(
                        [arr, np.full(wmax - len(arr),
                                      self.config.prompt_pad_id,
                                      arr.dtype)])
        # shape check against the first good request: mismatches error out
        # individually instead of failing np.stack for everyone
        ref_shapes = next((tuple(a.shape for a in v)
                           for v in per_req if v is not None), None)
        good_reqs, good_ids, good_vals, good_lens, done_ids = \
            [], [], [], [], []
        for r, eid, v, ln in zip(requests, ids, per_req, req_lengths):
            if v is None:
                done_ids.append(eid)        # error already published
                continue
            if tuple(a.shape for a in v) != ref_shapes:
                self._publish_error(
                    r, f"input shape {[a.shape for a in v]} != batch "
                       f"shape {list(ref_shapes)}")
                done_ids.append(eid)
                continue
            good_reqs.append(r)
            good_ids.append(eid)
            good_vals.append(v)
            good_lens.append(ln)
        self._finish_entries(client, done_ids)
        if not good_reqs:
            return None
        arrays = [np.stack([v[ci] for v in good_vals])
                  for ci in range(len(cols))]
        if prompts_active:
            # every row that survived the prompt checks above has a
            # length; threading them unconditionally means the model never
            # falls back to re-inferring lengths from its own pad id
            assert all(ln is not None for ln in good_lens)
            arrays.append(np.asarray(good_lens, np.int32))
        try:
            waiter = self.model.predict_async(*arrays)
        except Exception as e:
            # dispatch itself failed (e.g. an incompatible hot-reloaded
            # model): the stream entries are already consumed, so every
            # request must get an error result, not a silent vanish
            logger.exception("serving model dispatch failed")
            for r in good_reqs:
                self._publish_error(r, f"model dispatch failed: {e!r}")
            self._finish_entries(client, good_ids)
            return None
        return good_reqs, good_ids, waiter, time.perf_counter()

    def _publish_batch(self, client: RespClient, requests, ids, waiter,
                       t0: float):
        preds = np.asarray(waiter())    # blocks until the device is done
        dt = (time.perf_counter() - t0) * 1000
        uris = [r["uri"].decode() for r in requests]
        cmds = []
        for uri, p in zip(uris, preds):
            cmds.append(("HSET", RESULT_PREFIX + uri,
                         "value", encode_ndarray(p)))
            # wake the XREAD-blocked client AFTER the hash is in place
            # (pipelined commands execute in order on the broker)
            cmds.append(("XADD", SIGNAL_PREFIX + uri, "*", "ok", "1"))
        # maintain the dequeue-all index (client OutputQueue.dequeue);
        # a set, pruned by the client on consume, so it stays bounded by
        # the number of UNREAD results rather than total requests served
        cmds.append(("SADD", "__result_keys__", *uris))
        client.pipeline(cmds)
        self._finish_entries(client, ids)   # results are visible: ack+del
        now = time.monotonic()
        with self._stats_lock:
            self._written.extend((u, now) for u in uris)
            self.stats["requests"] += len(requests)
            self.stats["batches"] += 1
            self.stats["batch_fill"] = len(requests) / self.config.batch_size
            self.stats["predict_ms"] = dt
        self._prune_abandoned(client, now)

    def _prune_abandoned(self, client: RespClient, now: float):
        """One pipeline round-trip per pruned uri, on the calling worker's
        own connection — pruning a TTL burst must not serialise every
        worker through the shared client's lock.  Each pruned result is
        counted (``zoo_serving_requests_abandoned_total``) and leaves a
        terminal ``request_abandoned`` event in the trace — a client
        that timed out and walked away used to vanish without a sign.

        Continuous mode also prunes IN-FLIGHT rows here: a request
        resident (or queued) in the engine longer than the ttl has no
        collector left, so it is aborted — the engine frees its slot
        and every KV block it holds, target AND draft pools alike for
        a speculative row — and its stream entry is acked so the group
        never redelivers dead work."""
        ttl = self.config.result_ttl_s
        engines = list(getattr(self, "engines", ()))
        if engines:
            with self._stats_lock:
                stale = [(u, te) for u, te in self._inflight.items()
                         if now - te[0] > ttl]
                for u, _ in stale:
                    del self._inflight[u]
            for u, (t_sub, eid) in stale:
                # False = the row completed in the race window; its
                # publish already handled the entry.  A uri lives in at
                # most ONE replica's engine, so any() stops there.
                if any(e.abort(u) for e in engines):
                    self.telemetry.req_abandoned(u, now - t_sub)
                    self._finish_entries(client, [eid])
                    # a streaming abandoner's token stream dies with it
                    try:
                        client.execute("DEL", TOKEN_PREFIX + u)
                    except Exception:
                        pass
        while True:
            with self._stats_lock:
                if not self._written or \
                        now - self._written[0][1] <= ttl:
                    return
                uri, written_at = self._written.popleft()
            client.pipeline([
                ("DEL", RESULT_PREFIX + uri, SIGNAL_PREFIX + uri,
                 TOKEN_PREFIX + uri),
                ("SREM", "__result_keys__", uri)])
            self.telemetry.req_abandoned(uri, now - written_at)

    def _drop_inflight(self, uri: str) -> None:
        with self._stats_lock:
            self._inflight.pop(uri, None)

    # ---- front door (serving/frontdoor.py) ----------------------------

    def stream_events(self, uri: str, timeout: float = 30.0,
                      poll_s: float = 1.0):
        """Tail a ``stream=True`` request's per-token stream — the
        Redis-queue analog of the HTTP SSE path (same events:
        token / done / cancelled / error, plus ping heartbeats).
        Opens its own broker connection so it can block without
        serialising the shared client."""
        outq = OutputQueue(self.config.redis_host, self.port)
        try:
            yield from outq.stream_events(uri, timeout=timeout,
                                          poll_s=poll_s)
        finally:
            outq.close()

    def cancel(self, uri: str) -> None:
        """Request live cancellation: the pump aborts the row on its
        next loop iteration, freeing both pool tenants' blocks
        immediately (vs. the ``result_ttl_s`` prune).  Idempotent;
        callable from any thread."""
        self.client.execute("XADD", CANCEL_STREAM, "*", "uri", uri)

    def mode_flags(self) -> Dict[str, bool]:
        """Engine mode booleans for /healthz: which serving features
        this job composed (the engine object is authoritative for
        speculation — it knows whether a draft actually loaded)."""
        eng = getattr(self, "engine", None)
        return {
            "continuous": bool(self.config.continuous_batching),
            "paged": bool(self.config.engine_paged),
            "chunked": bool(self.config.engine_chunked),
            "speculative": bool(
                eng is not None and
                getattr(eng, "draft_model", None) is not None),
            "qos": bool(self.config.qos_enabled),
            "brownout": bool(getattr(self.config, "brownout", False)),
        }

    # ---- observability (SURVEY §5: queue depth = backlog metric) ------

    def backlog(self) -> int:
        return int(self.client.execute("XLEN", INPUT_STREAM))

    def accepting_replicas(self) -> Optional[int]:
        """Live pump count for readiness checks, or ``None`` when pump
        liveness doesn't apply (micro-batch mode, or the job not yet
        started).  The HTTP front door treats only an explicit 0 as
        fleet-dead: /healthz flips ``accepting: false`` and submits
        503 with a finite Retry-After instead of accepting work that
        can never be placed."""
        if not self.config.continuous_batching or not self._threads:
            return None
        return sum(1 for v in self._pump_live if v)

"""Cluster Serving — continuous-batching TPU inference service.

Reference surface (SURVEY.md §2.6, §3.5; ref: serving/ClusterServing.scala,
serving/engine/ClusterServingInference.scala, ClusterServingHelper.scala):
a Flink job XREADGROUPs the Redis input stream, micro-batches by size/
timeout, runs InferenceModel, XADDs results; config.yaml drives model path,
batch size, redis address.

TPU re-design: no Flink — ONE host thread owns the serving loop (queue →
micro-batcher → bucketed-pad → jitted forward → result hashes). The TPU's
own pipelining replaces Flink operator parallelism: while step N computes
on device, step N+1 is being batched on host. Backpressure = stream length
(the reference's de-facto backlog metric, SURVEY §5); fixed jit shapes come
from InferenceModel's bucket cache.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.common.log import logger
from analytics_zoo_tpu.learn.inference_model import InferenceModel
from analytics_zoo_tpu.serving.queues import (
    IMG_MAGIC, INPUT_STREAM, RESULT_PREFIX, SIGNAL_PREFIX, decode_ndarray,
    encode_ndarray)
from analytics_zoo_tpu.serving.resp import RespClient, RespServer


@dataclasses.dataclass
class ServingConfig:
    """config.yaml parity (ref: ClusterServingHelper field names)."""

    model_path: str = ""
    redis_host: str = "127.0.0.1"
    redis_port: int = 6379
    batch_size: int = 32            # micro-batch cap
    batch_timeout_ms: float = 5.0   # flush partial batch after this wait
    input_cols: Optional[List[str]] = None  # None: infer from request
    image_shape: Optional[List[int]] = None  # (H, W): resize decoded
    #                                          image payloads to the model
    #                                          input (ref: serving image
    #                                          resize per model config)
    result_ttl_s: float = 300.0     # abandoned results pruned after this
    core_number: Optional[int] = None   # ref: host CPU cores per serving
    #                                     task — here it caps concurrent
    #                                     host staging (InferenceModel
    #                                     semaphore), NOT batch; None keeps
    #                                     the model's own concurrent_num

    @staticmethod
    def from_yaml(path: str) -> "ServingConfig":
        import yaml

        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        params = raw.get("params") or {}
        redis_raw = raw.get("redis") or {}
        redis = redis_raw.get("src", redis_raw.get("url", ""))
        cfg = ServingConfig()
        model = raw.get("model", {})
        if isinstance(model, dict):
            cfg.model_path = model.get("path", "")
        if isinstance(redis, str) and ":" in redis:
            host, port = redis.rsplit(":", 1)
            cfg.redis_host, cfg.redis_port = host, int(port)
        # reference config.yaml semantics: core_number is CPU cores (a
        # resource knob), batch_size is the micro-batch — never conflate
        cfg.batch_size = int(params.get("batch_size", 32))
        if "core_number" in params:
            cfg.core_number = int(params["core_number"])
        if "image_shape" in params:
            cfg.image_shape = [int(v) for v in params["image_shape"]]
        return cfg


class ClusterServing:
    """The serving job. Optionally owns an embedded RESP broker.

    Usage:
      serving = ClusterServing(model, config, embedded_broker=True).start()
      InputQueue(port=serving.port).enqueue(...)
    """

    def __init__(self, inference_model: InferenceModel,
                 config: Optional[ServingConfig] = None,
                 embedded_broker: bool = False):
        self.model = inference_model
        self.config = config or ServingConfig()
        if self.config.core_number is not None:
            inference_model.set_concurrency(self.config.core_number)
        self.broker: Optional[RespServer] = None
        if embedded_broker:
            self.broker = RespServer(port=0).start()
            self.config.redis_host = "127.0.0.1"
            self.config.redis_port = self.broker.port
        self.port = self.config.redis_port
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_id = b"0-0"
        # (uri, written_at) of results not yet known consumed — abandoned
        # ones (client timed out / died) are pruned after result_ttl_s so
        # broker memory stays bounded in long-lived deployments
        self._written: collections.deque = collections.deque()
        self.stats = {"requests": 0, "batches": 0, "batch_fill": 0.0,
                      "predict_ms": 0.0}
        self._img_resize = None
        from concurrent.futures import ThreadPoolExecutor
        import os as _os

        self._decode_pool = ThreadPoolExecutor(
            max_workers=min(8, _os.cpu_count() or 4),
            thread_name_prefix="zoo-serving-decode")

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "ClusterServing":
        self.client = RespClient(self.config.redis_host,
                                 self.config.redis_port)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        logger.info("ClusterServing up (redis %s:%d, batch<=%d)",
                    self.config.redis_host, self.config.redis_port,
                    self.config.batch_size)
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.broker is not None:
            self.broker.stop()
        self._decode_pool.shutdown(wait=False)

    def reload_model(self, inference_model: InferenceModel
                     ) -> "ClusterServing":
        """Hot-swap the served model without stopping the loop (ref:
        ClusterServingHelper model hot-load from config).  The swap is one
        attribute assignment — the loop reads ``self.model`` once per
        dispatch, so in-flight batches finish on the old model and the
        next batch runs the new one; no request is dropped."""
        if self.config.core_number is not None:
            inference_model.set_concurrency(self.config.core_number)
        self.model = inference_model
        logger.info("ClusterServing model hot-reloaded")
        return self

    # ---- serving loop -------------------------------------------------

    def _read_batch(self, block_ms: int = 200) -> List[Dict[str, bytes]]:
        """Micro-batch: block up to block_ms for the first request, then
        grab whatever else is queued up to batch_size within
        batch_timeout_ms.  With a batch already in flight on the device the
        loop passes a tiny block_ms so finished results are written
        promptly instead of waiting out a full idle poll."""
        cfg = self.config
        first = self.client.execute(
            "XREAD", "COUNT", cfg.batch_size, "BLOCK", block_ms, "STREAMS",
            INPUT_STREAM, self._last_id)
        if not first:
            return []
        entries = first[0][1]
        deadline = time.monotonic() + cfg.batch_timeout_ms / 1000.0
        while len(entries) < cfg.batch_size:
            wait_ms = int(max(0, (deadline - time.monotonic()) * 1000))
            if wait_ms <= 0:
                break
            more = self.client.execute(
                "XREAD", "COUNT", cfg.batch_size - len(entries), "BLOCK",
                wait_ms, "STREAMS", INPUT_STREAM, entries[-1][0])
            if not more:
                break
            entries.extend(more[0][1])
        self._last_id = entries[-1][0]
        out = []
        for eid, flat in entries:
            fields = {flat[i].decode(): flat[i + 1]
                      for i in range(0, len(flat), 2)}
            out.append(fields)
        # delete exactly the consumed entries (by id) so XLEN == pending
        # backlog; MAXLEN-style trimming would race concurrent producers
        # and could drop entries that were never read
        self.client.execute("XDEL", INPUT_STREAM,
                            *[eid for eid, _ in entries])
        return out

    def _loop(self):
        """Pipelined serving loop: while batch N computes on the TPU, batch
        N+1 is read from the stream and decoded on the host (XLA dispatch
        is async; blocking happens only when N's results are written)."""
        pending = None          # (requests, waiter, dispatched_at)
        while not self._stop.is_set():
            try:
                # with work in flight, poll briefly so finished results are
                # published as soon as the device is done
                requests = self._read_batch(2 if pending else 200)
            except (ConnectionError, OSError):
                if self._stop.is_set():
                    break
                time.sleep(0.05)
                continue
            nxt = None
            if requests:
                try:
                    nxt = self._dispatch_batch(requests)
                except Exception:
                    logger.exception("serving dispatch failed")
            if pending is not None:
                try:
                    self._publish_batch(*pending)
                except Exception:
                    logger.exception("serving publish failed")
            pending = nxt
        if pending is not None:
            try:
                self._publish_batch(*pending)
            except Exception:
                logger.exception("serving publish failed")

    def _decode_value(self, v: bytes) -> np.ndarray:
        """One request field -> ndarray.  IMG! payloads are compressed
        image bytes: native C++ decode (GIL released, RGB-normalised) +
        optional resize to the configured model input shape; everything
        else is a dense tensor (b64 npy)."""
        if not v.startswith(IMG_MAGIC):
            return decode_ndarray(v)
        from analytics_zoo_tpu.data.image import decode_image_bytes

        img = decode_image_bytes(v[len(IMG_MAGIC):])
        if self.config.image_shape:
            if self._img_resize is None:
                from analytics_zoo_tpu.data.image import ImageResize

                h, w = self.config.image_shape
                self._img_resize = ImageResize(int(h), int(w))
            img = self._img_resize(img)
        return img

    def _publish_error(self, req: Dict[str, bytes], msg: str):
        """One request failed decode/shape checks: publish an error result
        so its client fails fast instead of blocking to timeout.  (The
        stream entry is already consumed — without this the request would
        vanish.)"""
        try:
            uri = req["uri"].decode()
            self.client.pipeline([
                ("HSET", RESULT_PREFIX + uri, "error", msg[:500]),
                ("XADD", SIGNAL_PREFIX + uri, "*", "ok", "0"),
                # index it like a normal result so dequeue()-only clients
                # still observe (and consume) the failure
                ("SADD", "__result_keys__", uri)])
            self._written.append((uri, time.monotonic()))
        except Exception:
            logger.exception("failed to publish serving error")

    def _dispatch_batch(self, requests: List[Dict[str, bytes]]):
        """Decode + enqueue the forward on the device; returns the in-flight
        handle without blocking on the result.  Image payloads decode on a
        thread pool — the native decoder releases the GIL, so a batch of
        JPEGs decodes in parallel while the previous batch computes.
        A request that fails to decode (or whose shape disagrees with the
        batch) gets an ERROR result published; the rest of the batch still
        runs — one bad payload must never black-hole its batchmates."""
        cols = self.config.input_cols or \
            [k for k in requests[0] if k != "uri"]
        per_req: List[Optional[List[np.ndarray]]] = [None] * len(requests)

        def decode_req(i_req):
            i, r = i_req
            try:
                per_req[i] = [self._decode_value(r[c]) for c in cols]
            except Exception as e:
                self._publish_error(r, f"decode failed: {e!r}")

        heavy = any(r.get(c, b"").startswith(IMG_MAGIC)
                    for r in requests for c in cols)
        items = list(enumerate(requests))
        if heavy and len(requests) >= 4:
            list(self._decode_pool.map(decode_req, items))
        else:
            for it in items:
                decode_req(it)
        # shape check against the first good request: mismatches error out
        # individually instead of failing np.stack for everyone
        ref_shapes = next((tuple(a.shape for a in v)
                           for v in per_req if v is not None), None)
        good_reqs, good_vals = [], []
        for r, v in zip(requests, per_req):
            if v is None:
                continue
            if tuple(a.shape for a in v) != ref_shapes:
                self._publish_error(
                    r, f"input shape {[a.shape for a in v]} != batch "
                       f"shape {list(ref_shapes)}")
                continue
            good_reqs.append(r)
            good_vals.append(v)
        if not good_reqs:
            return None
        arrays = [np.stack([v[ci] for v in good_vals])
                  for ci in range(len(cols))]
        try:
            waiter = self.model.predict_async(*arrays)
        except Exception as e:
            # dispatch itself failed (e.g. an incompatible hot-reloaded
            # model): the stream entries are already consumed, so every
            # request must get an error result, not a silent vanish
            logger.exception("serving model dispatch failed")
            for r in good_reqs:
                self._publish_error(r, f"model dispatch failed: {e!r}")
            return None
        return good_reqs, waiter, time.perf_counter()

    def _publish_batch(self, requests, waiter, t0: float):
        preds = np.asarray(waiter())    # blocks until the device is done
        dt = (time.perf_counter() - t0) * 1000
        uris = [r["uri"].decode() for r in requests]
        cmds = []
        for uri, p in zip(uris, preds):
            cmds.append(("HSET", RESULT_PREFIX + uri,
                         "value", encode_ndarray(p)))
            # wake the XREAD-blocked client AFTER the hash is in place
            # (pipelined commands execute in order on the broker)
            cmds.append(("XADD", SIGNAL_PREFIX + uri, "*", "ok", "1"))
        # maintain the dequeue-all index (client OutputQueue.dequeue);
        # a set, pruned by the client on consume, so it stays bounded by
        # the number of UNREAD results rather than total requests served
        cmds.append(("SADD", "__result_keys__", *uris))
        self.client.pipeline(cmds)
        now = time.monotonic()
        self._written.extend((u, now) for u in uris)
        self._prune_abandoned(now)
        self.stats["requests"] += len(requests)
        self.stats["batches"] += 1
        self.stats["batch_fill"] = len(requests) / self.config.batch_size
        self.stats["predict_ms"] = dt

    def _prune_abandoned(self, now: float):
        ttl = self.config.result_ttl_s
        while self._written and now - self._written[0][1] > ttl:
            uri, _ = self._written.popleft()
            self.client.execute("DEL", RESULT_PREFIX + uri,
                                SIGNAL_PREFIX + uri)
            self.client.execute("SREM", "__result_keys__", uri)

    # ---- observability (SURVEY §5: queue depth = backlog metric) ------

    def backlog(self) -> int:
        return int(self.client.execute("XLEN", INPUT_STREAM))

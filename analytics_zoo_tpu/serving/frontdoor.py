"""QoS front door for the serving stack: the policy + plumbing pieces
that turn the engine's primitives (``abort``, per-token emissions, the
token-budget scheduler) into a production-shaped ingress.

Four pillars live here (docs/serving_qos.md):

* **Priority classes + per-tenant fair share** — ``QosPolicy`` names
  the three classes and their weights; ``WeightedWaitQueue`` is a
  drop-in replacement for the engine's plain waiting ``deque`` that
  pops in weighted stride-scheduling order over (priority class,
  tenant) subqueues, with aging promoting starved batch work.
* **Per-token streaming** — ``TokenEmitter`` is the bounded per-request
  emission queue between the engine's pump-thread ``on_token`` hook and
  the wire: the pump drains it once per ``step()`` and publishes every
  buffered token in ONE Redis pipeline (never a per-token round trip,
  never a device sync).
* **Backpressure** — ``retry_after_s`` / ``ThroughputEstimator`` turn
  queue depth + recent completion throughput into the finite
  ``Retry-After`` a 429 must carry.
* **Wire codecs** — the input queue transports ndarrays only (a str
  field is a client bug it rejects loudly), so the control fields the
  front door adds travel encoded: ``priority`` as an int32 index into
  ``PRIORITIES``, ``tenant`` as a uint8 byte array
  (``encode_str_field``/``decode_str_field``), ``stream`` as an int32
  flag.  ``sse_event`` formats the HTTP frontend's
  ``text/event-stream`` chunks.

This module is imported by ``continuous.py`` (scheduler swap-in), so it
must stay dependency-light: stdlib + numpy only, no jax, no imports
from the rest of the serving package.
"""

from __future__ import annotations

import collections
import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

#: Priority classes, best-first.  The wire encodes a priority as its
#: index in this tuple (the input queue transports ints, not strings);
#: aging promotes a waiting request one index at a time toward 0.
PRIORITIES: Tuple[str, ...] = ("interactive", "standard", "batch")

DEFAULT_WEIGHTS: Dict[str, float] = {
    "interactive": 8.0, "standard": 4.0, "batch": 1.0}


@dataclass(frozen=True)
class QosPolicy:
    """Admission policy knobs: per-class weights and the aging bound.

    ``weights`` are stride-scheduling shares — a class with weight 8
    gets ~8x the admission slots of weight 1 under contention, it does
    NOT strictly preempt it.  ``aging_s`` is the starvation bound: a
    request that has waited ``aging_s`` is treated as one class better
    (both for its subqueue's stride and for prefill-grant ordering),
    two intervals promotes two classes, so batch work can wait at most
    ``2 * aging_s`` before it competes as interactive.  ``aging_s <= 0``
    disables promotion (weights alone still prevent total starvation:
    a never-popped subqueue's virtual pass stands still while every
    other queue's advances, so it eventually holds the minimum)."""

    weights: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_WEIGHTS))
    aging_s: float = 30.0

    def __post_init__(self):
        for cls in PRIORITIES:
            w = self.weights.get(cls, DEFAULT_WEIGHTS[cls])
            if w <= 0:
                raise ValueError(f"qos weight for {cls!r} must be > 0, "
                                 f"got {w}")
            self.weights.setdefault(cls, DEFAULT_WEIGHTS[cls])

    def class_rank(self, priority: str, waited_s: float) -> int:
        """Aged class index (0 best).  Unknown priorities rank as
        ``standard`` rather than raising — the pump must never die on a
        stale wire value."""
        try:
            idx = PRIORITIES.index(priority)
        except ValueError:
            idx = PRIORITIES.index("standard")
        if self.aging_s > 0 and waited_s > 0:
            idx -= int(waited_s // self.aging_s)
        return max(0, idx)

    def effective_weight(self, priority: str, waited_s: float) -> float:
        return self.weights[PRIORITIES[self.class_rank(priority,
                                                       waited_s)]]


class WeightedWaitQueue:
    """Weighted deficit/stride scheduler over (priority class, tenant)
    FIFO subqueues, exposing the exact ``collections.deque`` surface
    the engine uses for ``self._waiting`` (``append`` / ``appendleft``
    / ``popleft`` / ``remove`` / iteration / ``len``) so QoS admission
    is a constructor-time swap, not a call-site rewrite.

    Entries are the engine's ``_Req`` tuples; the scheduler reads only
    their ``priority`` / ``tenant`` / ``enq_t`` attributes (absent
    attributes degrade to standard/shared/now).  Each subqueue carries
    a virtual ``pass``; ``popleft`` serves the minimum-pass nonempty
    subqueue and advances its pass by ``1 / effective_weight`` — equal
    passes per unit work means admission slots divide proportionally to
    weight across classes and EQUALLY across tenants inside a class
    (each (class, tenant) pair is its own subqueue at the class
    weight).  Aging shrinks a promoted subqueue's stride, so a starved
    batch tenant catches up instead of merely not falling further
    behind.

    ``appendleft`` is the engine's requeue path (preemption, blocked
    admission): the entry returns to the FRONT of its own subqueue and
    the pop's stride charge is refunded, so bouncing off a full pool
    costs a tenant nothing.  All call sites run under the engine lock —
    no internal locking.
    """

    def __init__(self, policy: QosPolicy):
        self.policy = policy
        self._queues: "collections.OrderedDict[Tuple[str, str], collections.deque]" = \
            collections.OrderedDict()
        self._pass: Dict[Tuple[str, str], float] = {}
        self._clock = 0.0
        self._charges: Dict[int, Tuple[Tuple[str, str], float]] = {}
        self._n = 0

    @staticmethod
    def _key(req) -> Tuple[str, str]:
        return (getattr(req, "priority", "standard"),
                getattr(req, "tenant", ""))

    def _subqueue(self, req) -> collections.deque:
        key = self._key(req)
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = collections.deque()
        if not q:
            # (re)arming an idle subqueue: clamp its pass to the global
            # virtual clock, or a long-idle tenant would bank credit
            # and burst past everyone on return
            self._pass[key] = max(self._pass.get(key, 0.0), self._clock)
        return q

    def append(self, req) -> None:
        self._subqueue(req).append(req)
        self._n += 1

    def appendleft(self, req) -> None:
        self._subqueue(req).appendleft(req)
        self._n += 1
        ent = self._charges.pop(id(req), None)
        if ent is not None:
            key, prior_pass = ent
            if key == self._key(req):
                self._pass[key] = prior_pass    # requeue is cost-neutral

    def popleft(self):
        if self._n == 0:
            raise IndexError("pop from an empty WeightedWaitQueue")
        now = time.monotonic()
        best_key = None
        best_rank: Optional[Tuple[float, float]] = None
        for key, q in self._queues.items():
            if not q:
                continue
            pv = self._pass[key]
            rank = (pv, getattr(q[0], "enq_t", now))
            if best_rank is None or rank < best_rank:
                best_key, best_rank = key, rank
        q = self._queues[best_key]
        req = q.popleft()
        self._n -= 1
        pv = self._pass[best_key]
        self._clock = max(self._clock, pv)
        waited = now - getattr(req, "enq_t", now)
        self._pass[best_key] = pv + 1.0 / self.policy.effective_weight(
            best_key[0], waited)
        if len(self._charges) > 4096:   # requeues long consumed
            self._charges.clear()
        self._charges[id(req)] = (best_key, pv)
        return req

    def remove(self, req) -> None:
        key = self._key(req)
        q = self._queues.get(key)
        if q is None:
            raise ValueError("WeightedWaitQueue.remove(x): x not in queue")
        q.remove(req)       # raises ValueError like deque when absent
        self._n -= 1

    def __iter__(self):
        for q in self._queues.values():
            yield from q

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def depths(self) -> Dict[Tuple[str, str], int]:
        """Per-(class, tenant) backlog snapshot (telemetry food)."""
        return {k: len(q) for k, q in self._queues.items() if q}


class TokenEmitter:
    """Bounded per-request emission buffer between the engine's
    ``on_token`` hook and the wire.

    ``emit`` runs inside ``engine.step()`` on the pump thread and does
    two list appends — no Redis I/O, no locks, no device syncs, so the
    hot decode loop's cost profile is unchanged.  After each ``step()``
    the pump calls ``drain()`` and publishes everything in one
    pipeline.  Terminal markers (``finish``/``error``/``cancelled``)
    ride the same per-request buffer, so a request's final tokens are
    always published BEFORE its done marker even though ``on_done``
    fires mid-step.

    The per-request bound is the engine's ``max_new`` ceiling plus the
    terminal marker — the buffer structurally cannot outgrow it between
    drains; ``max_events`` is a belt-and-suspenders cap (oldest events
    drop, which a bound this size never triggers in practice)."""

    def __init__(self, max_events: int = 8192):
        self.max_events = int(max_events)
        self._buf: "collections.OrderedDict[str, collections.deque]" = \
            collections.OrderedDict()
        self.dropped = 0

    def _events(self, uri: str) -> collections.deque:
        q = self._buf.get(uri)
        if q is None:
            q = self._buf[uri] = collections.deque()
        return q

    def emit(self, uri: str, token: int, index: int) -> None:
        """Engine ``on_token`` hook (pump thread, mid-step)."""
        q = self._events(uri)
        if len(q) >= self.max_events:
            q.popleft()
            self.dropped += 1
        q.append(("tok", index, token))

    def finish(self, uri: str) -> None:
        self._events(uri).append(("done", 0, 0))

    def error(self, uri: str, message: str) -> None:
        self._events(uri).append(("error", 0, message))

    def cancelled(self, uri: str) -> None:
        self._events(uri).append(("cancelled", 0, 0))

    def discard(self, uri: str) -> None:
        self._buf.pop(uri, None)

    def drain(self) -> List[Tuple[str, List[tuple]]]:
        """Take everything buffered since the last drain, in emission
        order per request."""
        if not self._buf:
            return []
        out = [(uri, list(q)) for uri, q in self._buf.items() if q]
        self._buf.clear()
        return out


class ThroughputEstimator:
    """EWMA completions/sec from a cumulative finished counter —
    ``Retry-After`` needs a recent-throughput denominator, and sampling
    the counter the engine already increments costs nothing.  Returns
    ``fallback_rate`` until two observations exist (a cold or idle
    server must still send a FINITE Retry-After)."""

    def __init__(self, fallback_rate: float = 4.0, alpha: float = 0.3):
        self.fallback_rate = float(fallback_rate)
        self.alpha = float(alpha)
        self._last: Optional[Tuple[float, float]] = None
        self._rate = 0.0

    def observe(self, total_finished: float,
                now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self._last is not None:
            dt = now - self._last[1]
            if dt > 0:
                inst = max(0.0, total_finished - self._last[0]) / dt
                self._rate = (inst if self._rate == 0.0 else
                              self.alpha * inst +
                              (1 - self.alpha) * self._rate)
        self._last = (float(total_finished), now)

    def rate(self) -> float:
        return self._rate if self._rate > 0 else self.fallback_rate


def retry_after_s(depth: int, rate: float, lo: float = 1.0,
                  hi: float = 120.0) -> int:
    """Seconds a 429'd client should wait: queue depth over recent
    completion throughput, clamped to ``[lo, hi]`` so the header is
    always finite and never tells a client to hammer back instantly."""
    if rate <= 0:
        return int(hi)
    return int(min(hi, max(lo, float(depth) / rate)))


# ---- wire codecs ------------------------------------------------------

def encode_str_field(s: str) -> np.ndarray:
    """A string control field as the uint8 byte array the input queue
    transports (it rejects str/bytes fields by design)."""
    return np.frombuffer(s.encode("utf-8"), np.uint8).copy()


def decode_str_field(a) -> str:
    return bytes(np.asarray(a, np.uint8).reshape(-1).tolist()) \
        .decode("utf-8", "replace")


def encode_priority(priority: str) -> np.ndarray:
    try:
        return np.int32(PRIORITIES.index(priority))
    except ValueError:
        raise ValueError(
            f"priority must be one of {PRIORITIES}, got {priority!r}")


def decode_priority(v) -> str:
    idx = int(np.asarray(v).reshape(-1)[0])
    if not 0 <= idx < len(PRIORITIES):
        return "standard"
    return PRIORITIES[idx]


def sse_event(event: str, data: dict) -> bytes:
    """One ``text/event-stream`` frame (docs/serving_qos.md wire
    format)."""
    return (f"event: {event}\ndata: "
            f"{json.dumps(data, separators=(',', ':'))}\n\n"
            ).encode("utf-8")


# request ids travel through queue field names, log lines, span args,
# and response headers — keep the accepted alphabet boring enough that
# none of those surfaces needs escaping
_REQUEST_ID_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    "-_.:")


def normalize_request_id(value) -> Optional[str]:
    """A client-supplied ``X-Request-Id`` as a usable request uri, or
    None when it is absent/empty/oversized/outside the safe alphabet
    (the frontend then falls back to a generated uuid — a bad header
    never rejects the request, it just loses client-side
    correlation)."""
    if not isinstance(value, str):
        return None
    value = value.strip()
    if not value or len(value) > 128:
        return None
    if not all(c in _REQUEST_ID_CHARS for c in value):
        return None
    return value

"""QoS front door for the serving stack: the policy + plumbing pieces
that turn the engine's primitives (``abort``, per-token emissions, the
token-budget scheduler) into a production-shaped ingress.

Four pillars live here (docs/serving_qos.md):

* **Priority classes + per-tenant fair share** — ``QosPolicy`` names
  the three classes and their weights; ``WeightedWaitQueue`` is a
  drop-in replacement for the engine's plain waiting ``deque`` that
  pops in weighted stride-scheduling order over (priority class,
  tenant) subqueues, with aging promoting starved batch work.  Both
  now LIVE in ``serving/policy.py`` (the pure scheduler-policy module
  the discrete-event simulator shares — docs/simulation.md) and are
  re-exported here unchanged.
* **Per-token streaming** — ``TokenEmitter`` is the bounded per-request
  emission queue between the engine's pump-thread ``on_token`` hook and
  the wire: the pump drains it once per ``step()`` and publishes every
  buffered token in ONE Redis pipeline (never a per-token round trip,
  never a device sync).
* **Backpressure** — ``retry_after_s`` / ``ThroughputEstimator`` turn
  queue depth + recent completion throughput into the finite
  ``Retry-After`` a 429 must carry.
* **Wire codecs** — the input queue transports ndarrays only (a str
  field is a client bug it rejects loudly), so the control fields the
  front door adds travel encoded: ``priority`` as an int32 index into
  ``PRIORITIES``, ``tenant`` as a uint8 byte array
  (``encode_str_field``/``decode_str_field``), ``stream`` as an int32
  flag.  ``sse_event`` formats the HTTP frontend's
  ``text/event-stream`` chunks.

This module is imported by ``continuous.py`` (scheduler swap-in), so it
must stay dependency-light: stdlib + numpy + ``serving/policy.py``
only, no jax, no imports from the rest of the serving package.
"""

from __future__ import annotations

import collections
import json
import math
import time
from typing import List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.serving.policy import (  # noqa: F401 (re-export)
    DEFAULT_WEIGHTS, PRIORITIES, QosPolicy, WeightedWaitQueue)


class TokenEmitter:
    """Bounded per-request emission buffer between the engine's
    ``on_token`` hook and the wire.

    ``emit`` runs inside ``engine.step()`` on the pump thread and does
    two list appends — no Redis I/O, no locks, no device syncs, so the
    hot decode loop's cost profile is unchanged.  After each ``step()``
    the pump calls ``drain()`` and publishes everything in one
    pipeline.  Terminal markers (``finish``/``error``/``cancelled``)
    ride the same per-request buffer, so a request's final tokens are
    always published BEFORE its done marker even though ``on_done``
    fires mid-step.

    The per-request bound is the engine's ``max_new`` ceiling plus the
    terminal marker — the buffer structurally cannot outgrow it between
    drains; ``max_events`` is a belt-and-suspenders cap (oldest events
    drop, which a bound this size never triggers in practice)."""

    def __init__(self, max_events: int = 8192):
        self.max_events = int(max_events)
        self._buf: "collections.OrderedDict[str, collections.deque]" = \
            collections.OrderedDict()
        self.dropped = 0

    def _events(self, uri: str) -> collections.deque:
        q = self._buf.get(uri)
        if q is None:
            q = self._buf[uri] = collections.deque()
        return q

    def emit(self, uri: str, token: int, index: int) -> None:
        """Engine ``on_token`` hook (pump thread, mid-step)."""
        q = self._events(uri)
        if len(q) >= self.max_events:
            q.popleft()
            self.dropped += 1
        q.append(("tok", index, token))

    def finish(self, uri: str) -> None:
        self._events(uri).append(("done", 0, 0))

    def error(self, uri: str, message: str) -> None:
        self._events(uri).append(("error", 0, message))

    def cancelled(self, uri: str) -> None:
        self._events(uri).append(("cancelled", 0, 0))

    def discard(self, uri: str) -> None:
        self._buf.pop(uri, None)

    def drain(self) -> List[Tuple[str, List[tuple]]]:
        """Take everything buffered since the last drain, in emission
        order per request."""
        if not self._buf:
            return []
        out = [(uri, list(q)) for uri, q in self._buf.items() if q]
        self._buf.clear()
        return out


class ThroughputEstimator:
    """EWMA completions/sec from a cumulative finished counter —
    ``Retry-After`` needs a recent-throughput denominator, and sampling
    the counter the engine already increments costs nothing.  Returns
    ``fallback_rate`` until two observations exist (a cold or idle
    server must still send a FINITE Retry-After)."""

    def __init__(self, fallback_rate: float = 4.0, alpha: float = 0.3):
        self.fallback_rate = float(fallback_rate)
        self.alpha = float(alpha)
        self._last: Optional[Tuple[float, float]] = None
        self._rate = 0.0

    def observe(self, total_finished: float,
                now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self._last is not None:
            dt = now - self._last[1]
            if dt > 0:
                inst = max(0.0, total_finished - self._last[0]) / dt
                self._rate = (inst if self._rate == 0.0 else
                              self.alpha * inst +
                              (1 - self.alpha) * self._rate)
        self._last = (float(total_finished), now)

    def rate(self) -> float:
        return self._rate if self._rate > 0 else self.fallback_rate


def retry_after_s(depth: int, rate: float, lo: float = 1.0,
                  hi: float = 120.0, level: int = 0) -> int:
    """Seconds a 429'd client should wait: queue depth over recent
    completion throughput, clamped to ``[lo, hi]`` so the header is
    always finite and never tells a client to hammer back instantly.
    ``level`` is the brownout ladder level: each level scales the
    pre-clamp estimate by one extra multiple, so the hint is monotone
    non-decreasing as degradation deepens (a shed class should back
    off LONGER than a merely-queued one) while the ``hi`` clamp keeps
    even level-4 finite."""
    if rate <= 0:
        return int(hi)
    base = float(depth) / rate * (1 + max(0, int(level)))
    return int(min(hi, max(lo, base)))


# ---- request deadlines (docs/serving_qos.md "Overload & brownout") ----

#: Deadlines past 24h are a client bug (an absolute timestamp sent
#: where a relative budget belongs, a ms/s unit mix-up), not patience.
MAX_DEADLINE_MS = 24 * 3600 * 1000


def validate_deadline_ms(value) -> int:
    """A client-supplied deadline budget (``X-Request-Deadline-Ms``
    header or ``deadline_ms`` body field): milliseconds from now.
    Returns the validated integer budget; raises ``ValueError`` (the
    front door's 400 path) with a pointed message on anything
    non-numeric, non-finite, non-positive, or past the 24h ceiling."""
    if isinstance(value, bool):
        raise ValueError(
            f"deadline_ms must be a number of milliseconds, "
            f"got {value!r}")
    try:
        f = float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"deadline_ms must be a number of milliseconds, "
            f"got {value!r}")
    if math.isnan(f) or math.isinf(f):
        raise ValueError(
            f"deadline_ms must be finite, got {value!r}")
    if f <= 0:
        raise ValueError(
            f"deadline_ms must be > 0 (milliseconds from now), "
            f"got {value!r}")
    if f > MAX_DEADLINE_MS:
        raise ValueError(
            f"deadline_ms {value!r} exceeds the 24h ceiling "
            f"({MAX_DEADLINE_MS} ms) — send a relative budget, not an "
            f"absolute timestamp")
    return int(f)


def encode_deadline(deadline_ms, now_wall: Optional[float] = None
                    ) -> np.ndarray:
    """Validated relative budget -> the int64 ABSOLUTE unix wall-clock
    millisecond the input queue transports.  Wall clock (not monotonic)
    because the queue entry crosses process boundaries; the consumer
    converts back to its own monotonic domain at decode."""
    ms = validate_deadline_ms(deadline_ms)
    now_wall = time.time() if now_wall is None else now_wall
    return np.int64(int(now_wall * 1000.0) + ms)


def decode_deadline(v, now_wall: Optional[float] = None,
                    now_mono: Optional[float] = None) -> float:
    """Wire deadline (absolute wall-clock ms) -> the engine-side
    ``deadline_t`` in the consumer's ``time.monotonic`` domain
    (seconds).  0.0 means no deadline; an already-passed wall time
    yields a ``deadline_t`` in the past, which admission sheds."""
    wall_ms = int(np.asarray(v).reshape(-1)[0])
    if wall_ms <= 0:
        return 0.0
    now_wall = time.time() if now_wall is None else now_wall
    now_mono = time.monotonic() if now_mono is None else now_mono
    return now_mono + (wall_ms / 1000.0 - now_wall)


# ---- wire codecs ------------------------------------------------------

def encode_str_field(s: str) -> np.ndarray:
    """A string control field as the uint8 byte array the input queue
    transports (it rejects str/bytes fields by design)."""
    return np.frombuffer(s.encode("utf-8"), np.uint8).copy()


def decode_str_field(a) -> str:
    return bytes(np.asarray(a, np.uint8).reshape(-1).tolist()) \
        .decode("utf-8", "replace")


def encode_priority(priority: str) -> np.ndarray:
    try:
        return np.int32(PRIORITIES.index(priority))
    except ValueError:
        raise ValueError(
            f"priority must be one of {PRIORITIES}, got {priority!r}")


def decode_priority(v) -> str:
    idx = int(np.asarray(v).reshape(-1)[0])
    if not 0 <= idx < len(PRIORITIES):
        return "standard"
    return PRIORITIES[idx]


def sse_event(event: str, data: dict) -> bytes:
    """One ``text/event-stream`` frame (docs/serving_qos.md wire
    format)."""
    return (f"event: {event}\ndata: "
            f"{json.dumps(data, separators=(',', ':'))}\n\n"
            ).encode("utf-8")


# request ids travel through queue field names, log lines, span args,
# and response headers — keep the accepted alphabet boring enough that
# none of those surfaces needs escaping
_REQUEST_ID_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    "-_.:")


def normalize_request_id(value) -> Optional[str]:
    """A client-supplied ``X-Request-Id`` as a usable request uri, or
    None when it is absent/empty/oversized/outside the safe alphabet
    (the frontend then falls back to a generated uuid — a bad header
    never rejects the request, it just loses client-side
    correlation)."""
    if not isinstance(value, str):
        return None
    value = value.strip()
    if not value or len(value) > 128:
        return None
    if not all(c in _REQUEST_ID_CHARS for c in value):
        return None
    return value

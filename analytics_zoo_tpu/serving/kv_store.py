"""Tiered KV memory: host-DRAM spill store + fleet-wide prefix index.

Analytics Zoo kept hot features one tier below DRAM instead of
recomputing them (the ``feature/pmem`` Optane FeatureSet cache); this
module is the same idea for LM serving.  A paged engine's block pool
(serving/paged_cache.py) evicts CACHED chain tails when it runs dry —
today the prefix dies and the next request re-prefills it from
scratch.  With a :class:`HostKVStore` attached, the eviction hook
offers the block to a bounded host-RAM tier instead, and admission's
prefix lookup extends past the device index into the store: a hit
turns a full re-prefill into a host->HBM copy (``adopt_chain``, the
PR 15 all-or-nothing contract).

The second half is fleet-wide: a :class:`PrefixDirectory` tracks
which replica holds which chain hash at which tier, so the router's
``route_request`` (serving/policy.py) can rank candidate replicas by
estimated reuse depth and send millions of shared-system-prompt users
to the replica that already holds their prefix.

Both classes are intentionally stdlib-only, like serving/policy.py:
the engine hands the store *opaque* payloads (numpy trees in
practice) with a caller-computed byte size, so the sim and bare-box
tooling can import this module with no numpy/jax on the path.

Threading: each class carries its own lock.  Pool callbacks fire
under the pool lock (see BlockPool.event_cb contract) — the store and
directory never call back into the pool, so lock order is always
pool -> store/directory and cannot invert.
"""

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "HostKVStore",
    "PrefixDirectory",
    "TIER_HBM",
    "TIER_HOST",
]

# Directory tier labels.  TIER_HBM entries are device-resident
# (published in a pool's hash index); TIER_HOST entries live in a
# replica's HostKVStore.
TIER_HBM = "hbm"
TIER_HOST = "host"


class HostKVStore:
    """Bounded host-RAM second tier for evicted KV blocks.

    Entries are keyed by *chain hash* — one full block of KV per hash.
    Because chain hashes are position-aligned and encode the full
    token history up to their block (paged_cache.chain_hashes), a run
    of per-hash entries composes back into a chain at probe time: the
    store never needs to remember which chain a block came from.

    The payload is opaque to the store (the engine passes host
    numpy trees; int8 ``QuantKV`` blocks spill quantized with their
    scales alongside) and the caller supplies its byte size, keeping
    this module numpy-free.  Capacity is enforced in bytes with LRU
    eviction *within the store*; ``put`` of an oversized entry is
    rejected rather than flushing the whole tier.

    Re-admission never removes an entry: ``adopt_chain`` back into a
    pool can still fail after a successful probe (dry pool), and the
    rollback contract requires the store copy to survive.  Entries
    leave only under capacity pressure (or ``pop``/``clear``).
    """

    def __init__(self, capacity_bytes: int,
                 evict_cb: Optional[Callable[[int], None]] = None):
        if capacity_bytes <= 0:
            raise ValueError(
                "HostKVStore capacity_bytes must be > 0 "
                "(got %r); use no store at all to disable the tier"
                % (capacity_bytes,))
        self.capacity_bytes = int(capacity_bytes)
        # hash -> (payload, nbytes); insertion order = LRU order with
        # move_to_end on every touch.
        self._entries: "OrderedDict[int, Tuple[Any, int]]" = OrderedDict()
        self._lock = threading.Lock()
        # fires for every entry dropped under capacity pressure (or
        # pop) so the owner can retract the host-tier directory claim
        self.evict_cb = evict_cb
        # counters (scraped via the engine's gauges)
        self.spilled_chains = 0
        self.spilled_bytes = 0
        self.store_evictions = 0
        self.probes = 0
        self.probe_hits = 0
        self.occupancy_bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, hash_: int) -> bool:
        with self._lock:
            return hash_ in self._entries

    def put(self, hash_: int, payload: Any, nbytes: int) -> bool:
        """Offer one block to the store.  Returns True when accepted.

        An already-present hash refreshes recency and is counted as
        accepted (the device copy and the store copy are snapshots of
        the same immutable published block).  Entries larger than the
        whole store are rejected without disturbing residents.
        """
        nbytes = int(nbytes)
        evicted: List[int] = []
        with self._lock:
            if hash_ in self._entries:
                self._entries.move_to_end(hash_)
                return True
            if nbytes > self.capacity_bytes:
                return False
            while (self.occupancy_bytes + nbytes > self.capacity_bytes
                   and self._entries):
                old_h, (_, old_n) = self._entries.popitem(last=False)
                self.occupancy_bytes -= old_n
                self.store_evictions += 1
                evicted.append(old_h)
            self._entries[hash_] = (payload, nbytes)
            self.occupancy_bytes += nbytes
            self.spilled_chains += 1
            self.spilled_bytes += nbytes
        if self.evict_cb is not None:
            for h in evicted:
                self.evict_cb(h)
        return True

    def probe(self, hashes: Sequence[int]) -> List[Tuple[int, Any]]:
        """Longest leading run of ``hashes`` present in the store.

        Returns ``[(hash, payload), ...]`` for the run (possibly
        empty) and bumps each hit's recency.  Only a *leading* run is
        useful to admission: a chain must extend an unbroken prefix.
        """
        out: List[Tuple[int, Any]] = []
        with self._lock:
            self.probes += 1
            for h in hashes:
                ent = self._entries.get(h)
                if ent is None:
                    break
                self._entries.move_to_end(h)
                out.append((h, ent[0]))
            if out:
                self.probe_hits += 1
        return out

    def pop(self, hash_: int) -> Optional[Any]:
        """Remove and return one entry (None when absent)."""
        with self._lock:
            ent = self._entries.pop(hash_, None)
            if ent is None:
                return None
            self.occupancy_bytes -= ent[1]
        if self.evict_cb is not None:
            self.evict_cb(hash_)
        return ent[0]

    def clear(self) -> None:
        with self._lock:
            hashes = list(self._entries)
            self._entries.clear()
            self.occupancy_bytes = 0
        if self.evict_cb is not None:
            for h in hashes:
                self.evict_cb(h)

    def metrics(self) -> Dict[str, int]:
        with self._lock:
            return {
                "capacity_bytes": self.capacity_bytes,
                "occupancy_bytes": self.occupancy_bytes,
                "entries": len(self._entries),
                "spilled_chains": self.spilled_chains,
                "spilled_bytes": self.spilled_bytes,
                "store_evictions": self.store_evictions,
                "probes": self.probes,
                "probe_hits": self.probe_hits,
            }


class PrefixDirectory:
    """Fleet-wide prefix index: chain hash -> {replica_id: tier}.

    Every replica publishes its device-index contents (TIER_HBM) and
    its host-store contents (TIER_HOST) here as they change — pool
    publish/evict hooks and store put/evict callbacks are the only
    writers.  The router reads it per request through
    :meth:`match_depths` to fill ``ReplicaSignals.prefix_blocks``, the
    prefix-locality rank term in ``route_request``.

    The directory is advisory: a stale entry costs one wasted probe on
    the chosen replica, never correctness (admission re-checks the
    pool index and the store under their own locks).
    """

    def __init__(self) -> None:
        self._by_hash: Dict[int, Dict[int, str]] = {}
        self._lock = threading.Lock()
        self.publishes = 0
        self.unpublishes = 0

    def publish(self, replica: int, hash_: int, tier: str) -> None:
        if tier not in (TIER_HBM, TIER_HOST):
            raise ValueError("unknown tier %r" % (tier,))
        with self._lock:
            self._by_hash.setdefault(hash_, {})[int(replica)] = tier
            self.publishes += 1

    def unpublish(self, replica: int, hash_: int,
                  tier: Optional[str] = None) -> None:
        """Retract a claim.  ``tier=None`` drops the replica's claim
        regardless of tier; a tier-qualified unpublish is a no-op when
        the replica's current claim is for the *other* tier (an HBM
        eviction must not retract a host-store claim published a
        moment earlier)."""
        with self._lock:
            claims = self._by_hash.get(hash_)
            if claims is None:
                return
            cur = claims.get(int(replica))
            if cur is None or (tier is not None and cur != tier):
                return
            del claims[int(replica)]
            if not claims:
                del self._by_hash[hash_]
            self.unpublishes += 1

    def lookup(self, hash_: int) -> Dict[int, str]:
        with self._lock:
            return dict(self._by_hash.get(hash_, ()))

    def match_depths(self, hashes: Sequence[int]) -> Dict[int, int]:
        """Longest leading run held per replica, any tier.

        Returns ``{replica_id: depth_in_blocks}`` for every replica
        holding at least the first hash.  Depth is the router's
        estimated reuse: blocks the replica can serve from HBM or
        host store instead of re-prefilling.
        """
        depths: Dict[int, int] = {}
        with self._lock:
            live: Optional[set] = None
            for i, h in enumerate(hashes):
                claims = self._by_hash.get(h)
                holders = set(claims) if claims else set()
                live = holders if live is None else (live & holders)
                if not live:
                    break
                for r in live:
                    depths[r] = i + 1
        return depths

    def metrics(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hashes": len(self._by_hash),
                "publishes": self.publishes,
                "unpublishes": self.unpublishes,
            }

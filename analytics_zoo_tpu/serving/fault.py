"""Deterministic fault injection for the serving fleet.

Chaos testing only earns its keep when a failure reproduces: this
module turns a config-declared schedule (``ServingConfig.
fault_injection`` — a list of plain dicts) into a thread-safe
:class:`FaultInjector` that fires each fault at an exact, replayable
point — an engine tick count, a handoff sequence number, or (in the
simulator) a virtual timestamp.  No wall clock and no RNG participate
in *when* a fault fires, so the same schedule produces the same
failure on every run, live or simulated — which is what lets
``make chaos-smoke`` and the ``golden-chaos-fleet`` sim scenario pin
recovery behavior in CI (docs/debugging.md § Crash recovery runbook).

Fault kinds (``FaultSpec.kind``):

- ``kill_pump`` — the pump calls ``ClusterServing.kill_pump`` on
  itself at tick ``at_tick``: PLANNED retirement, graceful drain.
- ``crash_pump`` — an :class:`InjectedFault` escapes the pump loop at
  tick ``at_tick`` (live) / the replica dies at virtual time ``at_t``
  (sim): UNPLANNED death; the supervisor must declare it dead and
  re-dispatch its lost in-flight requests.
- ``raise_step`` — ``ContinuousEngine.step`` raises at tick
  ``at_tick``: a device step blew up; the pump's existing crash
  handler dumps a bundle and keeps serving.
- ``freeze_tick`` — the engine sleeps ``duration_s`` before tick
  ``at_tick``: a wedged device; long enough freezes trip the
  supervisor's heartbeat-miss death.
- ``alloc_storm`` — ``count`` consecutive ticks from ``at_tick``
  each record a block-pool allocation failure: drives the alloc-fail
  streak, the anomaly monitor, and router pressure without actually
  draining the pool.
- ``drop_handoff`` — the ``at_handoff``-th (or next) prefill→decode
  handoff delivery is swallowed: the two-phase ack timeout must
  recover it.
- ``delay_handoff`` — ditto, but delivered ``duration_s`` late.

The injector is shared by every consumer of one fleet: each
``ContinuousEngine`` drives :meth:`tick_actions` (which advances that
replica's tick counter), the pump threads poll :meth:`pump_action`,
the broker's handoff path calls :meth:`handoff_action`, and the sim's
``FleetModel`` reads :meth:`due_crashes` / :meth:`handoff_action`
against virtual time.  Everything is stdlib-only on purpose, like
``serving/policy.py`` — the simulator imports this file with no jax.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["FAULT_KINDS", "FaultSpec", "FaultInjector", "InjectedFault",
           "parse_faults"]

FAULT_KINDS: Tuple[str, ...] = (
    "kill_pump", "crash_pump", "raise_step", "freeze_tick",
    "alloc_storm", "drop_handoff", "delay_handoff")

#: Kinds triggered by a replica-local tick counter.
_TICK_KINDS = frozenset({"kill_pump", "crash_pump", "raise_step",
                         "freeze_tick", "alloc_storm"})
#: Kinds triggered by the fleet-wide handoff sequence number.
_HANDOFF_KINDS = frozenset({"drop_handoff", "delay_handoff"})


class InjectedFault(RuntimeError):
    """Raised by an armed ``raise_step`` / ``crash_pump`` fault — a
    distinct type so tests and log readers can tell injected chaos
    from organic failures."""


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault, as plain data (see module docstring for
    the kinds and which trigger field each reads)."""

    kind: str
    replica: int = 0
    #: Engine-tick trigger (live engines / pumps count busy ticks).
    at_tick: Optional[int] = None
    #: Virtual-time trigger (the simulator's ``FleetModel``).
    at_t: Optional[float] = None
    #: Fleet-wide handoff sequence trigger (0-based; ``None`` = the
    #: next handoff after the spec arms).
    at_handoff: Optional[int] = None
    #: ``alloc_storm``: storm length in ticks; ``drop/delay_handoff``:
    #: how many deliveries to affect.
    count: int = 1
    #: ``freeze_tick``: sleep length; ``delay_handoff``: added latency.
    duration_s: float = 0.0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultSpec":
        if not isinstance(d, dict):
            raise TypeError(f"fault spec must be a dict, got {type(d)}")
        kind = d.get("kind")
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(choose from {FAULT_KINDS})")
        unknown = set(d) - {"kind", "replica", "at_tick", "at_t",
                            "at_handoff", "count", "duration_s"}
        if unknown:
            raise ValueError(f"unknown fault spec fields {sorted(unknown)}")
        spec = cls(
            kind=kind, replica=int(d.get("replica", 0)),
            at_tick=(None if d.get("at_tick") is None
                     else int(d["at_tick"])),
            at_t=(None if d.get("at_t") is None else float(d["at_t"])),
            at_handoff=(None if d.get("at_handoff") is None
                        else int(d["at_handoff"])),
            count=int(d.get("count", 1)),
            duration_s=float(d.get("duration_s", 0.0)))
        if spec.count < 1:
            raise ValueError(f"fault count must be >= 1, got {spec.count}")
        if spec.kind in _TICK_KINDS and spec.at_tick is None \
                and spec.at_t is None:
            raise ValueError(
                f"{kind!r} needs at_tick (live) or at_t (sim)")
        return spec


def parse_faults(specs: Optional[Sequence[Any]]) -> List[FaultSpec]:
    """Validate a config-level fault schedule (a list of dicts, or
    already-built :class:`FaultSpec` instances) into specs.  ``None``
    / empty parses to an empty schedule — injection off."""
    out: List[FaultSpec] = []
    for s in specs or ():
        out.append(s if isinstance(s, FaultSpec)
                   else FaultSpec.from_dict(s))
    return out


class _Armed:
    """Mutable firing state for one spec."""

    __slots__ = ("spec", "remaining")

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self.remaining = spec.count


class FaultInjector:
    """Deterministic fault scheduler for one fleet (see module
    docstring).  ``seed`` is carried for schedule provenance (bundles
    record it) — firing order itself is fully determined by the
    schedule, never sampled."""

    def __init__(self, specs: Optional[Sequence[Any]] = None,
                 seed: int = 0):
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._armed = [_Armed(s) for s in parse_faults(specs)]
        self._ticks: Dict[int, int] = {}    # replica -> busy ticks seen
        self._handoffs = 0                  # fleet-wide handoff seq
        self.fired: List[Tuple[str, Dict[str, Any]]] = []

    @property
    def enabled(self) -> bool:
        return bool(self._armed)

    def _fire(self, a: _Armed, **detail: Any) -> None:
        a.remaining -= 1
        self.fired.append((a.spec.kind,
                           dict(detail, replica=a.spec.replica)))

    # -- engine side ----------------------------------------------------

    def tick_actions(self, replica: int) -> Dict[str, Any]:
        """Called by ``ContinuousEngine.step`` once per BUSY tick
        (idle polls don't count — the sim's ``EngineModel`` only ticks
        with work too).  Advances this replica's tick counter and
        returns the due engine-side actions:
        ``{"freeze_s": float, "alloc_fail": bool, "raise_step": str?}``
        — an empty dict when nothing fires."""
        out: Dict[str, Any] = {}
        with self._lock:
            tick = self._ticks.get(replica, 0)
            self._ticks[replica] = tick + 1
            for a in self._armed:
                s = a.spec
                if (a.remaining <= 0 or s.replica != replica
                        or s.at_tick is None or tick < s.at_tick):
                    continue
                if s.kind == "freeze_tick":
                    self._fire(a, tick=tick)
                    out["freeze_s"] = out.get("freeze_s", 0.0) \
                        + s.duration_s
                elif s.kind == "alloc_storm":
                    # stays armed for `count` consecutive ticks
                    if tick < s.at_tick + s.count:
                        if tick == s.at_tick + s.count - 1:
                            a.remaining = 0
                        self.fired.append((s.kind, {"replica": replica,
                                                    "tick": tick}))
                        out["alloc_fail"] = True
                elif s.kind == "raise_step":
                    self._fire(a, tick=tick)
                    out["raise_step"] = (
                        f"injected device-step fault "
                        f"(replica {replica}, tick {tick})")
        return out

    def pump_action(self, replica: int) -> Optional[str]:
        """Polled by the pump loop between submits and steps: returns
        ``"kill"`` (graceful self-retirement), ``"crash"`` (raise out
        of the pump), or ``None``.  Fires once the replica's tick
        counter reaches ``at_tick`` — at-or-after, so a schedule can
        name a tick the replica never exactly lands on."""
        with self._lock:
            tick = self._ticks.get(replica, 0)
            for a in self._armed:
                s = a.spec
                if (a.remaining <= 0 or s.replica != replica
                        or s.kind not in ("kill_pump", "crash_pump")
                        or s.at_tick is None or tick < s.at_tick):
                    continue
                self._fire(a, tick=tick)
                return "kill" if s.kind == "kill_pump" else "crash"
        return None

    # -- handoff path (broker / sim fleet) ------------------------------

    def handoff_action(self, t: Optional[float] = None
                       ) -> Optional[Tuple[str, float]]:
        """Called once per prefill→decode handoff delivery (the broker
        before ``submit_handoff``; the sim fleet before ``_deliver``).
        Returns ``("drop", 0.0)``, ``("delay", seconds)``, or ``None``
        (deliver normally).  A spec with ``at_handoff`` fires on that
        sequence number; one with only ``at_t`` fires once virtual
        time reaches it (sim); one with neither fires on the next
        delivery."""
        with self._lock:
            seq = self._handoffs
            self._handoffs += 1
            for a in self._armed:
                s = a.spec
                if a.remaining <= 0 or s.kind not in _HANDOFF_KINDS:
                    continue
                if s.at_handoff is not None:
                    if not (s.at_handoff <= seq
                            < s.at_handoff + s.count):
                        continue
                elif s.at_t is not None:
                    if t is None or t < s.at_t:
                        continue
                self._fire(a, handoff=seq)
                return (("drop", 0.0) if s.kind == "drop_handoff"
                        else ("delay", s.duration_s))
        return None

    # -- simulator side -------------------------------------------------

    def due_crashes(self, replica: int, now_t: float) -> bool:
        """Virtual-time twin of ``pump_action``'s crash: True once
        when ``replica`` has a ``crash_pump`` spec with
        ``at_t <= now_t`` (the sim fleet marks the replica dead and
        re-dispatches its lost requests)."""
        with self._lock:
            for a in self._armed:
                s = a.spec
                if (a.remaining <= 0 or s.kind != "crash_pump"
                        or s.replica != replica or s.at_t is None
                        or now_t < s.at_t):
                    continue
                self._fire(a, t=now_t)
                return True
        return False

    # -- observability --------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Diagnostic view for bundles / ``router_status``."""
        with self._lock:
            return {
                "seed": self.seed,
                "armed": [{"kind": a.spec.kind,
                           "replica": a.spec.replica,
                           "remaining": a.remaining}
                          for a in self._armed if a.remaining > 0],
                "fired": [{"kind": k, **d} for k, d in self.fired],
            }

from analytics_zoo_tpu.serving.continuous import ContinuousEngine
from analytics_zoo_tpu.serving.flight import (AnomalyMonitor,
                                              FlightRecorder,
                                              JsonLogFormatter,
                                              RingLogHandler, SloPolicy,
                                              SloWatchdog, dump_bundle,
                                              install_flight_logging,
                                              prune_bundles,
                                              request_uri_context)
from analytics_zoo_tpu.serving.frontdoor import (PRIORITIES, QosPolicy,
                                                 TokenEmitter,
                                                 WeightedWaitQueue,
                                                 normalize_request_id,
                                                 retry_after_s)
from analytics_zoo_tpu.serving.paged_cache import BlockPool
from analytics_zoo_tpu.serving.queues import (BacklogFull, InputQueue,
                                              OutputQueue)
from analytics_zoo_tpu.serving.resp import RespClient, RespServer
from analytics_zoo_tpu.serving.server import ClusterServing, ServingConfig
from analytics_zoo_tpu.serving.http_frontend import HttpFrontend
from analytics_zoo_tpu.serving.telemetry import (
    MetricsRegistry, Telemetry, WindowHistogram, render_prometheus,
    validate_chrome_trace)

__all__ = ["ContinuousEngine", "BlockPool", "InputQueue", "OutputQueue",
           "RespClient", "RespServer", "ClusterServing", "ServingConfig",
           "HttpFrontend", "MetricsRegistry", "Telemetry",
           "WindowHistogram", "render_prometheus",
           "validate_chrome_trace",
           "BacklogFull", "PRIORITIES", "QosPolicy", "TokenEmitter",
           "WeightedWaitQueue", "retry_after_s",
           "FlightRecorder", "SloPolicy", "SloWatchdog", "AnomalyMonitor",
           "dump_bundle", "prune_bundles", "JsonLogFormatter",
           "RingLogHandler", "install_flight_logging",
           "request_uri_context", "normalize_request_id"]

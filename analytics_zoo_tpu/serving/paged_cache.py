"""Block-pool KV-cache memory manager for the serving engine.

The continuous-batching arena (serving/continuous.py) reserves a full
max-length KV strip per slot: HBM pays worst-case sequence length for
every resident, which caps co-residency far below what the traffic
actually needs.  This module is the vLLM-PagedAttention /
SGLang-RadixAttention answer: ONE flat pool of fixed-size blocks
``[n_layers, n_blocks, block_size, kv_heads, head_dim]`` on device,
and a host-side :class:`BlockPool` that hands blocks to requests as
they actually grow, refcounts them, and indexes FULL prompt blocks by
a position-aligned chain hash so later requests sharing a prompt
prefix attach to the same physical blocks copy-free.

Division of labour: everything here is host-side bookkeeping (plain
Python ints — no jax in this module); the device arena and the block
tables that feed ``TransformerLM.decode_step_paged`` live in the
engine.  The engine calls, in order:

- :meth:`BlockPool.block_hashes` + :meth:`BlockPool.lookup` at
  admission to find how many leading prompt blocks are already
  resident, then :meth:`BlockPool.acquire` each match (ref++),
- :meth:`BlockPool.allocate` for every block it must fill itself
  (free list first, then LRU eviction of unreferenced cached blocks),
- :meth:`BlockPool.insert` after a successful prefill to publish the
  request's own full prompt blocks for future sharing,
- :meth:`BlockPool.release` for every held block when the request
  finishes or is preempted — blocks that are still hash-indexed park
  in the LRU (reusable by future lookups OR evictable), unindexed
  ones return straight to the free list.

Hash-chain safety: a block's key hashes ALL tokens from position 0
through the block's end, so equal hash ⇒ equal token history ⇒ equal
K/V content at those positions for BOTH rope and learned position
encodings (K is stored post-rotation at absolute positions — see
``_apply_rope`` in models/lm.py).  Only full, position-aligned prompt
blocks are ever indexed; a partially-filled tail block is always
private to its request.

Block 0 is the SINK: never allocated, never indexed, permanently
garbage.  The engine points every unallocated block-table entry at it
so out-of-range or padding-row writes land in storage nothing ever
attends.

Two-tenant accounting: a speculative engine runs a SECOND pool for
the draft model's K/V (its own device arena and block tables — block
ids from one pool mean nothing in the other).  Each pool carries a
``name`` ("target" / "draft") that labels its metrics and event
callbacks so a scrape can tell whose blocks ran dry, and
:func:`split_block_budget` turns one HBM byte budget into the common
block count both tenants can afford — the split is proportional to
per-block cost (layers x kv_heads x head_dim x dtype), which is why a
small draft is nearly free to page alongside its target.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

SINK_BLOCK = 0

# The ONE statement of the pool-callback discipline.  The per-hook
# parameter docs below and every dispatch-site comment point here
# instead of paraphrasing it — three slightly-different wordings of
# "record-only under the pool lock" had already drifted apart once.
CALLBACK_CONTRACT = """\
BlockPool callback contract (event_cb / spill_cb / index_cb — and the
tiered-store hooks evict_cb/handoff_cb in serving/kv_store.py):

Every hook fires synchronously inside a pool mutation, while the
CALLER is typically holding its pool lock (the engine's _pool_lock).
A callback must therefore be RECORD-ONLY:

- append into its own structures, taking at most a private leaf lock
  that is never held around pool or engine calls (the documented
  fleet lock order is pool -> telemetry / store / directory, never
  inverted);
- never call back into this pool or the engine — re-entry would
  deadlock a non-reentrant pool lock or corrupt allocator state
  mid-mutation.  Under __debug__ the pool traps this with an
  assertion at every public entry point;
- never block: no device transfers (jax.device_get / device_put), no
  sleeps, no queue or socket waits.  Heavy work (the actual D2H spill
  copy) is deferred by the caller and drained after the pool lock is
  released — see _drain_spills in serving/continuous.py.

tpulint enforces this statically (TZ103 checks every callable passed
as event_cb=/spill_cb=/index_cb=/evict_cb= plus in-module invocation
sites under held locks) and dynamically (lint.lockguard.LockGuard
records under-lock blocking calls and raises on re-entry at test
time).
"""

# bytes per stored K (or V) element, keyed by the pool's ``kv_dtype``
# mode.  int8 rows carry a per-(block, position, kv-head) bfloat16
# scale alongside the 1-byte elements (see
# ``ops/flash_attention.quantize_kv``), so its cost is accounted per
# ROW as ``head_dim + KV_SCALE_BYTES`` rather than per element.
KV_DTYPE_BYTES = {"bf16": 2, "f16": 2, "f32": 4, "f64": 8, "int8": 1}
KV_SCALE_BYTES = 2  # bfloat16 scale per int8 row


def block_bytes(n_layers: int, block_size: int, kv_heads: int,
                head_dim: int, kv_dtype: str = "bf16") -> int:
    """HBM bytes ONE physical block costs across all layers, K and V
    both.  This is the quantity :func:`split_block_budget` splits a
    byte budget by, and the engine's capacity report bills.  For
    ``kv_dtype="int8"`` each ``head_dim`` row additionally stores a
    ``KV_SCALE_BYTES`` quantization scale, so the int8 pool fits
    ``(2*D)/(D+2)`` ≈ 1.94x (at D=64) as many blocks as bf16 in the
    same budget."""
    if kv_dtype not in KV_DTYPE_BYTES:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}; expected one "
                         f"of {sorted(KV_DTYPE_BYTES)}")
    row = head_dim * KV_DTYPE_BYTES[kv_dtype]
    if kv_dtype == "int8":
        row += KV_SCALE_BYTES
    return 2 * int(n_layers) * int(block_size) * int(kv_heads) * row


def split_block_budget(budget_bytes: int,
                       per_block_costs: Sequence[int]) -> int:
    """The COMMON block count every tenant can hold inside one HBM
    byte budget: tenants grow in lockstep (the engine mirrors a row's
    draft table onto its target table positions), so the budget splits
    proportionally to per-block cost rather than evenly — ``n`` blocks
    for each tenant where ``n * sum(costs) <= budget``."""
    total = sum(int(c) for c in per_block_costs)
    if total <= 0:
        raise ValueError(f"per-block costs must sum > 0, got "
                         f"{per_block_costs!r}")
    return int(budget_bytes) // total


def chain_hashes(tokens: Sequence[int], block_size: int) -> List[int]:
    """Position-aligned chain hash of each FULL ``block_size`` chunk of
    ``tokens``: chunk j's key covers tokens[0 : (j+1)*block_size], so
    two sequences share a key only when their entire history through
    that block is identical.  A trailing partial chunk gets no hash
    (it must stay private — its K/V will keep growing)."""
    out: List[int] = []
    h = 0x9E3779B97F4A7C15  # non-zero seed so an empty prefix != hash 0
    for j in range(len(tokens) // block_size):
        chunk = tuple(int(t) for t in
                      tokens[j * block_size:(j + 1) * block_size])
        # int-tuple hashing is deterministic (PYTHONHASHSEED only
        # perturbs str/bytes), so the index is stable across runs
        h = hash((h, chunk))
        out.append(h)
    return out


class BlockPool:
    """Host-side allocator/refcounter/prefix-index over ``n_blocks``
    physical KV blocks of ``block_size`` token positions each.

    Lifecycle of a physical block:

    - FREE (on ``_free``): content is garbage; ``allocate`` hands it
      out with ref=1.
    - REFERENCED (ref >= 1): owned by one or more live requests.  A
      block published via ``insert`` may be acquired by later lookups
      (ref counts sharers).
    - CACHED (ref == 0 but hash-indexed, on ``_lru``): no live owner,
      but its K/V is intact and future lookups may resurrect it
      (``acquire`` → ref=1).  ``allocate`` evicts from here, oldest
      first, when the free list is dry — eviction unpublishes the
      hash so no later lookup can match stale storage.

    Block 0 (``SINK_BLOCK``) is outside all three states forever.
    """

    def __init__(self, n_blocks: int, block_size: int,
                 enable_prefix_cache: bool = True,
                 event_cb: Optional[Callable[..., None]] = None,
                 name: str = "target",
                 kv_dtype: str = "bf16",
                 bytes_per_block: Optional[int] = None,
                 spill_cb: Optional[Callable[[int, int], None]] = None,
                 index_cb: Optional[Callable[..., None]] = None):
        if n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (block 0 is the sink), got "
                f"{n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.enable_prefix_cache = bool(enable_prefix_cache)
        # tenant label ("target" / "draft" in the speculative engine):
        # stamped on every event callback so a timeline can tell WHOSE
        # pool evicted or ran dry when two tenants share one telemetry
        self.name = str(name)
        # storage-mode accounting (the pool itself is jax-free — the
        # device arena actually quantizes/dequantizes; this is the
        # label and cost a scrape bills blocks at).  ``bytes_per_block``
        # is the all-layer K+V cost the engine computed via
        # :func:`block_bytes`; 0 when the caller did not say.
        if kv_dtype not in KV_DTYPE_BYTES:
            raise ValueError(f"unknown kv_dtype {kv_dtype!r}; expected "
                             f"one of {sorted(KV_DTYPE_BYTES)}")
        self.kv_dtype = kv_dtype
        self.bytes_per_block = int(bytes_per_block or 0)
        # observability hook, called as event_cb(kind, **info) for
        # "eviction" and "alloc_failure" (the two transitions the
        # cumulative counters alone cannot place on a timeline).  The
        # engine wires Telemetry.pool_event; record-only per
        # CALLBACK_CONTRACT (module top).
        self.event_cb = event_cb
        # tiered-KV hooks (serving/kv_store.py; both default None =
        # tier off, zero behavior change).  ``spill_cb(block, hash)``
        # fires when a CACHED block is evicted — the one moment its
        # K/V is intact, unreferenced, and about to become garbage —
        # giving the engine a last chance to note it for host-store
        # copy before the block id is reused.  ``index_cb(kind,
        # hash_, block)`` mirrors index membership ("publish" /
        # "unpublish") into the fleet PrefixDirectory.  Record-only
        # per CALLBACK_CONTRACT, same as event_cb.
        self.spill_cb = spill_cb
        self.index_cb = index_cb
        # True only while one of the three hooks above is on the
        # stack; armed by _fire, checked (``__debug__`` only) at every
        # public entry point to trap contract-breaking re-entry
        self._in_cb = False
        self._free: deque = deque(range(1, self.n_blocks))
        self._ref: Dict[int, int] = {}
        self._hash_of: Dict[int, int] = {}     # block -> published hash
        self._index: Dict[int, int] = {}       # hash  -> block
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # metrics (monotonic counters except the gauges derived below)
        self.prefix_queries = 0    # blocks asked of lookup()
        self.prefix_hits = 0       # blocks answered from the index
        self.evictions = 0
        self.alloc_failures = 0    # allocate() returned None
        self.resizes = 0           # grow()/shrink() calls that moved
        self.resize_clamps = 0     # shrink clamped by referenced tail
        self.chains_exported = 0   # export_chain() calls
        self.chains_adopted = 0    # successful adopt_chain() calls

    # -- callback dispatch (see CALLBACK_CONTRACT) --------------------

    def _fire(self, cb: Callable[..., None], *args, **kwargs) -> None:
        """Run one registered hook with the re-entrancy trap armed:
        while a callback is on the stack, every public pool method
        asserts instead of deadlocking on the caller's pool lock or
        corrupting allocator state mid-mutation."""
        self._in_cb = True
        try:
            cb(*args, **kwargs)
        finally:
            self._in_cb = False

    def _entered(self) -> bool:
        """Used as ``assert self._entered()`` so ``-O`` strips the
        whole check along with the assert statement."""
        if self._in_cb:
            raise AssertionError(
                f"BlockPool({self.name!r}) re-entered from inside one "
                f"of its own callbacks; hooks are record-only — see "
                f"paged_cache.CALLBACK_CONTRACT")
        return True

    # -- hashing / lookup --------------------------------------------

    def block_hashes(self, tokens: Sequence[int]) -> List[int]:
        """Chain hashes of every full block of ``tokens`` (see
        :func:`chain_hashes`)."""
        return chain_hashes(tokens, self.block_size)

    def lookup(self, hashes: Sequence[int]) -> List[int]:
        """Longest indexed run from the start of ``hashes`` → physical
        block ids.  Counts every offered hash as a query and every
        match as a hit (the hit RATE is hits/queries).  Does NOT take
        references — call :meth:`acquire` on each returned block while
        still holding the engine lock, or another admission could
        evict them out from under you."""
        assert self._entered()
        if not self.enable_prefix_cache:
            # the index was never consulted: counting these as queries
            # would drag the reported hit rate toward zero on a pool
            # that has prefix caching switched off
            return []
        self.prefix_queries += len(hashes)
        out: List[int] = []
        for h in hashes:
            blk = self._index.get(h)
            if blk is None:
                break
            out.append(blk)
        self.prefix_hits += len(out)
        return out

    # -- reference management ----------------------------------------

    def acquire(self, block: int) -> None:
        """ref++ on an indexed block a lookup returned (resurrects it
        from the LRU if it was unreferenced)."""
        assert self._entered()
        if block == SINK_BLOCK:
            raise ValueError("cannot acquire the sink block")
        self._ref[block] = self._ref.get(block, 0) + 1
        self._lru.pop(block, None)

    def allocate(self) -> Optional[int]:
        """A fresh block with ref=1 and garbage content: free list
        first, else evict the least-recently-parked CACHED block
        (unpublishing its hash).  ``None`` when every block is
        referenced — the engine's cue to stop admitting / preempt."""
        assert self._entered()
        if self._free:
            blk = self._free.popleft()
        elif self._lru:
            blk, _ = self._lru.popitem(last=False)
            h = self._hash_of.pop(blk)
            del self._index[h]
            self.evictions += 1
            # spill window: the block is unreferenced, unindexed, and
            # its K/V is still intact on device — the engine notes it
            # for the host tier here, before the id is reused below
            # (record-only per CALLBACK_CONTRACT)
            if self.spill_cb is not None:
                self._fire(self.spill_cb, blk, h)
            if self.index_cb is not None:
                self._fire(self.index_cb, "unpublish", hash_=h, block=blk)
            if self.event_cb is not None:
                self._fire(self.event_cb, "eviction", block=blk,
                           tenant=self.name)
        else:
            self.alloc_failures += 1
            if self.event_cb is not None:
                # every block is referenced — stamp who holds them so a
                # flight-ring/timeline reader sees the dry pool's shape
                # without a separate scrape
                self._fire(self.event_cb, "alloc_failure",
                           tenant=self.name, referenced=len(self._ref),
                           n_blocks=self.n_blocks)
            return None
        self._ref[blk] = 1
        return blk

    def release(self, block: int) -> None:
        """ref--; at zero the block parks in the LRU if it is still
        hash-indexed (K/V reusable), else returns to the free list."""
        assert self._entered()
        if block == SINK_BLOCK:
            raise ValueError("cannot release the sink block")
        r = self._ref.get(block, 0) - 1
        if r < 0:
            raise ValueError(f"release of unreferenced block {block}")
        if r:
            self._ref[block] = r
            return
        del self._ref[block]
        if block in self._hash_of:
            self._lru[block] = None
        else:
            self._free.append(block)

    def insert(self, hash_: int, block: int) -> None:
        """Publish a REFERENCED block under its chain hash so future
        lookups can share it.  First writer wins: if the hash is
        already indexed (two identical prompts prefetched in the same
        admission wave) the existing mapping stands and this block
        simply stays private — correct, merely not deduplicated."""
        assert self._entered()
        if not self.enable_prefix_cache:
            return
        if block == SINK_BLOCK or self._ref.get(block, 0) < 1:
            raise ValueError(
                f"insert requires a referenced non-sink block, got "
                f"{block} (ref={self._ref.get(block, 0)})")
        if hash_ in self._index or block in self._hash_of:
            return
        self._index[hash_] = block
        self._hash_of[block] = hash_
        if self.index_cb is not None:
            self._fire(self.index_cb, "publish", hash_=hash_, block=block)

    # -- prefill/decode handoff (docs/serving_memory.md) ---------------

    def export_chain(self, blocks: Sequence[int]) -> Dict[str, object]:
        """Host-side half of a prefill→decode handoff: snapshot a
        request's block chain so ANOTHER pool can adopt an equivalent
        chain.  Returns the wire-format dict (``block_size`` /
        ``kv_dtype`` / per-block published hashes, ``None`` for a
        private block) — plain Python data, no device state; the
        engine ships the device pool slices alongside.  Read-only:
        the source pool's refcounts are untouched (the engine releases
        the source chain through the normal completion path once the
        export is materialized)."""
        assert self._entered()
        hashes: List[Optional[int]] = []
        for b in blocks:
            if b == SINK_BLOCK or self._ref.get(b, 0) < 1:
                raise ValueError(
                    f"export_chain needs referenced non-sink blocks, "
                    f"got {b} (ref={self._ref.get(b, 0)})")
            hashes.append(self._hash_of.get(b))
        self.chains_exported += 1
        return {"block_size": self.block_size,
                "kv_dtype": self.kv_dtype,
                "n": len(hashes), "hashes": hashes}

    def adopt_chain(self, chain: Dict[str, object]) -> Optional[List[int]]:
        """Allocate a same-length chain in THIS pool (ref=1 each) and
        republish the carried prefix hashes so the decode side keeps
        sharing/serving the prefix — first writer wins exactly like
        :meth:`insert`.  Returns the new block ids in chain order, or
        ``None`` when the pool cannot take the whole chain right now
        (any partial allocation is rolled back — the caller's
        requeue/blocked path)."""
        assert self._entered()
        if int(chain["block_size"]) != self.block_size:
            raise ValueError(
                f"adopt_chain block_size {chain['block_size']} != "
                f"pool block_size {self.block_size}")
        if chain["kv_dtype"] != self.kv_dtype:
            raise ValueError(
                f"adopt_chain kv_dtype {chain['kv_dtype']!r} != pool "
                f"kv_dtype {self.kv_dtype!r}")
        out: List[int] = []
        for _ in range(int(chain["n"])):
            blk = self.allocate()
            if blk is None:
                for b in out:
                    self.release(b)
                return None
            out.append(blk)
        for h, b in zip(chain["hashes"], out):
            if h is not None:
                self.insert(h, b)
        self.chains_adopted += 1
        return out

    # -- elastic resize (block-granular, at the eviction boundary) -----

    def grow(self, n: int) -> int:
        """Append ``n`` fresh FREE blocks at the top of the id range
        (ids ``n_blocks .. n_blocks+n-1``).  The caller must have
        already extended the device arena to match — block ids are
        indices into it.  Returns ``n``."""
        assert self._entered()
        if n < 0:
            raise ValueError(f"grow needs n >= 0, got {n}")
        if n == 0:
            return 0
        start = self.n_blocks
        self.n_blocks += int(n)
        self._free.extend(range(start, self.n_blocks))
        self.resizes += 1
        return int(n)

    def shrinkable(self) -> int:
        """Length of the contiguous UNREFERENCED tail of the id range —
        the most :meth:`shrink` can remove right now.  Only a tail can
        go: the device arena is dense in block id, so dropping a middle
        block would renumber live tables.  Bounded so ``n_blocks``
        never drops below 2 (sink + one usable block)."""
        n = 0
        b = self.n_blocks - 1
        while b >= 2 and b not in self._ref:
            n += 1
            b -= 1
        return n

    def shrink(self, n: int) -> int:
        """Remove up to ``n`` blocks from the top of the id range,
        stopping at the first referenced block (the eviction boundary —
        a live request's storage is NEVER evicted).  Cached tail blocks
        are evicted (hash unpublished, counted like an LRU eviction);
        free tail blocks just leave the free list.  Returns the count
        actually removed; a clamped request (achieved < asked) bumps
        ``resize_clamps`` instead of raising.  The caller slices the
        device arena to the new ``n_blocks`` afterwards."""
        assert self._entered()
        if n < 0:
            raise ValueError(f"shrink needs n >= 0, got {n}")
        m = min(int(n), self.shrinkable())
        if m < n:
            self.resize_clamps += 1
        if m == 0:
            return 0
        for b in range(self.n_blocks - 1, self.n_blocks - m - 1, -1):
            if b in self._lru:
                del self._lru[b]
                h = self._hash_of.pop(b)
                del self._index[h]
                self.evictions += 1
                # same spill window as allocate(): intact K/V about to
                # vanish — the caller slices the arena only after
                # shrink returns, so the device copy is still readable
                if self.spill_cb is not None:
                    self._fire(self.spill_cb, b, h)
                if self.index_cb is not None:
                    self._fire(self.index_cb, "unpublish", hash_=h, block=b)
                if self.event_cb is not None:
                    self._fire(self.event_cb, "eviction", block=b,
                               tenant=self.name)
            else:
                self._free.remove(b)
        self.n_blocks -= m
        self.resizes += 1
        return m

    # -- introspection -----------------------------------------------

    def allocatable(self) -> int:
        """Blocks ``allocate`` could return right now (free + cached)."""
        return len(self._free) + len(self._lru)

    def num_referenced(self) -> int:
        return len(self._ref)

    def num_cached(self) -> int:
        return len(self._lru)

    def occupancy(self) -> float:
        """Fraction of non-sink blocks currently referenced by live
        requests (cached-but-unreferenced blocks do not count — they
        are reclaimable on demand)."""
        return len(self._ref) / max(1, self.n_blocks - 1)

    def hit_rate(self) -> float:
        return self.prefix_hits / max(1, self.prefix_queries)

    def metrics(self) -> Dict[str, float]:
        return {
            "tenant": self.name,
            "kv_dtype": self.kv_dtype,
            "bytes_per_block": self.bytes_per_block,
            "n_blocks": self.n_blocks,
            "block_size": self.block_size,
            "referenced_blocks": len(self._ref),
            "cached_blocks": len(self._lru),
            "free_blocks": len(self._free),
            "occupancy": self.occupancy(),
            "prefix_queries": self.prefix_queries,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": self.hit_rate(),
            "evictions": self.evictions,
            "alloc_failures": self.alloc_failures,
            "resizes": self.resizes,
            "resize_clamps": self.resize_clamps,
            "chains_exported": self.chains_exported,
            "chains_adopted": self.chains_adopted,
        }

    def check(self) -> None:
        """Invariant audit (tests): every non-sink block is in exactly
        one of free/referenced/cached, and the hash index is a
        bijection onto indexed blocks."""
        free = set(self._free)
        ref = set(self._ref)
        cached = set(self._lru)
        assert not (free & ref) and not (free & cached) \
            and not (ref & cached), "block state overlap"
        assert free | ref | cached == set(range(1, self.n_blocks)), \
            "block leak/duplication"
        assert cached <= set(self._hash_of), "cached block lost its hash"
        assert set(self._hash_of) <= ref | cached, \
            "indexed block neither referenced nor cached"
        assert (sorted(self._index.values())
                == sorted(self._hash_of.keys())), "index not a bijection"
        assert all(self._index[h] == b
                   for b, h in self._hash_of.items()), \
            "index/hash_of disagree"

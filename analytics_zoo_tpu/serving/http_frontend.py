"""Cluster Serving HTTP frontend — the akka-http gateway, TPU edition.

Reference surface (SURVEY.md §2.6; ref: serving/http/FrontEndApp.scala with
RedisPutActor/QueryActor): a REST gateway that accepts JSON/image payloads,
enqueues them on the Redis input stream, awaits the result hash, and
responds; optional TLS.

Rebuild shape: stdlib ThreadingHTTPServer (one OS thread per in-flight
request — the actor pool analog), per-thread RESP connections, and the
reference's de-facto observability (queue depth + per-request latency)
exposed as JSON gauges with p50/p90/p99.

Routes:
  POST /predict   {"instances": [{col: <nested list | {"b64","shape",
                  "dtype"}>, ...}, ...]} -> {"predictions": [...]}
  POST /v1/generate  generative front door (docs/serving_qos.md):
                  {"text" | <prompt_col>, "stream", "priority",
                  "tenant", "max_new", "temperature", "seed", "top_p",
                  "prefix"}.  ``stream: true`` answers
                  ``text/event-stream`` (SSE token/done/cancelled/
                  error events); otherwise one JSON body.  A full
                  admission queue answers 429 + ``Retry-After``.
  POST /v1/cancel {"uri": ...} — live-cancel an in-flight request
                  (frees its KV blocks ahead of the TTL prune)
  GET  /metrics   Prometheus text exposition merging the frontend's
                  HTTP latency, the serving job's counters, and the
                  engine's TTFT/TPOT/queue/pool metrics
                  (``?format=json`` keeps the legacy JSON dict)
  GET  /trace     Chrome trace-event JSON of the engine's event ring
                  (load at https://ui.perfetto.dev)
  GET  /healthz   readiness JSON: admission-queue depth vs. cap,
                  accepting/backpressure state, engine mode flags,
                  per-class SLO goodput/breach summary
  GET  /debug/flight  live flight-recorder inspection (docs/
                  debugging.md): the last ``?n=`` tick records, the
                  SLO watchdog's status, and the anomaly-bundle
                  history — the bundle's content without waiting for
                  a trigger

A client-supplied ``X-Request-Id`` header on /v1/generate becomes the
request's uri end-to-end (spans, structured logs, SSE ``start``
event) and is echoed back in the response headers; absent or unusable
ids fall back to a generated uuid.
"""

from __future__ import annotations

import base64
import json
import ssl
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from analytics_zoo_tpu.common.log import logger
from analytics_zoo_tpu.serving.flight import request_uri_context
from analytics_zoo_tpu.serving.frontdoor import (ThroughputEstimator,
                                                 decode_priority,
                                                 encode_deadline,
                                                 encode_priority,
                                                 encode_str_field,
                                                 normalize_request_id,
                                                 retry_after_s, sse_event,
                                                 validate_deadline_ms)
from analytics_zoo_tpu.serving.policy import (brownout_admit,
                                              brownout_classes)
from analytics_zoo_tpu.serving.queues import (
    BacklogFull, ImageBytes, InputQueue, OutputQueue)
from analytics_zoo_tpu.serving.telemetry import (
    MetricsRegistry, WindowHistogram, render_prometheus)


def _decode_value(v):
    """JSON value -> ndarray or image payload: nested lists,
    {"b64","shape","dtype"} dense tensors, or {"image_b64": ...} encoded
    JPEG/PNG bytes the server decodes natively (ref: FrontEndApp accepted
    base64 image bodies)."""
    if isinstance(v, dict):
        if "image_b64" in v:
            return ImageBytes(base64.b64decode(v["image_b64"],
                                               validate=True))
        raw = base64.b64decode(v["b64"], validate=True)
        a = np.frombuffer(raw, dtype=np.dtype(v.get("dtype", "float32")))
        return a.reshape(v["shape"]) if "shape" in v else a
    return np.asarray(v)


class _Percentiles:
    """Sliding-window latency gauge — back-compat shim over a telemetry
    :class:`WindowHistogram` (serving/telemetry.py), which generalized
    this class's private deque.  Same ms-scaled snapshot keys; same
    window-count semantics (``count`` is the samples currently in the
    window, not the cumulative total — that is the histogram's own
    ``snapshot()["count"]``)."""

    def __init__(self, window: int = 2048,
                 hist: Optional[WindowHistogram] = None):
        self._hist = hist if hist is not None else WindowHistogram(
            "latency_seconds", window=window)

    def record(self, seconds: float):
        self._hist.record(seconds)

    def snapshot(self) -> dict:
        s = self._hist.snapshot()
        if not s["window"]:
            return {"count": 0}
        return {
            "count": int(s["window"]),
            "p50_ms": round(s["p50"] * 1e3, 3),
            "p90_ms": round(s["p90"] * 1e3, 3),
            "p99_ms": round(s["p99"] * 1e3, 3),
        }


class HttpFrontend:
    """ref-parity: FrontEndApp — REST in front of the serving queues."""

    def __init__(self, redis_host: str = "127.0.0.1",
                 redis_port: int = 6379, http_port: int = 0,
                 timeout: float = 30.0,
                 certfile: Optional[str] = None,
                 keyfile: Optional[str] = None,
                 serving=None, tokenizer=None,
                 prompt_col: Optional[str] = None,
                 max_backlog: Optional[int] = None):
        self.redis_host, self.redis_port = redis_host, redis_port
        self.timeout = timeout
        self.serving = serving          # optional ClusterServing for stats
        # bounded admission (backpressure): the pooled InputQueues
        # reject past this backlog with BacklogFull -> 429.  None
        # inherits the serving config's cap when attached.
        if max_backlog is None:
            max_backlog = (getattr(serving.config, "max_backlog", 10000)
                           if serving is not None else 10000)
        self.max_backlog = int(max_backlog)
        self._throughput = ThroughputEstimator()
        # text-in / text-out generative serving: a ``tokenizers``
        # Tokenizer instance or a tokenizer.json path.  Instances with a
        # "text" field encode into the prompt column; their results
        # decode back to strings (trimmed at the serving eos, if set).
        if isinstance(tokenizer, str):
            from tokenizers import Tokenizer

            tokenizer = Tokenizer.from_file(tokenizer)
        self.tokenizer = tokenizer
        # fallback mirrors server.py's continuous pump ("prompt") so an
        # unset ServingConfig.prompt_col yields ONE shared default
        self.prompt_col = prompt_col or (
            serving.config.prompt_col if serving is not None
            and getattr(serving.config, "prompt_col", None)
            else "prompt")
        self._eos_id = (serving.config.eos_id
                        if serving is not None else None)
        # frontend-local metrics (zoo_http_*); /metrics merges them
        # with the serving job's and the engine's registries at scrape
        self.registry = MetricsRegistry()
        self.latency = _Percentiles(hist=self.registry.histogram(
            "zoo_http_request_seconds",
            "end-to-end POST /predict wall time (failures included)"))
        self.c_rejected = self.registry.counter(
            "zoo_http_backpressure_rejections_total",
            "requests answered 429 under a full admission queue")
        self.c_disconnects = self.registry.counter(
            "zoo_http_stream_disconnects_total",
            "SSE clients that disconnected mid-stream (each triggers "
            "a live cancel)")
        if serving is not None:
            self.registry.gauge(
                "zoo_http_backlog",
                "input-stream entries not yet consumed by the backend",
                fn=lambda: self.serving.backlog())
        # ThreadingHTTPServer spawns a fresh thread per connection, so
        # thread-local caching would never hit: pool the RESP client pairs
        self._pool: list = []
        self._pool_lock = threading.Lock()
        self._pool_max = 16
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # route through our logger
                logger.debug("http: " + a[0], *a[1:])

            def _send(self, code: int, payload: dict,
                      headers: Optional[dict] = None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _send_429(self, depth, message, level=0):
                # header and body carry the SAME value by construction
                # — a client honoring either backs off identically
                ra = frontend._retry_after(depth, level=level)
                body = json.dumps({"error": message,
                                   "retry_after_s": ra}).encode()
                self.send_response(429)
                self.send_header("Content-Type", "application/json")
                self.send_header("Retry-After", str(ra))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_503_dead(self):
                # zero live replicas: refusing with a finite
                # Retry-After beats accepting a submit that can never
                # be placed (the supervisor may be restarting pumps —
                # clients should back off and retry, not hang)
                ra = frontend._retry_after(None)
                body = json.dumps(
                    {"error": "no live replicas — fleet is "
                              "recovering",
                     "retry_after_s": ra}).encode()
                self.send_response(503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Retry-After", str(ra))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    self._send(200, frontend.health())
                elif path == "/metrics":
                    if "format=json" in query:
                        self._send(200, frontend.metrics())
                        return
                    body = frontend.prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/trace":
                    trace = frontend.trace()
                    if trace is None:
                        self._send(404, {
                            "error": "no engine telemetry attached "
                                     "(start the frontend with "
                                     "serving=...)"})
                    else:
                        self._send(200, trace)
                elif path == "/debug/flight":
                    n = 100
                    for part in query.split("&"):
                        if part.startswith("n="):
                            try:
                                n = max(1, int(part[2:]))
                            except ValueError:
                                pass
                    body = frontend.debug_flight(last=n)
                    if body is None:
                        self._send(404, {
                            "error": "no flight recorder attached "
                                     "(start the frontend with "
                                     "serving=...)"})
                    else:
                        self._send(200, body)
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path == "/v1/generate":
                    self._do_generate()
                    return
                if self.path == "/v1/cancel":
                    self._do_cancel()
                    return
                if self.path != "/predict":
                    self._send(404, {"error": f"no route {self.path}"})
                    return
                if frontend._fleet_dead():
                    self._send_503_dead()
                    return
                t0 = time.perf_counter()
                # record failures too — excluding timeouts would hide the
                # slowest tail exactly when the backend is unhealthy
                try:
                    # payload-shaped failures are the client's fault (400);
                    # everything else (broker down, RESP protocol error,
                    # backend crash) is a server-side failure (502)
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        body = self.rfile.read(n) or b"{}"
                        req = json.loads(body)
                        instances = req.get("instances")
                        if instances is None:
                            instances = [req]   # single-instance body
                        text_rows = []
                        decoded = []
                        for inst in instances:
                            if "text" in inst:
                                if frontend.tokenizer is None:
                                    raise ValueError(
                                        "'text' instances need the "
                                        "frontend started with "
                                        "tokenizer=...")
                                if frontend.prompt_col in inst:
                                    raise ValueError(
                                        f"instance carries BOTH 'text' "
                                        f"and {frontend.prompt_col!r}: "
                                        f"ambiguous prompt — send one")
                                inst = dict(inst)
                                ids = np.asarray(
                                    frontend.tokenizer.encode(
                                        str(inst.pop("text"))).ids,
                                    np.int32)
                                if ids.size == 0:
                                    raise ValueError(
                                        "text tokenized to zero tokens")
                                inst[frontend.prompt_col] = ids
                                text_rows.append(True)
                            else:
                                text_rows.append(False)
                            decoded.append({k: _decode_value(v)
                                            for k, v in inst.items()})
                        for inst in decoded:
                            if "uri" in inst:
                                raise ValueError(
                                    "'uri' is reserved for the request id"
                                    " and cannot be an input column")
                    except (json.JSONDecodeError, KeyError, ValueError,
                            TypeError, AttributeError) as e:
                        self._send(400,
                                   {"error": f"{type(e).__name__}: {e}"})
                        return
                    preds = frontend._predict(decoded, text_rows)
                except BacklogFull as e:
                    frontend._count_rejection()
                    self._send_429(e.depth, str(e))
                    return
                except TimeoutError as e:
                    self._send(504, {"error": str(e)})
                    return
                except Exception as e:   # backend/broker failure
                    self._send(502, {"error": f"{type(e).__name__}: {e}"})
                    return
                finally:
                    frontend.latency.record(time.perf_counter() - t0)
                self._send(200, {"predictions": preds})

            def _do_cancel(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    uri = req.get("uri")
                    if not uri or not isinstance(uri, str):
                        raise ValueError("body needs a string 'uri'")
                except (json.JSONDecodeError, ValueError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                    return
                pair = frontend._acquire()
                try:
                    pair[0].cancel(uri)
                except Exception as e:
                    pair[0].close()
                    pair[1].close()
                    self._send(502, {"error": f"{type(e).__name__}: {e}"})
                    return
                frontend._release(pair)
                self._send(200, {"uri": uri, "status": "cancelling"})

            def _do_generate(self):
                t0 = time.perf_counter()
                if frontend._fleet_dead():
                    self._send_503_dead()
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(req, dict):
                        raise ValueError("body must be a JSON object")
                    deadline_ms = frontend._deadline_ms(
                        req.pop("deadline_ms", None),
                        self.headers.get("X-Request-Deadline-Ms"))
                    fields, stream = frontend._generate_fields(req)
                    if deadline_ms is not None:
                        fields["deadline"] = encode_deadline(deadline_ms)
                except (json.JSONDecodeError, KeyError, ValueError,
                        TypeError, AttributeError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                    return
                # brownout admission gate (docs/serving_qos.md): with
                # at least one replica live (the fleet-dead 503 above
                # owns zero-live), a browned-out class gets 429 + a
                # level-scaled Retry-After — an honest "come back
                # later", never a silent queue-then-shed
                level = frontend._brownout_level()
                if level > 0:
                    pri = (decode_priority(fields["priority"])
                           if "priority" in fields else "standard")
                    if not brownout_admit(level, pri):
                        frontend._count_shed(pri)
                        self._send_429(
                            None,
                            f"brownout level {level}: {pri}-class "
                            f"admissions are shed — retry later",
                            level=level)
                        return
                pair = frontend._acquire()
                inq, outq = pair
                # a client-supplied X-Request-Id becomes the uri end to
                # end (spans, logs, SSE start event) so the caller's own
                # correlation id works on every surface; unusable values
                # silently fall back to a uuid rather than rejecting
                uri = normalize_request_id(
                    self.headers.get("X-Request-Id")) or str(uuid.uuid4())
                echo = {"X-Request-Id": uri}
                with request_uri_context(uri):
                    try:
                        try:
                            inq.enqueue(uri, **fields)
                        except BacklogFull as e:
                            # the rejecting XADD/XDEL completed cleanly —
                            # the pair is protocol-safe to pool again
                            frontend._count_rejection()
                            self._send_429(e.depth, str(e))
                            frontend._release(pair)
                            return
                        if not stream:
                            r = outq.query(uri, timeout=frontend.timeout)
                            if r is None:
                                raise TimeoutError(
                                    f"result for {uri} not ready within "
                                    f"{frontend.timeout}s")
                            frontend._release(pair)
                            self._send(200, frontend._generate_result(
                                uri, np.asarray(r)), headers=echo)
                            return
                    except TimeoutError as e:
                        pair[0].close()
                        pair[1].close()
                        self._send(504, {"error": str(e), "uri": uri},
                                   headers=echo)
                        return
                    except Exception as e:
                        pair[0].close()
                        pair[1].close()
                        self._send(502,
                                   {"error": f"{type(e).__name__}: {e}",
                                    "uri": uri}, headers=echo)
                        return
                    finally:
                        frontend.latency.record(time.perf_counter() - t0)
                    self._stream_sse(pair, uri)

            def _stream_sse(self, pair, uri):
                """Tail the request's token stream onto the socket as
                SSE.  A failed write means the client hung up: cancel
                the request so its KV blocks free NOW, not at the TTL
                prune."""
                inq, outq = pair
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.send_header("X-Request-Id", uri)
                self.end_headers()
                self.close_connection = True
                clean = False
                try:
                    self.wfile.write(sse_event("start", {"uri": uri}))
                    self.wfile.flush()
                    for ev in outq.stream_events(
                            uri, timeout=frontend.timeout):
                        if "ping" in ev:
                            # heartbeat: touches the socket so a dead
                            # client surfaces between tokens
                            self.wfile.write(b": ping\n\n")
                        elif "token" in ev:
                            self.wfile.write(sse_event(
                                "token", {"index": ev["index"],
                                          "token": ev["token"]}))
                        elif "restart" in ev:
                            # crash-recovery redispatch: the token
                            # index resets to 0 and the generation
                            # re-streams — the client must drop what
                            # it buffered, never splice
                            self.wfile.write(sse_event(
                                "restart",
                                {"uri": uri,
                                 "attempt": ev["restart"]}))
                        elif "done" in ev:
                            self.wfile.write(sse_event(
                                "done", {"uri": uri}))
                            clean = True
                        elif "cancelled" in ev:
                            self.wfile.write(sse_event(
                                "cancelled", {"uri": uri}))
                            clean = True
                        else:
                            err = ev.get("error", "")
                            # admission-time deadline sheds get their
                            # OWN terminal event so clients can
                            # distinguish "arrived too late" from a
                            # server-side failure without parsing text
                            kind = ("deadline_exceeded"
                                    if "deadline_exceeded" in err
                                    else "error")
                            self.wfile.write(sse_event(
                                kind, {"uri": uri, "error": err}))
                            clean = True
                        self.wfile.flush()
                        if clean:
                            break
                except (BrokenPipeError, ConnectionResetError,
                        OSError):
                    frontend._count_disconnect(uri)
                    try:
                        inq.cancel(uri)
                    except Exception:
                        logger.exception(
                            "disconnect cancel failed for %r", uri)
                except TimeoutError as e:
                    try:
                        self.wfile.write(sse_event(
                            "error", {"uri": uri, "error": str(e)}))
                        self.wfile.flush()
                    except OSError:
                        pass
                    clean = True
                if clean:
                    frontend._release(pair)
                else:
                    # abandoned mid-generator: the RESP read state is
                    # clean (each execute completed) but the token
                    # stream wasn't consumed — don't pool a pair whose
                    # tok: key may still receive events
                    pair[0].close()
                    pair[1].close()

        self._server = ThreadingHTTPServer(("0.0.0.0", http_port), Handler)
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            # handshake on first read (per-connection handler thread), not
            # inside accept() — a stalled client must not block the single
            # accept loop and with it every other request
            self._server.socket = ctx.wrap_socket(
                self._server.socket, server_side=True,
                do_handshake_on_connect=False)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ---- pooled queue clients -----------------------------------------

    def _acquire(self):
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return (InputQueue(self.redis_host, self.redis_port,
                           max_backlog=self.max_backlog),
                OutputQueue(self.redis_host, self.redis_port))

    def _release(self, pair):
        with self._pool_lock:
            if len(self._pool) < self._pool_max:
                self._pool.append(pair)
                return
        pair[0].close()
        pair[1].close()

    def _predict(self, decoded, text_rows=None):
        # instances are decoded by the handler BEFORE enqueueing anything
        # (payload errors -> 400 without leaving orphaned work behind);
        # failures in here are backend-side by construction
        pair = self._acquire()
        inq, outq = pair
        try:
            uris = [inq.enqueue(str(uuid.uuid4()), **data)
                    for data in decoded]
            # one deadline for the whole request — per-uri waits share it
            # instead of compounding to n * timeout
            deadline = time.monotonic() + self.timeout
            preds = []
            for uri in uris:
                remaining = deadline - time.monotonic()
                r = outq.query(uri, timeout=max(0.0, remaining))
                if r is None:
                    raise TimeoutError(
                        f"result for {uri} not ready within "
                        f"{self.timeout}s")
                preds.append(np.asarray(r))
            out = []
            for i, p in enumerate(preds):
                if text_rows and i < len(text_rows) and text_rows[i]:
                    ids = p.astype(np.int64).ravel()
                    if self._eos_id is not None:
                        hits = np.nonzero(ids == self._eos_id)[0]
                        if hits.size:
                            ids = ids[:hits[0]]
                    out.append(self.tokenizer.decode(ids.tolist()))
                else:
                    out.append(p.tolist())
            preds = out
        except BaseException:
            # a failure may leave the RESP protocol state mid-message —
            # drop the pair rather than poisoning the pool
            pair[0].close()
            pair[1].close()
            raise
        else:
            self._release(pair)
            return preds

    # ---- front door (docs/serving_qos.md) -----------------------------

    def _generate_fields(self, req: dict):
        """/v1/generate JSON body -> input-queue fields.  Raises
        ``ValueError`` on anything payload-shaped (mapped to 400)."""
        body = dict(req)
        stream = bool(body.pop("stream", False))
        prompt = None
        if "text" in body:
            if self.tokenizer is None:
                raise ValueError("'text' needs the frontend started "
                                 "with tokenizer=...")
            if self.prompt_col in body:
                raise ValueError(
                    f"body carries BOTH 'text' and "
                    f"{self.prompt_col!r}: ambiguous prompt — send one")
            ids = np.asarray(
                self.tokenizer.encode(str(body.pop("text"))).ids,
                np.int32)
            if ids.size == 0:
                raise ValueError("text tokenized to zero tokens")
            prompt = ids
        elif self.prompt_col in body:
            prompt = np.asarray(
                _decode_value(body.pop(self.prompt_col)), np.int32)
        if prompt is None or prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(
                f"body needs 'text' or a 1-D {self.prompt_col!r} "
                f"token array")
        fields = {self.prompt_col: prompt}
        if "priority" in body:
            fields["priority"] = encode_priority(
                str(body.pop("priority")))
        if "tenant" in body:
            fields["tenant"] = encode_str_field(str(body.pop("tenant")))
        if "max_new" in body:
            fields["max_new"] = np.int32(int(body.pop("max_new")))
        if "temperature" in body:
            fields["temperature"] = np.float32(
                float(body.pop("temperature")))
        if "seed" in body:
            fields["seed"] = np.int64(int(body.pop("seed")))
        if "top_p" in body:
            fields["top_p"] = np.float32(float(body.pop("top_p")))
        if "prefix" in body:
            fields["prefix"] = np.int32(int(body.pop("prefix")))
        if stream:
            fields["stream"] = np.int32(1)
        if body:
            raise ValueError(
                f"unknown /v1/generate fields {sorted(body)}")
        return fields, stream

    def _generate_result(self, uri: str, tokens: np.ndarray) -> dict:
        out = {"uri": uri,
               "tokens": tokens.astype(np.int64).ravel().tolist()}
        if self.tokenizer is not None:
            ids = tokens.astype(np.int64).ravel()
            if self._eos_id is not None:
                hits = np.nonzero(ids == self._eos_id)[0]
                if hits.size:
                    ids = ids[:hits[0]]
            out["text"] = self.tokenizer.decode(ids.tolist())
        return out

    def _fleet_dead(self) -> bool:
        """True only when the attached serving job positively reports
        ZERO live pumps (``accepting_replicas() == 0``): detached
        frontends and micro-batch jobs (``None``) keep accepting —
        this guard is about not swallowing submits the router can
        never place."""
        if self.serving is None:
            return False
        try:
            return self.serving.accepting_replicas() == 0
        except Exception:
            return False

    def _retry_after(self, depth=None, level: int = 0) -> int:
        """Finite Retry-After for a 429: queue depth over the engine's
        recent completion throughput (frontdoor.retry_after_s clamps
        it, and the estimator falls back to a default rate, so the
        header is finite even on a cold or detached frontend).
        ``level`` is the brownout ladder level — the hint scales up
        monotonically with it, clamped finite at every level."""
        if depth is None and self.serving is not None:
            try:
                depth = self.serving.backlog()
            except Exception:
                depth = None
        if depth is None:
            depth = self.max_backlog
        if self.serving is not None:
            try:
                self._throughput.observe(
                    float(self.serving.telemetry.c_finished.value))
            except Exception:
                pass
        return retry_after_s(int(depth), self._throughput.rate(),
                             level=level)

    def _brownout_level(self) -> int:
        """The attached fleet's brownout ladder level (0 detached or
        when the controller is off)."""
        if self.serving is None:
            return 0
        try:
            return int(self.serving.brownout_level())
        except Exception:
            return 0

    def _deadline_ms(self, body_value, header_value):
        """Merge the ``deadline_ms`` body field and the
        ``X-Request-Deadline-Ms`` header into ONE validated relative
        budget (milliseconds), or None when neither was sent.  Raises
        ``ValueError`` (the 400 path) on anything invalid, or when
        both arrive and disagree — a split-brain deadline is a client
        bug, not a tiebreak."""
        vals = []
        if header_value is not None:
            vals.append(validate_deadline_ms(header_value))
        if body_value is not None:
            vals.append(validate_deadline_ms(body_value))
        if not vals:
            return None
        if len(vals) == 2 and vals[0] != vals[1]:
            raise ValueError(
                f"X-Request-Deadline-Ms header ({vals[0]}) and "
                f"deadline_ms body field ({vals[1]}) disagree — send "
                f"one, or the same value in both")
        return vals[0]

    def health(self) -> dict:
        """/healthz body: readiness for LOAD, not just liveness —
        admission-queue depth vs. cap, accepting/backpressure state,
        and the engine mode flags."""
        out = {"status": "ok", "accepting": True,
               "max_backlog": self.max_backlog}
        if self.serving is None:
            return out
        try:
            depth = int(self.serving.backlog())
        except Exception:
            depth = None
        accepting = (depth is None or not self.max_backlog
                     or depth < self.max_backlog)
        fleet_dead = self._fleet_dead()
        if fleet_dead:
            # zero live replicas beats any backlog arithmetic: the
            # fleet cannot place work at all until a pump returns
            accepting = False
        out.update({
            "backlog": depth,
            "accepting": accepting,
            "backpressure": not accepting,
            "engine": self.serving.mode_flags(),
        })
        if fleet_dead:
            out["live_replicas"] = 0
        lvl = self._brownout_level()
        out["brownout"] = {"level": lvl,
                           "admitting": list(brownout_classes(lvl))}
        wd = getattr(self.serving, "watchdog", None)
        if wd is not None:
            # the routing view of the SLO score: per-class goodput and
            # total breach counts (full detail lives at /debug/flight)
            st = wd.status()["per_class"]
            out["slo"] = {
                "goodput": {c: round(s["goodput"], 4)
                            for c, s in st.items()},
                "breaches": {c: sum(s["breaches"].values())
                             for c, s in st.items()},
            }
        if not accepting:
            out["retry_after_s"] = self._retry_after(depth)
        return out

    def _count_rejection(self) -> None:
        self.c_rejected.inc()
        if self.serving is not None:
            try:
                self.serving.telemetry.backpressure_rejection()
            except Exception:
                pass

    def _count_shed(self, priority: str) -> None:
        if self.serving is not None:
            try:
                self.serving.telemetry.brownout_shed(priority)
            except Exception:
                pass

    def _count_disconnect(self, uri: str) -> None:
        self.c_disconnects.inc()
        if self.serving is not None:
            try:
                self.serving.telemetry.stream_disconnect(uri)
            except Exception:
                pass

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "HttpFrontend":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        logger.info("HttpFrontend on :%d -> redis %s:%d", self.port,
                    self.redis_host, self.redis_port)
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # ---- observability ------------------------------------------------

    def metrics(self) -> dict:
        """Legacy JSON metrics dict (``GET /metrics?format=json``)."""
        out = {"latency": self.latency.snapshot()}
        if self.serving is not None:
            out["serving"] = dict(self.serving.stats)
            try:
                out["backlog"] = self.serving.backlog()
            except Exception:
                out["backlog"] = None
        return out

    def _registries(self) -> list:
        regs = [self.registry]
        if self.serving is not None:
            tm = getattr(self.serving, "telemetry", None)
            if tm is not None:
                regs.append(tm.metrics)
            etm = getattr(getattr(self.serving, "engine", None),
                          "telemetry", None)
            if etm is not None and all(etm.metrics is not r
                                       for r in regs):
                regs.append(etm.metrics)
            # multi-replica: every replica's registry rides along; the
            # exposition dedupes by name (first registration wins), so
            # shared families keep replica 0's sample while the
            # zoo_router_*_r{r} families are per-replica by NAME
            for rtm in getattr(self.serving, "telemetries", ()) or ():
                if all(rtm.metrics is not r for r in regs):
                    regs.append(rtm.metrics)
        return regs

    def prometheus(self) -> str:
        """Text exposition over every reachable registry: the
        frontend's own HTTP latency, the serving job's request
        counters, and (continuous mode) the engine's TTFT/TPOT/queue/
        pool metrics.  Distinct name prefixes per layer mean the merge
        cannot collide."""
        return render_prometheus(*self._registries())

    def trace(self) -> Optional[dict]:
        """Chrome trace-event JSON from the nearest telemetry (engine
        first — its event ring holds the request spans), or None when
        the frontend runs without an attached serving job."""
        if self.serving is None:
            return None
        tm = getattr(getattr(self.serving, "engine", None),
                     "telemetry", None) \
            or getattr(self.serving, "telemetry", None)
        return tm.dump_trace() if tm is not None else None

    def debug_flight(self, last: int = 100) -> Optional[dict]:
        """``GET /debug/flight``: the live view of what a diagnostic
        bundle would capture — the flight ring's newest ``last`` tick
        records, the SLO watchdog's status, and the anomaly-bundle
        history.  None without an attached serving job."""
        if self.serving is None:
            return None
        fl = getattr(getattr(self.serving, "engine", None),
                     "flight", None) \
            or getattr(self.serving, "flight", None)
        out = {
            "capacity": fl.capacity if fl is not None else 0,
            "n_retained": len(fl) if fl is not None else 0,
            "ticks": fl.snapshot(last=last) if fl is not None else [],
        }
        wd = getattr(self.serving, "watchdog", None)
        if wd is not None:
            out["slo"] = wd.status()
        an = getattr(self.serving, "anomalies", None)
        if an is not None:
            out["anomalies"] = an.history()
        return out

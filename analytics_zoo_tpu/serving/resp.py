"""Minimal Redis-protocol (RESP2) server + client.

Reference context (SURVEY.md §2.6): Cluster Serving's data plane is Redis
streams — clients XADD to an input stream, the serving job XREADGROUPs
batches, results land in output hashes (ref: serving/FlinkRedisSource.scala,
FlinkRedisSink.scala, pyzoo/zoo/serving/client.py).

The rebuild keeps Redis as the WIRE PROTOCOL for client parity but ships
its own in-process broker: a tiny RESP2 server (thread-per-connection —
the command set is tiny and the TPU forward pass dominates) implementing
the command subset Cluster Serving uses: PING, XADD/XLEN/XREAD/XRANGE/
XDEL/XTRIM, HSET/HGETALL/DEL, GET/SET, FLUSHDB.  A real ``redis-server``
can be dropped in unchanged — the client speaks standard RESP.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple


# ---------------------------------------------------------------------------
# RESP2 encoding
# ---------------------------------------------------------------------------

def encode(obj) -> bytes:
    """Python -> RESP2: bytes/str -> bulk, int -> integer, list -> array,
    None -> null bulk, Exception -> error, bool ok-marker via _OK."""
    if obj is None:
        return b"$-1\r\n"
    if isinstance(obj, _OK):
        return b"+" + obj.msg.encode() + b"\r\n"
    if isinstance(obj, Exception):
        return b"-ERR " + str(obj).encode() + b"\r\n"
    if isinstance(obj, bool):
        return encode(int(obj))
    if isinstance(obj, int):
        return b":" + str(obj).encode() + b"\r\n"
    if isinstance(obj, str):
        obj = obj.encode()
    if isinstance(obj, (bytes, bytearray)):
        return b"$" + str(len(obj)).encode() + b"\r\n" + bytes(obj) + b"\r\n"
    if isinstance(obj, (list, tuple)):
        out = b"*" + str(len(obj)).encode() + b"\r\n"
        return out + b"".join(encode(x) for x in obj)
    raise TypeError(f"cannot RESP-encode {type(obj)}")


class _OK:
    def __init__(self, msg: str = "OK"):
        self.msg = msg


class _Reader:
    """Buffered RESP2 parser over a socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def _read_until(self, n: Optional[int] = None) -> bytes:
        if n is None:  # read a \r\n-terminated line
            while b"\r\n" not in self.buf:
                chunk = self.sock.recv(65536)
                if not chunk:
                    raise ConnectionError("peer closed")
                self.buf += chunk
            line, self.buf = self.buf.split(b"\r\n", 1)
            return line
        while len(self.buf) < n + 2:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("peer closed")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n + 2:]
        return data

    def read(self):
        line = self._read_until()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RedisError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            return None if n == -1 else self._read_until(n)
        if t == b"*":
            n = int(rest)
            return None if n == -1 else [self.read() for _ in range(n)]
        raise ValueError(f"bad RESP type byte {t!r}")


class RedisError(Exception):
    pass


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class Stream:
    def __init__(self):
        self.entries: List[Tuple[bytes, List[bytes]]] = []  # (id, kv flat)
        self.seq = itertools.count(1)
        self.cond = threading.Condition()
        # consumer groups: name -> {"last": delivered-up-to id,
        #                           "pending": {id: consumer}}
        # (the mechanism behind horizontally-scaled serving workers —
        # ref: Flink source parallelism over XREADGROUP)
        self.groups: Dict[bytes, Dict] = {}

    def add(self, fields: List[bytes]) -> bytes:
        eid = f"{int(time.time() * 1000)}-{next(self.seq)}".encode()
        with self.cond:
            self.entries.append((eid, fields))
            self.cond.notify_all()
        return eid


def _id_after(eid: bytes, last: bytes) -> bool:
    def parse(x: bytes):
        a, _, b = x.partition(b"-")
        return (int(a), int(b or 0))
    return parse(eid) > parse(last)


def _range_bound(x: bytes, *, high: bool) -> Tuple[int, int]:
    """Parse an XRANGE start/end bound: '-'/'+' sentinels, and a bare
    ms timestamp means seq 0 at the start bound / seq max at the end
    bound (Redis semantics — both bounds are inclusive)."""
    if x == b"-":
        return (0, 0)
    if x == b"+":
        return (1 << 63, 1 << 63)
    a, dash, b = x.partition(b"-")
    if dash:
        return (int(a), int(b))
    return (int(a), (1 << 63) if high else 0)


def _scan_read_opts(args: List[bytes], i: int):
    """Parse [COUNT c] [BLOCK ms] up to STREAMS; returns (count, block_ms,
    index-of-STREAMS) — shared by XREAD and XREADGROUP."""
    count, block_ms = None, None
    while args[i].upper() != b"STREAMS":
        if args[i].upper() == b"COUNT":
            count = int(args[i + 1])
        elif args[i].upper() == b"BLOCK":
            block_ms = int(args[i + 1])
        i += 2
    return count, block_ms, i


def _await_fresh(s: "Stream", block_ms, select):
    """Run `select()` under s.cond until it yields entries or the block
    window expires.  select() may mutate claim state (XREADGROUP) — it is
    always called with the stream lock held, so claims are atomic."""
    deadline = None if block_ms is None else \
        time.monotonic() + block_ms / 1000.0
    while True:
        with s.cond:
            got = select()
            if got:
                return got
            if deadline is None:
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            s.cond.wait(remaining)


class RespServer:
    """In-process broker. start() binds 127.0.0.1:port (0 = ephemeral)."""

    def __init__(self, port: int = 0):
        self.port = port
        self.streams: Dict[bytes, Stream] = {}
        self.hashes: Dict[bytes, Dict[bytes, bytes]] = {}
        self.kv: Dict[bytes, bytes] = {}
        self.sets: Dict[bytes, set] = {}
        self.lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "RespServer":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self

    def stop(self):
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # small request/response frames: Nagle + delayed-ACK would add
            # ~40ms per reply, dwarfing the model forward itself
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        reader = _Reader(conn)
        try:
            while not self._stop.is_set():
                req = reader.read()
                if req is None:
                    return
                try:
                    resp = self._dispatch([bytes(x) if isinstance(
                        x, (bytes, bytearray)) else str(x).encode()
                        for x in req])
                except RedisError as e:
                    resp = e
                except Exception as e:  # command bug -> error reply
                    resp = RedisError(str(e))
                conn.sendall(encode(resp))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    # ---- commands -----------------------------------------------------

    def _stream(self, key: bytes) -> Stream:
        with self.lock:
            if key not in self.streams:
                self.streams[key] = Stream()
            return self.streams[key]

    def _dispatch(self, args: List[bytes]):
        cmd = args[0].upper()
        if cmd == b"PING":
            return _OK("PONG")
        if cmd == b"FLUSHDB":
            with self.lock:
                self.streams.clear()
                self.hashes.clear()
                self.kv.clear()
                self.sets.clear()
            return _OK()
        if cmd == b"SET":
            self.kv[args[1]] = args[2]
            return _OK()
        if cmd == b"GET":
            return self.kv.get(args[1])
        if cmd == b"DEL":
            n = 0
            with self.lock:
                for k in args[1:]:
                    n += (self.kv.pop(k, None) is not None) + \
                        (self.hashes.pop(k, None) is not None) + \
                        (self.streams.pop(k, None) is not None) + \
                        (self.sets.pop(k, None) is not None)
            return n
        if cmd == b"SADD":
            with self.lock:
                s = self.sets.setdefault(args[1], set())
                before = len(s)
                s.update(args[2:])
                return len(s) - before
        if cmd == b"SREM":
            with self.lock:
                s = self.sets.get(args[1], set())
                before = len(s)
                s.difference_update(args[2:])
                return before - len(s)
        if cmd == b"SMEMBERS":
            with self.lock:
                return sorted(self.sets.get(args[1], set()))
        if cmd == b"SCARD":
            with self.lock:
                return len(self.sets.get(args[1], set()))
        if cmd == b"HSET":
            h = self.hashes.setdefault(args[1], {})
            kvs = args[2:]
            added = 0
            for i in range(0, len(kvs), 2):
                added += kvs[i] not in h
                h[kvs[i]] = kvs[i + 1]
            return added
        if cmd == b"HGETALL":
            h = self.hashes.get(args[1], {})
            out: List[bytes] = []
            for k, v in h.items():
                out.extend([k, v])
            return out
        if cmd == b"XADD":
            # XADD key [MAXLEN n] id field value ...
            i = 2
            if args[i].upper() == b"MAXLEN":
                i += 2
            i += 1  # the id (we always auto-assign '*' semantics)
            return self._stream(args[1]).add(list(args[i:]))
        if cmd == b"XLEN":
            return len(self._stream(args[1]).entries)
        if cmd == b"XRANGE":
            # XRANGE key start end [COUNT n] — inclusive id range; the
            # router leans on exact-id lookups (`XRANGE k eid eid`) to
            # re-read a dead replica's in-flight entries, so honouring
            # the bounds is correctness-critical, not a nicety.
            s = self._stream(args[1])
            lo = _range_bound(args[2], high=False)
            hi = _range_bound(args[3], high=True)
            count = int(args[5]) if len(args) > 5 and \
                args[4].upper() == b"COUNT" else None

            def _pid(eid: bytes) -> Tuple[int, int]:
                a, _, b = eid.partition(b"-")
                return (int(a), int(b or 0))
            with s.cond:
                got = [[eid, fv] for eid, fv in s.entries
                       if lo <= _pid(eid) <= hi]
            return got[:count] if count else got
        if cmd == b"XDEL":
            s = self._stream(args[1])
            ids = set(args[2:])
            with s.cond:
                before = len(s.entries)
                s.entries = [e for e in s.entries if e[0] not in ids]
                return before - len(s.entries)
        if cmd == b"XTRIM":
            s = self._stream(args[1])
            # XTRIM key MAXLEN n
            n = int(args[3])
            with s.cond:
                cut = max(0, len(s.entries) - n)
                s.entries = s.entries[cut:]
                return cut
        if cmd == b"XREAD":
            # XREAD [COUNT c] [BLOCK ms] STREAMS key id
            count, block_ms, i = _scan_read_opts(args, 1)
            key, last = args[i + 1], args[i + 2]
            s = self._stream(key)
            if last == b"$":
                with s.cond:
                    last = s.entries[-1][0] if s.entries else b"0-0"

            def select():
                fresh = [e for e in s.entries if _id_after(e[0], last)]
                return fresh[:count] if count else fresh

            got = _await_fresh(s, block_ms, select)
            if got is None:
                return None
            return [[key, [[eid, fv] for eid, fv in got]]]
        if cmd == b"XGROUP":
            # XGROUP CREATE key group id [MKSTREAM]
            if args[1].upper() != b"CREATE":
                raise RedisError("only XGROUP CREATE is supported")
            s = self._stream(args[2])
            start = args[4]
            with s.cond:
                if args[3] in s.groups:
                    raise RedisError("BUSYGROUP Consumer Group name "
                                     "already exists")
                if start == b"$":
                    start = s.entries[-1][0] if s.entries else b"0-0"
                s.groups[args[3]] = {"last": start, "pending": {}}
            return _OK()
        if cmd == b"XREADGROUP":
            # XREADGROUP GROUP g consumer [COUNT c] [BLOCK ms] STREAMS key >
            group, consumer = args[2], args[3]
            count, block_ms, i = _scan_read_opts(args, 4)
            key, cursor = args[i + 1], args[i + 2]
            if cursor != b">":
                raise RedisError("only the '>' cursor is supported")
            s = self._stream(key)
            with s.cond:
                if group not in s.groups:
                    raise RedisError(
                        f"NOGROUP no such consumer group {group.decode()}")

            def select():
                # atomic claim under s.cond (held by _await_fresh):
                # advance the group pointer so no other consumer sees these
                g = s.groups.get(group)
                if g is None:
                    return None
                fresh = [e for e in s.entries
                         if _id_after(e[0], g["last"])]
                if not fresh:
                    return None
                if count:
                    fresh = fresh[:count]
                g["last"] = fresh[-1][0]
                for eid, _ in fresh:
                    g["pending"][eid] = consumer
                return fresh

            got = _await_fresh(s, block_ms, select)
            if got is None:
                return None
            return [[key, [[eid, fv] for eid, fv in got]]]
        if cmd == b"XACK":
            # XACK key group id [id ...]
            s = self._stream(args[1])
            with s.cond:
                g = s.groups.get(args[2])
                if g is None:
                    return 0
                n = 0
                for eid in args[3:]:
                    n += g["pending"].pop(eid, None) is not None
                return n
        if cmd == b"XPENDING":
            # XPENDING key group -> [count, min-id, max-id, consumers]
            s = self._stream(args[1])
            with s.cond:
                g = s.groups.get(args[2])
                if g is None:
                    return [0, None, None, None]
                ids = sorted(g["pending"])
                return [len(ids), ids[0] if ids else None,
                        ids[-1] if ids else None, None]
        raise RedisError(f"unknown command {cmd.decode()}")


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class RespClient:
    """Tiny RESP2 client (drop-in for redis-py's execute_command subset);
    thread-safe via a per-call lock."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.reader = _Reader(self.sock)
        self.lock = threading.Lock()

    def execute(self, *args):
        payload = encode([a if isinstance(a, (bytes, bytearray))
                          else str(a).encode() for a in args])
        with self.lock:
            self.sock.sendall(payload)
            return self.reader.read()

    def pipeline(self, commands):
        """Send many commands in one write, read all replies (real Redis
        pipelining — one round-trip for N commands).

        Every reply is consumed even when some are errors — bailing out
        mid-stream would leave unread replies in the buffer and desync
        every later command on this connection.  The first error reply is
        raised after the stream is drained."""
        payload = b"".join(
            encode([a if isinstance(a, (bytes, bytearray))
                    else str(a).encode() for a in cmd])
            for cmd in commands)
        with self.lock:
            self.sock.sendall(payload)
            replies, first_err = [], None
            for _ in commands:
                try:
                    replies.append(self.reader.read())
                except RedisError as e:   # error reply: keep draining
                    replies.append(e)
                    first_err = first_err or e
        if first_err is not None:
            raise first_err
        return replies

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass

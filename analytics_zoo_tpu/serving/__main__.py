"""``python -m analytics_zoo_tpu.serving config.yaml`` — the
``cluster-serving-start`` entry point (ref: scripts/cluster-serving/
cluster-serving-start reading config.yaml): parse the config, load the
model artifact it names, start the serving loop, block until SIGINT.

``--embedded-broker`` runs the bundled RESP broker in-process (local/
single-box deployments); without it the config's redis host:port must
already be running.

Engine modes come from the config's ``params`` block (see
ServingConfig): ``engine_paged`` / ``engine_chunked`` /
``engine_speculation_k`` compose freely on a draft-loaded model —
paged blocks, budgeted prefill chunks, and draft-verify decoding are
one scheduler, not three exclusive engines (docs/serving_memory.md
'Composed modes').
"""

import argparse
import signal
import sys
import threading


def main(argv=None, block=True):
    ap = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.serving",
        description="Start a Cluster Serving job from a config.yaml")
    ap.add_argument("config", help="path to config.yaml")
    ap.add_argument("--embedded-broker", action="store_true",
                    help="run the bundled RESP broker in-process")
    ap.add_argument("--http-port", type=int, default=None,
                    help="also start the HTTP frontend (ref: "
                         "FrontEndApp) on this port (0 = an ephemeral "
                         "port, printed in the banner)")
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu) — env vars "
                         "are too late once sitecustomize imports jax")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="on shutdown, dump the telemetry event ring "
                         "as Chrome trace-event JSON to PATH (load at "
                         "https://ui.perfetto.dev); the same data is "
                         "live at GET /trace while serving")
    args = ap.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from analytics_zoo_tpu.serving import ClusterServing

    # default signal behavior DURING assembly (a hung model load or
    # broker connect must stay killable with Ctrl-C/SIGTERM); graceful
    # handlers go in after start() but BEFORE the banner, so a
    # supervisor signalling the instant it sees the banner still gets a
    # clean shutdown rather than the SIGTERM default
    serving = ClusterServing.from_config(
        args.config, embedded_broker=args.embedded_broker).start()
    frontend = None
    if args.http_port is not None:
        from analytics_zoo_tpu.serving import HttpFrontend

        try:
            frontend = HttpFrontend(
                redis_host=serving.config.redis_host,
                redis_port=serving.port, http_port=args.http_port,
                serving=serving).start()
        except BaseException:
            # a bind failure (port in use) must not abandon the already-
            # started serving loop / broker / decode pool
            serving.stop()
            raise
    stop = threading.Event()
    banner = (f"serving up on {serving.config.redis_host}:"
              f"{serving.port}"
              + (f", http on :{frontend.port}" if frontend else "")
              + " (Ctrl-C to stop)")

    def shutdown():
        if frontend is not None:
            frontend.stop()
        serving.stop()
        if args.trace:
            serving.telemetry.dump_trace(args.trace)
            print(f"trace written to {args.trace}", flush=True)

    if not block:       # tests drive the assembled stack directly
        return serving, frontend, shutdown
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    print(banner, flush=True)
    stop.wait()
    shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""``python -m analytics_zoo_tpu.serving config.yaml`` — the
``cluster-serving-start`` entry point (ref: scripts/cluster-serving/
cluster-serving-start reading config.yaml): parse the config, load the
model artifact it names, start the serving loop, block until SIGINT.

``--embedded-broker`` runs the bundled RESP broker in-process (local/
single-box deployments); without it the config's redis host:port must
already be running.
"""

import argparse
import signal
import sys
import threading


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.serving",
        description="Start a Cluster Serving job from a config.yaml")
    ap.add_argument("config", help="path to config.yaml")
    ap.add_argument("--embedded-broker", action="store_true",
                    help="run the bundled RESP broker in-process")
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu) — env vars "
                         "are too late once sitecustomize imports jax")
    args = ap.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from analytics_zoo_tpu.serving import ClusterServing

    # default signal behavior DURING assembly (a hung model load or
    # broker connect must stay killable with Ctrl-C/SIGTERM); graceful
    # handlers go in after start() but BEFORE the banner, so a
    # supervisor signalling the instant it sees the banner still gets a
    # clean shutdown rather than the SIGTERM default
    serving = ClusterServing.from_config(
        args.config, embedded_broker=args.embedded_broker).start()
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    print(f"serving up on {serving.config.redis_host}:"
          f"{serving.port} (Ctrl-C to stop)", flush=True)
    stop.wait()
    serving.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

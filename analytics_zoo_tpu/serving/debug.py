"""Diagnostic-bundle renderer — ``python -m analytics_zoo_tpu.serving.debug``.

Turns a flight bundle directory (serving/flight.py ``dump_bundle``)
into a terminal post-mortem: what triggered it, the tick timeline
leading up to the trigger, the SLO score at the moment of capture, and
the per-request lifecycle histories reconstructed from the bundled
Perfetto trace — the "what was the engine doing in the 30 seconds
before this" answer, offline, from one directory (docs/debugging.md
is the runbook).

Usage::

    python -m analytics_zoo_tpu.serving.debug <bundle-dir> \\
        [--ticks N] [--requests N] [--uri URI] [--logs N] [--replay]

``--uri`` filters the request histories to one request id (the same
id the X-Request-Id header / SSE start event / structured logs
carry).  ``--replay`` additionally runs the discrete-event simulator
(``serving/sim/``, docs/simulation.md) over the bundle: re-derives the
request metrics from the trace, cross-checks them against the recorded
watchdog score, re-simulates the recorded schedule, and prints the
simulated-vs-recorded SLO deltas.  Exit code 0 on a rendered bundle,
1 when ``--replay``'s cross-check breached its tolerances, 2 on an
unreadable (or unknown-schema) one.

Stdlib-only by design: rendering a bundle must work on a machine with
nothing but Python — no jax, no numpy, no serving stack.  (The ``-m``
spelling imports the package root, which needs the full deps; on a
bare box run the file directly: ``python path/to/serving/debug.py
<bundle-dir>``.)  ``--replay`` keeps that contract: the simulator is
itself stdlib-only, and the bare-file spelling bootstraps it through a
synthetic parent package so its relative imports resolve without
installing anything.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

# trace events that mark request-lifecycle edges, in render order
_LIFECYCLE = ("enqueued", "queue_wait", "admitted", "first_token",
              "preempted", "request", "request_error",
              "request_cancelled", "request_abandoned",
              "stream_disconnect")

# tick-record columns: (header, key, width); missing keys render "-"
_TICK_COLS = (("seq", "seq", 6), ("kind", "kind", 12),
              ("ms", "dur_ms", 8), ("act", "active", 4),
              ("dec", "decode_rows", 4), ("pre", "prefill_rows", 4),
              ("que", "queue_depth", 4), ("free", "free_blocks", 5),
              ("dfree", "draft_free_blocks", 6),
              ("fail", "alloc_failures", 5),
              ("strk", "alloc_fail_streak", 5),
              ("pre+", "preempted", 5), ("cmp", "compiles", 4))


def _load_json(path: str) -> Optional[Any]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fmt_cell(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.2f}"
    return str(v)


def render_ticks(ticks: List[Dict[str, Any]], last: int,
                 out) -> None:
    tail = ticks[-last:]
    print(f"tick timeline ({len(tail)} of {len(ticks)} retained ticks, "
          f"newest last):", file=out)
    header = " ".join(h.rjust(w) for h, _, w in _TICK_COLS)
    print("  " + header, file=out)
    for t in tail:
        row = " ".join(_fmt_cell(t.get(k)).rjust(w)
                       for _, k, w in _TICK_COLS)
        print("  " + row, file=out)
    # the rows a tick carried (uri lists are too wide for the table)
    if tail:
        t = tail[-1]
        dec, pre = t.get("decode_uris"), t.get("prefill_uris")
        if dec is not None or pre is not None:
            print(f"  last tick rows: decode={dec or []} "
                  f"prefill={pre or []}", file=out)


def request_histories(trace: Dict[str, Any]
                      ) -> Dict[str, List[Dict[str, Any]]]:
    """Per-uri lifecycle edges from the bundled Chrome trace, each a
    dict of (name, ts seconds, args), sorted by time."""
    per_uri: Dict[str, List[Dict[str, Any]]] = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") not in ("X", "i"):
            continue
        name = ev.get("name")
        if name not in _LIFECYCLE:
            continue
        args = ev.get("args") or {}
        uri = args.get("uri")
        if not uri:
            continue
        per_uri.setdefault(uri, []).append(
            {"name": name, "ts": float(ev["ts"]) / 1e6,
             "dur": (float(ev.get("dur", 0.0)) / 1e6
                     if ev.get("ph") == "X" else None),
             "tid": ev.get("tid"), "args": args})
    for evs in per_uri.values():
        # spans order by their END ("request" starts at admission but
        # means "finished" — it must render after the tokens it spans)
        evs.sort(key=lambda e: e["ts"] + (e["dur"] or 0.0))
    return per_uri


def render_request(uri: str, evs: List[Dict[str, Any]], out) -> None:
    t0 = evs[0]["ts"]
    parts = []
    for e in evs:
        label = e["name"]
        if label == "admitted":
            label = f"admitted slot {e['tid']}"
        elif label == "request":
            end = e["ts"] + (e["dur"] or 0.0)
            parts.append(f"finished +{end - t0:.3f}s "
                         f"({e['args'].get('tokens', '?')} tokens)")
            continue
        elif label == "queue_wait":
            label = f"queue_wait {e['dur']:.3f}s"
            parts.append(label)
            continue
        parts.append(f"{label} +{e['ts'] - t0:.3f}s")
    print(f"  {uri}: " + " -> ".join(parts), file=out)


def render_slo(slo: Dict[str, Any], out) -> None:
    print("SLO score at capture:", file=out)
    for cls, s in (slo.get("per_class") or {}).items():
        br = s.get("breaches") or {}
        print(f"  {cls:<12} goodput={s.get('goodput', 1.0):.3f} "
              f"finished={s.get('finished', 0)} "
              f"breaches(ttft/tpot/queue)="
              f"{br.get('ttft', 0)}/{br.get('tpot', 0)}/"
              f"{br.get('queue_wait', 0)}", file=out)
    recent = slo.get("recent_breaches") or []
    for b in recent[-3:]:
        print(f"  recent: {b.get('class')}/{b.get('metric')} "
              f"{b.get('value_s')}s > {b.get('target_s')}s "
              f"uri={b.get('uri')}", file=out)


def _load_sim_replay():
    """Import ``serving.sim.replay`` in either spelling of this CLI.

    Under ``python -m`` the package-relative import just works.  As a
    bare file (``python path/to/debug.py``) there is no parent package,
    so build a synthetic one whose ``__path__`` is this directory and
    import the sim through it — the sim's ``from ..policy import ...``
    then resolves to the sibling ``policy.py`` file, and the whole
    chain stays stdlib-only (no numpy, no jax, nothing installed)."""
    if __package__:
        from .sim import replay  # type: ignore[no-redef]
        return replay
    import importlib
    import types
    name = "_azt_serving_bare"
    pkg = sys.modules.get(name)
    if pkg is None:
        pkg = types.ModuleType(name)
        pkg.__path__ = [os.path.dirname(os.path.abspath(__file__))]
        sys.modules[name] = pkg
    return importlib.import_module(f"{name}.sim.replay")


def render_replay(path: str, out, seed: int = 0) -> int:
    """Run the simulator's replay pipeline over a bundle and print the
    simulated-vs-recorded SLO deltas.  Returns a process exit code (0
    crosscheck ok, 1 tolerance breach, 2 unreadable/unknown schema)."""
    replay = _load_sim_replay()
    try:
        report = replay.replay_bundle(path, seed=seed)
    except (FileNotFoundError, ValueError) as e:
        # SchemaVersionError subclasses ValueError
        print(f"error: replay failed: {e}", file=sys.stderr)
        return 2
    print("replay (serving/sim, docs/simulation.md):", file=out)
    rec_cls = report.get("recorded_slo") or {}
    for cls, obs in (report["observed"].get("per_class") or {}).items():
        rec = rec_cls.get(cls) or {}
        sim = ((report.get("simulated") or {}).get("per_class")
               or {}).get(cls) or {}
        print(f"  {cls:<12} goodput recorded="
              f"{rec.get('goodput', float('nan')):.3f} "
              f"observed={obs['goodput']:.3f} "
              f"simulated={sim.get('goodput', float('nan')):.3f}  "
              f"ttft p99 observed={obs['ttft']['p99'] * 1e3:.1f}ms "
              f"simulated="
              f"{(sim.get('ttft') or {}).get('p99', 0.0) * 1e3:.1f}ms",
              file=out)
    for c in report["crosscheck"]["checks"]:
        if c["verdict"] == "skipped_ring_truncated":
            print(f"  crosscheck {c['class']}: skipped (trace ring "
                  f"truncated: {c['observed_finished']} of "
                  f"{c['recorded_finished']} visible)", file=out)
        else:
            print(f"  crosscheck {c['class']}: delta {c['delta']:+.3f} "
                  f"(tolerance {c['tolerance']}) [{c['verdict']}]",
                  file=out)
    print(f"  crosscheck: "
          f"{'OK' if report['ok'] else 'BREACH'}", file=out)
    return 0 if report["ok"] else 1


def render_bundle(path: str, *, ticks: int = 20, requests: int = 10,
                  uri: Optional[str] = None, logs: int = 5,
                  out=None) -> int:
    """Render one bundle directory; returns a process exit code."""
    out = out or sys.stdout
    manifest = _load_json(os.path.join(path, "manifest.json"))
    if manifest is None:
        print(f"error: {path!r} is not a diagnostic bundle "
              f"(no readable manifest.json)", file=sys.stderr)
        return 2
    print(f"bundle: {path}", file=out)
    print(f"reason: {manifest.get('reason')}  "
          f"written: {manifest.get('written_at')}", file=out)
    detail = manifest.get("detail") or {}
    if detail:
        print(f"trigger detail: "
              f"{json.dumps(detail, sort_keys=True)}", file=out)

    config = _load_json(os.path.join(path, "config.json")) or {}
    if config:
        keys = ("continuous_batching", "engine_slots", "engine_paged",
                "engine_blocks", "engine_block_size", "engine_kernel",
                "engine_kv_dtype", "engine_chunked",
                "engine_speculation_k", "qos_enabled",
                "flight_capacity")
        print("config: " + " ".join(
            f"{k}={config[k]}" for k in keys if k in config), file=out)

    flight = _load_json(os.path.join(path, "flight.json")) or {}
    tick_recs = flight.get("ticks") or []
    if tick_recs:
        render_ticks(tick_recs, ticks, out)
    else:
        print("tick timeline: empty (recorder disabled or no ticks "
              "before capture)", file=out)

    slo = _load_json(os.path.join(path, "slo.json"))
    if slo:
        render_slo(slo, out)

    trace = _load_json(os.path.join(path, "trace.json")) or {}
    per_uri = request_histories(trace)
    if uri is not None:
        if uri not in per_uri:
            print(f"error: uri {uri!r} has no events in this bundle "
                  f"(known: {sorted(per_uri)[:20]})", file=sys.stderr)
            return 2
        selected = [uri]
    else:
        # newest-active first: order by each request's last event time
        selected = sorted(per_uri,
                          key=lambda u: per_uri[u][-1]["ts"],
                          reverse=True)[:requests]
    if selected:
        print(f"request histories ({len(selected)} of "
              f"{len(per_uri)} in trace):", file=out)
        for u in selected:
            render_request(u, per_uri[u], out)

    log_path = os.path.join(path, "logs.jsonl")
    try:
        with open(log_path) as f:
            lines = [ln.rstrip("\n") for ln in f if ln.strip()]
    except OSError:
        lines = []
    if lines:
        print(f"recent logs (last {min(logs, len(lines))} of "
              f"{len(lines)}):", file=out)
        for ln in lines[-logs:]:
            print("  " + ln, file=out)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.serving.debug",
        description="Render a serving diagnostic bundle "
                    "(docs/debugging.md)")
    ap.add_argument("bundle", help="bundle directory written by "
                                   "serving/flight.py dump_bundle")
    ap.add_argument("--ticks", type=int, default=20,
                    help="tick-timeline tail length (default 20)")
    ap.add_argument("--requests", type=int, default=10,
                    help="max request histories (default 10, newest)")
    ap.add_argument("--uri", default=None,
                    help="render only this request id's history")
    ap.add_argument("--logs", type=int, default=5,
                    help="log-tail length (default 5)")
    ap.add_argument("--replay", action="store_true",
                    help="re-simulate the bundle (serving/sim) and "
                         "print simulated-vs-recorded SLO deltas")
    ap.add_argument("--seed", type=int, default=0,
                    help="replay simulation seed (default 0)")
    args = ap.parse_args(argv)
    rc = render_bundle(args.bundle, ticks=args.ticks,
                       requests=args.requests, uri=args.uri,
                       logs=args.logs)
    if rc == 0 and args.replay:
        rc = render_replay(args.bundle, sys.stdout, seed=args.seed)
    return rc


if __name__ == "__main__":
    sys.exit(main())

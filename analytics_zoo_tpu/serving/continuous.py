"""Continuous batching for generative serving.

SURVEY.md §2.6's TPU mapping names "continuous batching" as the serving
bar; the reference's Flink engine (upstream ``serving/engine/``) stops at
request-level micro-batching — a batch of prompts runs its whole
generation before the next batch starts, so a 2-token request convoys
behind a 32-token neighbour.  This module is the beyond-parity engine:

- A fixed-size **slot arena**: KV caches ``[n_layers, S, L, H, D]`` for
  ``S`` co-resident requests, allocated once.  Static shapes — the decode
  step compiles exactly once, no matter how requests come and go.
- **In-flight joining**: a new request PREFILLS with one MXU-friendly
  forward (``TransformerLM.prefill``) and its K/V are spliced into a free
  slot while other slots are mid-generation; the next engine tick decodes
  all residents together at their own positions (``decode_step`` with a
  per-row position vector).
- **Slot recycling**: a request that hits EOS or its token budget frees
  its slot immediately; the next waiting request takes it on the same
  tick.  Stale cache entries need no scrubbing — a resident only attends
  positions ``<= pos`` it has itself written (prompt prefill + its own
  decode steps), so a recycled slot never reads its predecessor's K/V.

Per-request results match ``models.lm.generate`` run solo: same frozen
tail EOS semantics, same ``[max_new_tokens]`` output shape (eos-padded),
greedy or per-request-temperature sampling with ``generate``-compatible
position-folded rngs.
"""

from __future__ import annotations

import collections
import logging
import threading
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.learn.inference_model import _next_bucket
from analytics_zoo_tpu.models.lm import TransformerLM

logger = logging.getLogger("analytics_zoo_tpu")


@dataclass
class _Slot:
    uri: str
    plen: int
    tokens: List[int] = field(default_factory=list)
    on_done: Optional[Callable] = None
    temperature: float = 0.0
    rng_seed: Optional[int] = None


class ContinuousEngine:
    """Slot-arena generation engine over one ``TransformerLM``.

    Host-side control loop + three jitted device programs:
    ``_step`` (advance every slot one token, per-slot positions),
    ``_prefill[bucket]`` (one forward for a joining prompt), and
    ``_insert[bucket]`` (splice prefilled K/V into a slot).  The arena
    buffers are donated through ``_step``/``_insert`` so XLA updates them
    in place instead of copying ``S*L`` of KV per token.

    Not thread-safe by itself: ``submit`` may be called from any thread,
    but ``step``/``drain`` must run on ONE pump thread (the serving loop).
    """

    def __init__(self, model: TransformerLM, variables, *,
                 max_new_tokens: int, max_slots: int = 8,
                 prompt_buckets: Sequence[int] = (16, 32, 64, 128),
                 eos_id: Optional[int] = None, pad_id: int = 0):
        if model.pp_stages > 0:
            raise ValueError("continuous batching serves pp_stages=0 "
                             "models (models.lm.unstack_pp_params)")
        self.model = model
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        limit = int(model.max_position) - self.max_new_tokens
        self.prompt_buckets = tuple(
            b for b in sorted(set(int(b) for b in prompt_buckets))
            if b <= limit)
        if not self.prompt_buckets:
            raise ValueError(
                f"no prompt bucket fits: max_position {model.max_position}"
                f" - max_new_tokens {max_new_tokens} = {limit} < smallest "
                f"bucket {min(prompt_buckets)}")
        self.max_prompt_width = self.prompt_buckets[-1]
        S = int(max_slots)
        L = self.max_prompt_width + self.max_new_tokens
        self._S, self._L = S, L
        H = model.num_heads
        D = model.hidden_size // H
        cdtype = jnp.dtype(model.dtype)
        self._ck = jnp.zeros((model.num_layers, S, L, H, D), cdtype)
        self._cv = jnp.zeros_like(self._ck)
        self._variables = variables
        # host-side per-slot state (device copies travel as step args)
        self._tok = np.zeros(S, np.int32)
        self._pos = np.zeros(S, np.int32)
        self._slots: List[Optional[_Slot]] = [None] * S
        self._free = collections.deque(range(S))
        self._lock = threading.Lock()
        self._waiting: collections.deque = collections.deque()
        self._step_count = 0

        def step_fn(ck, cv, tok, pos, temps, seeds, use_sample):
            logits, ck, cv = model.apply(
                variables, tok, ck, cv, pos,
                method=TransformerLM.decode_step)
            greedy = jnp.argmax(logits, -1).astype(jnp.int32)
            if not use_sample:          # static: greedy-only compile
                return greedy, ck, cv

            def sample_row(seed, t, lg, p):
                key = jax.random.fold_in(jax.random.key(seed), p)
                scaled = lg.astype(jnp.float32) / jnp.maximum(t, 1e-6)
                return jax.random.categorical(key, scaled).astype(
                    jnp.int32)

            sampled = jax.vmap(sample_row)(seeds, temps, logits, pos)
            return jnp.where(temps > 0.0, sampled, greedy), ck, cv

        self._step = jax.jit(partial(step_fn, use_sample=False),
                             donate_argnums=(0, 1))
        self._step_sampled = jax.jit(partial(step_fn, use_sample=True),
                                     donate_argnums=(0, 1))

        def prefill_fn(prompt, plen):
            logits, ks, vs = model.apply(variables, prompt,
                                         method=TransformerLM.prefill)
            return logits[0, plen - 1], ks, vs

        self._prefill = jax.jit(prefill_fn)

        def insert_fn(ck, cv, ks, vs, slot):
            ck = jax.lax.dynamic_update_slice(
                ck, ks.astype(ck.dtype), (0, slot, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                cv, vs.astype(cv.dtype), (0, slot, 0, 0, 0))
            return ck, cv

        self._insert = jax.jit(insert_fn, donate_argnums=(0, 1))

    # ---- submission ---------------------------------------------------

    @property
    def n_active(self) -> int:
        return self._S - len(self._free)

    @property
    def n_waiting(self) -> int:
        with self._lock:
            return len(self._waiting)

    def submit(self, uri: str, prompt: np.ndarray,
               on_done: Optional[Callable] = None, *,
               temperature: float = 0.0,
               rng_seed: Optional[int] = None) -> None:
        """Queue one request.  ``prompt``: 1-D int32 token array.
        ``on_done(uri, tokens)`` fires from the pump thread when the
        request finishes (tokens: ``[max_new_tokens]`` int32, eos-padded
        frozen tail).  Raises on bounds violations — the serving layer
        error-publishes per request before calling this."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1:
            raise ValueError(f"prompt must be 1-D, got {prompt.shape}")
        n = len(prompt)
        if n < 1 or n > self.max_prompt_width:
            raise ValueError(
                f"prompt length {n} outside [1, {self.max_prompt_width}]")
        if temperature > 0.0 and rng_seed is None:
            raise ValueError("temperature > 0 needs rng_seed")
        with self._lock:
            self._waiting.append(
                (uri, prompt, on_done, float(temperature), rng_seed))

    # ---- pump ---------------------------------------------------------

    def _admit(self) -> int:
        """Move waiting requests into free slots (prefill + splice).
        Returns the number admitted this call."""
        admitted = 0
        while self._free:
            with self._lock:
                if not self._waiting:
                    break
                uri, prompt, on_done, temp, seed = self._waiting.popleft()
            slot = self._free.popleft()
            plen = len(prompt)
            pb = _next_bucket(plen, self.prompt_buckets)
            padded = np.full((1, pb), self.pad_id, np.int32)
            padded[0, :plen] = prompt
            last_logits, ks, vs = self._prefill(jnp.asarray(padded),
                                                jnp.int32(plen))
            self._ck, self._cv = self._insert(
                self._ck, self._cv, ks, vs, jnp.int32(slot))
            first = self._pick_first(last_logits, plen, temp, seed)
            st = _Slot(uri=uri, plen=plen, on_done=on_done,
                       temperature=temp, rng_seed=seed)
            self._slots[slot] = st
            self._tok[slot] = first
            self._pos[slot] = plen
            admitted += 1
            self._record_token(slot, int(first))
        return admitted

    def _pick_first(self, last_logits, plen: int, temp: float,
                    seed) -> int:
        """The prefill's last-position logits produce the request's first
        token — same pick semantics (and rng position-fold) as
        ``generate``'s step at t = plen-1."""
        if temp <= 0.0:
            return int(jnp.argmax(last_logits))
        key = jax.random.fold_in(jax.random.key(int(seed)), plen - 1)
        return int(jax.random.categorical(
            key, last_logits.astype(jnp.float32) / temp))

    def _record_token(self, slot: int, token: int):
        """Append one generated token; finish + free the slot when done."""
        st = self._slots[slot]
        st.tokens.append(token)
        done = len(st.tokens) >= self.max_new_tokens or \
            (self.eos_id is not None and token == self.eos_id)
        if not done:
            return
        out = np.full(self.max_new_tokens,
                      self.eos_id if self.eos_id is not None else 0,
                      np.int32)
        out[:len(st.tokens)] = st.tokens      # frozen tail: eos padding
        self._slots[slot] = None
        self._free.append(slot)
        if st.on_done is not None:
            try:
                st.on_done(st.uri, out)
            except Exception:
                logger.exception("continuous-batching on_done callback "
                                 "failed for %r", st.uri)

    def step(self) -> int:
        """One engine tick: admit joiners, then advance every resident
        one token.  Returns the number of active slots after the tick
        (0 = idle; the caller decides how to wait for new work)."""
        self._admit()
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return 0
        sampled = any(self._slots[i].temperature > 0.0 for i in active)
        temps = np.zeros(self._S, np.float32)
        seeds = np.zeros(self._S, np.uint32)
        for i in active:
            temps[i] = self._slots[i].temperature
            seeds[i] = self._slots[i].rng_seed or 0
        step = self._step_sampled if sampled else self._step
        nxt, self._ck, self._cv = step(
            self._ck, self._cv, jnp.asarray(self._tok),
            jnp.asarray(self._pos), jnp.asarray(temps),
            jnp.asarray(seeds))
        nxt = np.asarray(nxt)
        for i in active:
            self._tok[i] = nxt[i]
            self._pos[i] += 1
            self._record_token(i, int(nxt[i]))
        self._admit()       # freed slots recycle on the SAME tick
        return self.n_active

    def drain(self, max_ticks: int = 100_000) -> None:
        """Run ticks until every submitted request has finished (tests /
        batch use)."""
        for _ in range(max_ticks):
            if self.step() == 0 and self.n_waiting == 0:
                return
        raise RuntimeError("drain did not converge")

"""Continuous batching for generative serving.

SURVEY.md §2.6's TPU mapping names "continuous batching" as the serving
bar; the reference's Flink engine (upstream ``serving/engine/``) stops at
request-level micro-batching — a batch of prompts runs its whole
generation before the next batch starts, so a 2-token request convoys
behind a 32-token neighbour.  This module is the beyond-parity engine:

- A fixed-size **slot arena**: KV caches ``[n_layers, S, L, H, D]`` for
  ``S`` co-resident requests, allocated once.  Static shapes — the decode
  step compiles exactly once, no matter how requests come and go.
- **In-flight joining**: a new request PREFILLS with one MXU-friendly
  forward (``TransformerLM.prefill``) and its K/V are spliced into a free
  slot while other slots are mid-generation; the next engine tick decodes
  all residents together at their own positions (``decode_step`` with a
  per-row position vector).
- **Slot recycling**: a request that hits EOS or its token budget frees
  its slot immediately; the next waiting request takes it on the same
  tick.  Stale cache entries need no scrubbing — a resident only attends
  positions ``<= pos`` it has itself written (prompt prefill + its own
  decode steps), so a recycled slot never reads its predecessor's K/V.

Per-request results match ``models.lm.generate`` run solo: same frozen
tail EOS semantics, same ``[max_new_tokens]`` output shape (eos-padded),
greedy or per-request-temperature sampling with ``generate``-compatible
position-folded rngs.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import (Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Tuple)

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.learn.inference_model import (
    _next_bucket, filter_prompt_buckets)
from analytics_zoo_tpu.models.lm import (TransformerLM,
                                         top_p_filter)
from analytics_zoo_tpu.models.speculative import accept_proposals
from analytics_zoo_tpu.ops.flash_attention import (KV_SCALE_DTYPE,
                                                   QuantKV)
from analytics_zoo_tpu.serving.frontdoor import (PRIORITIES, QosPolicy,
                                                 WeightedWaitQueue)
from analytics_zoo_tpu.serving import policy as scheduler_policy
from analytics_zoo_tpu.serving.paged_cache import (BlockPool,
                                                   SINK_BLOCK,
                                                   block_bytes,
                                                   split_block_budget)
from analytics_zoo_tpu.serving.flight import FlightRecorder
from analytics_zoo_tpu.serving.kv_store import (HostKVStore, TIER_HBM,
                                                TIER_HOST)
from analytics_zoo_tpu.serving.telemetry import Telemetry

logger = logging.getLogger("analytics_zoo_tpu")


def _zeros_like(x):
    """``jnp.zeros_like`` that also accepts the quantized KV pools
    (``QuantKV`` pytrees — int8 data + per-row scales): every leaf is
    zeroed independently."""
    return jax.tree_util.tree_map(jnp.zeros_like, x)


def _kv_label(dtype) -> str:
    """Short storage-mode label for a floating cache dtype, matching
    the ``paged_cache.KV_DTYPE_BYTES`` keys where one exists."""
    return {"bfloat16": "bf16", "float32": "f32",
            "float16": "f16", "float64": "f64"}.get(
        jnp.dtype(dtype).name, jnp.dtype(dtype).name)


class DeadlineExceeded(RuntimeError):
    """Terminal error for a request shed at ADMISSION because its
    end-to-end deadline already passed — distinct on purpose from the
    supervisor's in-flight deadline give-up (``plan_redispatch``'s
    error verdict), so the two show up separately on /metrics and in
    postmortems.  The message always starts with ``deadline_exceeded``
    so the wire error event is greppable and the SSE stream can name
    the event type."""


class _Req(NamedTuple):
    """One waiting-queue entry — named fields, because positional
    indexing across three consumers silently breaks when a field is
    added."""

    uri: str
    prompt: np.ndarray
    on_done: Optional[Callable]
    on_error: Optional[Callable]
    temperature: float
    rng_seed: Optional[int]
    max_new: int
    prefix: Optional[int]
    top_p: float
    # front-door fields (serving/frontdoor.py) — appended with defaults
    # so positional construction at older arity keeps working
    on_token: Optional[Callable] = None
    priority: str = "standard"
    tenant: str = ""
    enq_t: float = 0.0
    # prefill/decode disaggregation (docs/serving_memory.md): on the
    # SOURCE engine, ``handoff_cb(state)`` fires once the prefill's
    # first token lands — the row exports instead of decoding here.  On
    # the DESTINATION engine, ``handoff_state`` carries the exported
    # chain (submit_handoff); admission adopts it instead of prefilling.
    handoff_cb: Optional[Callable] = None
    handoff_state: Optional[dict] = None
    # end-to-end deadline (docs/serving_qos.md "Overload & brownout"):
    # an absolute ``time.monotonic`` instant; 0.0 = no deadline.
    # Admission sheds entries already past it BEFORE any prefill work.
    deadline_t: float = 0.0


@dataclass
class _Slot:
    uri: str
    plen: int
    max_new: int
    tokens: List[int] = field(default_factory=list)
    on_done: Optional[Callable] = None
    on_error: Optional[Callable] = None
    temperature: float = 0.0
    rng_seed: Optional[int] = None
    top_p: float = 0.0
    # streaming: fires per generated token from the pump thread
    # (``on_token(uri, token, index)``) — the index survives preemption
    # dedup because a readmitted row regenerates tokens
    # deterministically at the same positions
    on_token: Optional[Callable] = None
    # paged mode: the original request (requeued verbatim on
    # preemption) and an admission sequence number (the preemption
    # victim is always the LATEST admission — earliest admissions keep
    # making forward progress, so preemption can never livelock)
    req: Optional[_Req] = None
    admit_seq: int = 0
    # chunked-prefill state machine: a slot admits as "PREFILLING" and
    # feeds its prompt to the cache chunk by chunk (fill_pos = next
    # cache position to write, starting past any spliced/shared
    # prefix); the tick its last chunk lands it emits its first token
    # and flips to "DECODE".  ``full`` holds the not-yet-fed tokens
    # (positions base..plen-1); ``hashes``/``n_pub`` track which full
    # prompt blocks the paged path has already published for sharing.
    state: str = "DECODE"
    fill_pos: int = 0
    base: int = 0
    full: Optional[np.ndarray] = None
    hashes: Optional[list] = None
    n_pub: int = 0


class ContinuousEngine:
    """Slot-arena generation engine over one ``TransformerLM``.

    Host-side control loop + three jitted device programs: the step
    program (advance every slot ``ticks_per_step`` tokens at per-slot
    positions in one lax.scan call; compiled per (n_ticks, sampled) via
    ``_get_step``), the bucketed batched prefill (one forward for ALL
    joiners sharing a prompt bucket), and the per-slot K/V splice.  The
    arena buffers are donated through step/insert so XLA updates them in
    place instead of copying ``S*L`` of KV per token.

    **KV memory.** The cache stores only ``model.kv_heads`` heads per
    position: a grouped-query model (``num_kv_heads < num_heads``)
    shrinks every resident's K/V ``num_heads/num_kv_heads``-fold, which
    is proportionally more co-resident requests for the same HBM
    (``capacity_report()`` quantifies it); ``cache_dtype`` narrows it
    further (e.g. a bfloat16 cache under an f32 model halves it again —
    attention upcasts via the einsums' f32 accumulation).

    **``paged=True``** replaces the per-slot arena with a block-pool
    cache (serving/paged_cache.py): K/V live in one flat pool of
    ``block_size``-token blocks, each resident holds only the blocks it
    has actually filled (via a per-slot block table), full prompt
    blocks are hash-indexed so requests sharing a prompt prefix attach
    to the same physical blocks copy-free (subsuming the manual
    ``register_prefix`` splice), and when the pool runs dry the engine
    PREEMPTS the latest admission back to the queue front instead of
    OOMing — its partial tokens are discarded and regenerate
    deterministically on readmission (greedy argmax, and sampled rows
    fold the rng by absolute position).  ``cache_metrics()`` reports
    occupancy/hit-rate/preemptions.  A ``draft_model`` composes with
    paged (and with chunked, and with both): the draft pages its own
    K/V through a SECOND pool tenant — its own block tables and
    allocator over a proportionally small slice of HBM — and the
    verify step writes k+1 positions through the paged write path,
    rolling rejected positions back by pointer (never by block copy).
    Remaining paged limitation (ROADMAP open item): no mesh; paged
    ``register_prefix`` must run before the pump starts (it updates
    the donated pool buffers — racing a live ``step()`` is undefined).

    Not thread-safe by itself: ``submit`` may be called from any thread,
    but ``step``/``drain`` must run on ONE pump thread (the serving loop).
    """

    def __init__(self, model: TransformerLM, variables, *,
                 max_new_tokens: int, max_slots: int = 8,
                 prompt_buckets: Sequence[int] = (16, 32, 64, 128),
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 ticks_per_step: int = 1,
                 cache_dtype=None,
                 kernel: str = "gather",
                 kv_dtype: Optional[str] = None,
                 mesh=None, partition_rules=None,
                 draft_model: Optional[TransformerLM] = None,
                 draft_variables=None, speculation_k: int = 4,
                 paged: bool = False, block_size: int = 16,
                 n_blocks: Optional[int] = None,
                 draft_n_blocks: Optional[int] = None,
                 hbm_fraction: Optional[float] = None,
                 enable_prefix_cache: bool = True,
                 elastic_pool: bool = False,
                 kv_host_store_bytes: int = 0,
                 prefix_directory=None,
                 replica_id: int = 0,
                 fault_injector=None,
                 chunked: bool = False,
                 tick_token_budget: Optional[int] = None,
                 record_timings: bool = False,
                 telemetry: Optional[Telemetry] = None,
                 qos: Optional[QosPolicy] = None,
                 flight: Optional[FlightRecorder] = None,
                 flight_capacity: int = 2048):
        """``mesh`` (with a ``tp`` axis) serves a model LARGER than one
        chip's HBM: weights shard per ``partition_rules`` (default
        ``LM_PARTITION_RULES`` — Megatron layout), the KV arena shards
        over tp on the kv-heads axis (each chip holds 1/tp of every
        slot's cache), and slot bookkeeping (tok/pos/done) replicates.
        XLA propagates the shardings through the jitted step/prefill/
        splice programs — decode runs as one SPMD program with the tp
        collectives the weight layout implies.

        ``draft_n_blocks`` (paged + draft only) overrides the draft
        tenant's pool size, which otherwise matches ``n_blocks`` — the
        draft's K/V is cheap (per-block bytes scale with its
        layers x kv_heads x head_dim), so equal counts cost little; a
        smaller override is mainly a test lever for draft-pool-dry
        preemption.

        ``kernel`` picks the paged-attention read path:
        ``"gather"`` (default) is the materialising ``jnp.take``
        reference, ``"fused"`` the Pallas kernel that streams KV
        blocks HBM→VMEM per grid step (interpret mode off-TPU, so
        greedy parity holds on CPU too).  ``kv_dtype`` picks the
        TARGET pool's storage: ``None`` follows ``cache_dtype``,
        ``"bf16"`` forces a bfloat16 pool, ``"int8"`` stores
        quantized blocks with per-row bfloat16 scales (~1.9x more
        blocks at equal HBM; both kernels dequantize on read).  Both
        knobs require ``paged=True``; the draft tenant's pool stays
        in ``cache_dtype`` (it is already small)."""
        if model.pp_stages > 0:
            raise ValueError("continuous batching serves pp_stages=0 "
                             "models (models.lm.unstack_pp_params)")
        self.model = model
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        # ---- telemetry (always-on; serving/telemetry.py) ---------------
        # one facade per engine unless the serving layer passes its own
        # (to merge registries under one scrape).  Every hook below is
        # host-side floats/ints only: nothing telemetry does enters a
        # jitted program, so it can neither sync the device nor retrace.
        self.telemetry = telemetry if telemetry is not None \
            else Telemetry()
        # ---- flight recorder (serving/flight.py) -----------------------
        # always-on bounded ring of per-tick state snapshots — the
        # incident lookback a diagnostic bundle ships.  One plain dict
        # of host ints per tick (no device reads beyond what telemetry
        # already sampled), so greedy outputs are bitwise-identical
        # with it on or off.  ``flight_capacity=0`` disables it (the
        # overhead benchmark's lever); a shared recorder can be passed
        # in so the serving layer can bundle it after an engine crash.
        self.flight = flight if flight is not None else (
            FlightRecorder(flight_capacity) if flight_capacity > 0
            else None)
        self._tick_kind = "decode"
        self._alloc_fail_streak = 0
        # cumulative-counter baselines for the per-tick deltas the
        # flight record carries
        self._flight_last = {"preempt": 0, "compiles": 0, "chunks": 0,
                             "budget_tokens": 0, "alloc_fail": 0,
                             "draft_alloc_fail": 0, "spec_proposed": 0,
                             "spec_accepted": 0, "pool_resizes": 0,
                             "handoffs_out": 0, "handoffs_in": 0,
                             "kv_spills": 0, "kv_readmits": 0,
                             "deadline_sheds": 0}
        # ---- overload brownout + deadline admission (policy.py) --------
        # per-tick engine state the broker's plan_brownout controller
        # pushes via set_brownout(); 0/off by default, and every gate
        # below checks the level first, so an engine nobody browns out
        # makes bit-identical decisions to the pre-brownout engine.
        self._brownout_level = 0
        self._brownout_enabled = False
        self._brownout_clamp = 0
        self._deadline_seen = False
        self._deadline_sheds = 0
        # ---- speculative mode (draft arena) ----------------------------
        # the slot arena is ALREADY per-row-positioned, which is exactly
        # what per-slot acceptance rates need: each verify round advances
        # every slot by its own accepted count.  Greedy-only (a sampled
        # slot's speculative contract needs rejection sampling — not
        # implemented; submit() rejects temperature > 0 in this mode).
        self.draft_model = draft_model
        self._draft_variables = draft_variables
        self._spec_k = int(speculation_k) if draft_model is not None else 0
        if draft_model is not None:
            if draft_variables is None:
                raise ValueError("draft_model needs draft_variables")
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.vocab_size} != target "
                    f"vocab {model.vocab_size}")
            if draft_model.pp_stages > 0:
                raise ValueError("draft must be pp_stages=0")
            if self._spec_k < 1:
                raise ValueError("speculation_k must be >= 1")
        # speculative verify writes k+1 entries past the pointer and
        # looks up positions there, so the bucket limit tightens by k+1
        # and must fit BOTH models' position tables
        eff_max_pos = model.max_position if draft_model is None else \
            min(model.max_position, draft_model.max_position)
        self.prompt_buckets = filter_prompt_buckets(
            prompt_buckets, eff_max_pos,
            max_new_tokens + (self._spec_k + 1 if draft_model else 0))
        self.max_prompt_width = self.prompt_buckets[-1]
        S = int(max_slots)
        L = self.max_prompt_width + self.max_new_tokens \
            + (self._spec_k + 1 if draft_model is not None else 0)
        self._S, self._L = S, L
        # GQA models store only kv_heads in the cache: the arena shrinks
        # num_heads/kv_heads-fold, which is more co-resident requests
        # for the same HBM.  cache_dtype narrows it further (e.g.
        # bfloat16 arena under an f32 model: 2x more slots; attention
        # reads upcast via the einsums' f32 accumulation).
        H = getattr(model, "kv_heads", model.num_heads)
        D = model.hidden_size // model.num_heads
        # validate cache_dtype EAGERLY with a serving-level message — a
        # bad value must not surface as a bare jnp.dtype TypeError deep
        # inside arena allocation
        if cache_dtype is None:
            cdtype = jnp.dtype(model.dtype)
        else:
            try:
                cdtype = jnp.dtype(cache_dtype)
            except TypeError:
                raise ValueError(
                    f"cache_dtype {cache_dtype!r} is not a dtype the KV "
                    f"cache can be allocated with; pass a floating "
                    f"dtype like 'bfloat16' or 'float32' (or None to "
                    f"follow model.dtype "
                    f"{jnp.dtype(model.dtype).name})") from None
            if not jnp.issubdtype(cdtype, jnp.floating):
                raise ValueError(
                    f"cache_dtype {cache_dtype!r} resolves to "
                    f"{cdtype.name}, which is not a floating dtype — "
                    f"K/V projections cannot be stored in it without "
                    f"corrupting attention")
        # ---- paged-attention kernel / KV storage knobs -----------------
        # both only change how the PAGED read/write path runs; default
        # (gather + cache_dtype storage) is bit-for-bit the pre-knob
        # behavior.
        if kernel not in ("gather", "fused"):
            raise ValueError(f"kernel must be 'gather' or 'fused', got "
                             f"{kernel!r}")
        if kv_dtype not in (None, "bf16", "int8"):
            raise ValueError(f"kv_dtype must be None, 'bf16' or "
                             f"'int8', got {kv_dtype!r}")
        if not paged and (kernel != "gather" or kv_dtype is not None):
            raise ValueError(
                f"kernel={kernel!r} / kv_dtype={kv_dtype!r} require "
                f"paged=True: both select the paged-attention path "
                f"(the arena engine has no block pool to apply them to)")
        if elastic_pool and not paged:
            raise ValueError(
                "elastic_pool=True requires paged=True: the arena "
                "engine has no block pool to grow or shrink")
        # ---- tiered KV memory (serving/kv_store.py) --------------------
        # a host-RAM second tier for evicted prefix chains plus an
        # optional fleet-wide prefix directory.  Both default OFF —
        # kv_host_store_bytes=0 and prefix_directory=None leave every
        # pool hook None, bit-identical to the single-tier engine.
        if kv_host_store_bytes < 0:
            raise ValueError(
                f"kv_host_store_bytes must be >= 0, got "
                f"{kv_host_store_bytes}")
        if (kv_host_store_bytes > 0 or prefix_directory is not None) \
                and not paged:
            raise ValueError(
                "kv_host_store_bytes / prefix_directory require "
                "paged=True: the tiered KV store spills and re-admits "
                "BLOCK CHAINS (the arena engine has no blocks to "
                "spill)")
        if kv_host_store_bytes > 0 and draft_model is not None:
            raise ValueError(
                "kv_host_store_bytes does not compose with a draft "
                "model: speculative mode runs two pool tenants in "
                "lockstep and re-admitting only the target tenant's "
                "chain would desynchronize them — serve the host tier "
                "on non-speculative replicas")
        self.kernel = kernel
        if kv_dtype == "bf16":
            # explicit storage request wins over cache_dtype/model dtype
            cdtype = jnp.dtype(jnp.bfloat16)
        self._kv_int8 = kv_dtype == "int8"
        self.kv_dtype = "int8" if self._kv_int8 else _kv_label(cdtype)
        self.mesh = mesh
        # ---- mesh: weights shard FIRST, for EVERY engine mode ----------
        # arena, paged, chunked, and speculative engines all ride the
        # same Megatron-layout rules; the per-mode KV storage below only
        # decides how the cache itself is laid out.  _kv_tp records
        # whether the chosen rules actually put "tp" on the k/v
        # projection outputs — the KV storage (arena OR block pool) must
        # match what they emit, or every tick pays resharding
        # collectives the layout never required.
        tp = int(mesh.shape.get("tp", 1)) if mesh is not None else 1
        self._tp = tp
        self._kv_tp = self._dkv_tp = False
        if tp > 1:
            from analytics_zoo_tpu.models.lm import LM_PARTITION_RULES
            from analytics_zoo_tpu.parallel.partition import state_sharding

            if H % tp and partition_rules is None:
                raise ValueError(
                    f"kv_heads={H} must divide by tp={tp} to shard the "
                    f"KV cache under the default LM_PARTITION_RULES; "
                    f"narrow-KV (MQA/GQA) models pass partition_rules "
                    f"with the key/value kernels replicated (P()) — the "
                    f"KV storage then replicates too")
            rules = partition_rules or LM_PARTITION_RULES
            shardings = state_sharding(mesh, variables, rules)
            # sharded-from-BIRTH: materialising full weights on one chip
            # first would OOM exactly the beyond-one-chip models this
            # path exists for
            variables = jax.device_put(variables, shardings)
            self._kv_tp = H % tp == 0 and self._kv_kernels_tp_sharded(
                shardings)
            if draft_model is not None:
                # the draft shards under the SAME rules (same
                # architecture, same regexes); a draft whose kv_heads
                # don't divide tp replicates its k/v kernels per-dim
                # (match_partition_rules' divisibility fallback) and its
                # KV storage follows suit
                dshardings = state_sharding(mesh, draft_variables, rules)
                draft_variables = jax.device_put(draft_variables,
                                                 dshardings)
                self._draft_variables = draft_variables
                dH = getattr(draft_model, "kv_heads",
                             draft_model.num_heads)
                self._dkv_tp = dH % tp == 0 and \
                    self._kv_kernels_tp_sharded(dshardings)
        # ---- paged mode (block-pool cache, serving/paged_cache.py) -----
        self.paged = bool(paged)
        self._preemptions = 0
        self._peak_resident = 0
        self._admit_seq = 0
        self._pool: Optional[BlockPool] = None
        self._pk = self._pv = None
        self._paged_prefixes: Dict[int, tuple] = {}
        self._dpool: Optional[BlockPool] = None
        self._dpk = self._dpv = None
        # tiered-KV state (None/0 = tier off on every path)
        self._kv_store: Optional[HostKVStore] = None
        self._prefix_directory = prefix_directory
        self._replica_id = int(replica_id)
        # chaos harness (serving/fault.py): None = injection off, and
        # every hook below is a no-op — bit-identical behavior
        self._fault = fault_injector
        self._kv_spills = 0
        self._kv_spill_bytes = 0
        self._kv_readmits = 0
        self._kv_readmit_tokens_saved = 0
        # deferred device work recorded by pool callbacks / readmission
        # while ``_pool_lock`` is held — the pump thread drains both
        # BEFORE the next device write to the pool (tpulint TZ102/TZ103:
        # no D2H/H2D under the pool lock)
        self._pending_spills: List[Tuple[int, int]] = []   # (block, hash)
        self._pending_readmits: List[tuple] = []    # (blocks, kcat, vcat)
        if self.paged:
            bs = int(block_size)
            if bs < 1:
                raise ValueError(f"block_size must be >= 1, got {bs}")
            M = -(-L // bs)         # logical blocks per row, ceil(L/bs)
            # int8 rows cost D + 2 bytes (1/elt + a bf16 scale) vs
            # 2D for bf16 — block_bytes() is the shared ledger the
            # budget split, capacity report, and bench all bill at
            if self._kv_int8:
                per_block = block_bytes(model.num_layers, bs, H, D,
                                        "int8")
            else:
                per_block = 2 * model.num_layers * bs * H * D \
                    * cdtype.itemsize
            draft_per_block = 0
            if draft_model is not None:
                DHp = getattr(draft_model, "kv_heads",
                              draft_model.num_heads)
                DDp = draft_model.hidden_size // draft_model.num_heads
                draft_per_block = 2 * draft_model.num_layers * bs \
                    * DHp * DDp * cdtype.itemsize
            self._per_block_bytes = per_block
            self._draft_per_block_bytes = draft_per_block
            if n_blocks is None:
                lim = 0
                if hbm_fraction is not None:
                    try:
                        stats = jax.devices()[0].memory_stats() or {}
                        lim = int(stats.get("bytes_limit", 0))
                    except Exception:
                        lim = 0
                if lim:
                    # with a draft the byte budget covers BOTH tenants:
                    # the common block count splits it proportionally
                    # to per-block cost (the draft's slice is small)
                    n_blocks = max(M + 1, split_block_budget(
                        int(lim * float(hbm_fraction)),
                        (per_block, draft_per_block)
                        if draft_model is not None else (per_block,)))
                else:
                    if hbm_fraction is not None:
                        logger.warning(
                            "hbm_fraction=%s ignored: device exposes no "
                            "memory_stats (CPU backend?); sizing the "
                            "pool arena-equivalent (S*M+1 blocks)",
                            hbm_fraction)
                    # arena-equivalent capacity: every slot can run to
                    # full length — paged still wins whenever real
                    # traffic doesn't (shorter prompts, prefix sharing)
                    n_blocks = S * M + 1
            n_blocks = int(n_blocks)
            if n_blocks < M + 1:
                raise ValueError(
                    f"n_blocks={n_blocks} cannot hold one full-length "
                    f"sequence: need >= {M + 1} ({M} logical blocks of "
                    f"{bs} positions + the sink block 0)")
            self._bs, self._M = bs, M
            # host tier + directory hooks precede pool creation: the
            # pool fires them from inside allocate()/shrink()/insert()
            if kv_host_store_bytes > 0:
                self._kv_store = HostKVStore(
                    int(kv_host_store_bytes),
                    evict_cb=self._store_evicted)
            self._pool = BlockPool(
                n_blocks, bs, enable_prefix_cache,
                event_cb=self.telemetry.pool_event,
                name="target",
                kv_dtype=self.kv_dtype,
                bytes_per_block=per_block,
                spill_cb=(self._spill_block
                          if self._kv_store is not None else None),
                index_cb=(self._pool_index_event
                          if self._prefix_directory is not None
                          else None))
            # pool-mutation guard: admission/growth run on the pump
            # thread, but unregister_prefix releases from client threads
            self._pool_lock = threading.Lock()
            # HEAD-MAJOR pool layout [layers, N, KH, bs, D]: the fused
            # kernel's block specs carve (1, 1, bs, D) tiles per
            # (table[b, j], head) grid step, which only squeezes
            # LEADING singletons — Mosaic-clean on TPU (jax's own paged
            # kernel uses the same order).  int8 pools are QuantKV
            # pytrees (int8 data + per-(block, position, head) bf16
            # scales) — every jitted program moves them like arrays.
            shape = (model.num_layers, n_blocks, H, bs, D)
            # mesh: the pool shards over tp on the kv-heads dim exactly
            # like the arena — [layers, N, KH/tp, bs, D] per chip,
            # allocated sharded-from-birth.  Bookkeeping (BlockPool,
            # block tables) stays host-side and replicated — allocation,
            # prefix hashing, preemption, and pointer-rollback verify
            # are all table rewrites, mesh-oblivious by construction —
            # and the jitted decode/chunk/verify programs reach the
            # pool through XLA's sharding propagation.
            pool_sh = scale_sh = None
            if tp > 1:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                hax = "tp" if self._kv_tp else None
                pool_sh = NamedSharding(mesh,
                                        P(None, None, hax, None, None))
                scale_sh = NamedSharding(mesh, P(None, None, hax, None))
            if self._kv_int8:
                self._pk = QuantKV(
                    jnp.zeros(shape, jnp.int8, device=pool_sh),
                    jnp.ones(shape[:-1], KV_SCALE_DTYPE,
                             device=scale_sh))
                self._pv = QuantKV(
                    jnp.zeros(shape, jnp.int8, device=pool_sh),
                    jnp.ones(shape[:-1], KV_SCALE_DTYPE,
                             device=scale_sh))
            else:
                self._pk = jnp.zeros(shape, cdtype, device=pool_sh)
                self._pv = jnp.zeros(shape, cdtype, device=pool_sh)
            # per-slot block tables; SINK everywhere a row holds no
            # block, so stray writes land in storage nothing attends
            self._tables = np.full((S, M), SINK_BLOCK, np.int32)
            self._row_blocks: List[List[int]] = [[] for _ in range(S)]
            if draft_model is not None:
                # the draft is a second POOL TENANT: its own physical
                # block arena, block tables, and host allocator (block
                # ids from one pool mean nothing in the other).  The
                # draft position pointer tracks the target's, so a
                # row's draft table grows in LOCKSTEP with its target
                # table — same block count, per-block bytes scaled by
                # the draft's layers x kv_heads x head_dim.
                dnb = n_blocks if draft_n_blocks is None \
                    else int(draft_n_blocks)
                if dnb < M + 1:
                    raise ValueError(
                        f"draft_n_blocks={dnb} cannot hold one "
                        f"full-length sequence: need >= {M + 1} "
                        f"({M} logical blocks of {bs} positions + the "
                        f"sink block 0)")
                self._dpool = BlockPool(
                    dnb, bs, enable_prefix_cache,
                    event_cb=self.telemetry.pool_event, name="draft",
                    kv_dtype=_kv_label(cdtype),
                    bytes_per_block=draft_per_block)
                dpool_sh = None
                if tp > 1:
                    from jax.sharding import NamedSharding
                    from jax.sharding import PartitionSpec as P
                    dpool_sh = NamedSharding(
                        mesh, P(None, None,
                                "tp" if self._dkv_tp else None,
                                None, None))
                self._dpk = jnp.zeros(
                    (draft_model.num_layers, dnb, DHp, bs, DDp),
                    cdtype, device=dpool_sh)
                self._dpv = jnp.zeros(
                    (draft_model.num_layers, dnb, DHp, bs, DDp),
                    cdtype, device=dpool_sh)
                self._dtables = np.full((S, M), SINK_BLOCK, np.int32)
                self._drow_blocks: List[List[int]] = [
                    [] for _ in range(S)]
        # ---- elastic pool (opt-in; docs/serving_memory.md) -------------
        # probe free HBM AFTER weights + initial pool allocation to set
        # the grow ceiling; grow/shrink execute in resize_pool() on the
        # pump thread, block-granular, at the eviction boundary
        # (BlockPool.shrink never evicts a referenced block).
        self.elastic_pool = bool(elastic_pool)
        self._pool_resizes = 0
        self._pool_resize_clamps = 0
        # prefill/decode disaggregation traffic (paged only): rows this
        # engine exported at first-token time / adopted from a donor
        self._handoffs_out = 0
        self._handoffs_in = 0
        self._autoresize_last_fails = 0
        self._pool_floor = (self._M + 1) if self.paged else 0
        self._pool_ceiling = 0
        self._resize_step = 0
        if self.elastic_pool:
            # resize steps snap to a coarse granularity so the jitted
            # programs see FEW distinct pool shapes (each new shape
            # compiles once, then caches)
            self._resize_step = max(self._bs, n_blocks // 8)
            ceiling = n_blocks
            try:
                stats = jax.devices()[0].memory_stats() or {}
                lim = int(stats.get("bytes_limit", 0))
                used = int(stats.get("bytes_in_use", 0))
            except Exception:
                lim = used = 0
            per = self._per_block_bytes + self._draft_per_block_bytes
            if lim > used and per > 0:
                # leave 20% of the probed headroom for activations /
                # compile scratch — the elastic pool must never be the
                # reason a forward OOMs
                ceiling = max(ceiling, n_blocks
                              + (int((lim - used) * 0.8) // per))
            else:
                # no memory_stats (CPU backend): cap at arena-equivalent
                # capacity — every slot can run to full length
                ceiling = max(ceiling, S * self._M + 1)
            self._pool_ceiling = int(ceiling)
        # kv-bytes-per-token: all-layer, both-tenant HBM cost of ONE
        # cached token position — the gauge/flight-record figure that
        # makes bf16 and int8 runs comparable at a glance.
        if self.paged:
            self._kv_bytes_per_token = \
                (self._per_block_bytes
                 + self._draft_per_block_bytes) // self._bs
        else:
            bpt = 2 * model.num_layers * H * D * cdtype.itemsize
            if draft_model is not None:
                dH = getattr(draft_model, "kv_heads",
                             draft_model.num_heads)
                dD = draft_model.hidden_size // draft_model.num_heads
                bpt += 2 * draft_model.num_layers * dH * dD \
                    * cdtype.itemsize
            self._kv_bytes_per_token = bpt
        # ---- chunked prefill (token-budget tick scheduler) -------------
        # chunked=True replaces monolithic admission prefill with
        # incremental chunks packed alongside decodes under a per-tick
        # token budget — long prompts stop stalling active decoders.
        self.chunked = bool(chunked)
        self.record_timings = bool(record_timings)
        self._prefill_stall_ticks = 0
        self._prefill_preemptions = 0
        self._budget_tokens_used = 0
        self._budget_ticks = 0
        self.tick_token_budget: Optional[int] = None
        if self.chunked:
            if tick_token_budget is None:
                # default: roughly one decode-bucket of MXU work — all S
                # decode rows plus at least one smallest-bucket chunk
                # (and at least one paged block) fit in a tick.  A
                # speculative decode row costs k+1 verify positions, so
                # the default scales with the row's true footprint
                per_row = self._spec_k + 1
                budget = max(self.prompt_buckets[0] + per_row * S,
                             2 * per_row * S)
                if self.paged:
                    budget = max(budget, self._bs)
            else:
                budget = int(tick_token_budget)
                if budget < self.prompt_buckets[0]:
                    raise ValueError(
                        f"tick_token_budget={budget} is below the "
                        f"smallest chunk bucket "
                        f"{self.prompt_buckets[0]}: no prefill chunk "
                        f"could ever be scheduled and admission would "
                        f"livelock; raise the budget or add a smaller "
                        f"prompt bucket")
                if self.paged and budget < self._bs:
                    raise ValueError(
                        f"tick_token_budget={budget} is below "
                        f"block_size={self._bs}: a chunk could never "
                        f"cover one paged block per tick; raise the "
                        f"budget or shrink block_size")
            self.tick_token_budget = budget
            # chunk widths reuse the prompt buckets (bounded compile
            # count), trimmed to what the budget can ever schedule
            self._chunk_buckets = tuple(
                b for b in self.prompt_buckets if b <= budget)
            # arena chunk attention reads a [kb, read_len] cache window
            # that tracks the fill frontier — pow2 buckets keep the
            # compile count O(log L) instead of one per frontier
            rb: List[int] = []
            v = 8
            while v < L:
                rb.append(v)
                v *= 2
            rb.append(L)
            self._read_buckets = tuple(rb)
        if self.paged:
            self._ck = self._cv = None  # pool replaces the slot arena
        elif tp > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # the arena must MATCH what the kv projections emit under
            # the chosen rules (weights sharded above) — custom rules
            # that replicate the k/v kernels (even on a divisible-heads
            # model) need a replicated arena, or every decode step pays
            # resharding collectives the layout never required
            kv_sh = NamedSharding(
                mesh, P(None, None, None, "tp", None) if self._kv_tp
                else P())
            # allocate sharded-from-BIRTH, like the weights above
            self._ck = jnp.zeros((model.num_layers, S, L, H, D), cdtype,
                                 device=kv_sh)
            self._cv = jnp.zeros((model.num_layers, S, L, H, D), cdtype,
                                 device=kv_sh)
        else:
            self._ck = jnp.zeros((model.num_layers, S, L, H, D), cdtype)
            self._cv = jnp.zeros_like(self._ck)
        self._variables = variables
        self.ticks_per_step = max(1, int(ticks_per_step))
        # host-side per-slot state (device copies travel as step args)
        self._tok = np.zeros(S, np.int32)
        self._pos = np.zeros(S, np.int32)
        self._done = np.zeros(S, bool)
        self._slots: List[Optional[_Slot]] = [None] * S
        self._free = collections.deque(range(S))
        self._lock = threading.Lock()
        # QoS off (default): a plain FIFO deque — bit-identical
        # admission and grant order to the pre-front-door engine.  QoS
        # on: a weighted stride scheduler with the same deque surface,
        # so every admission/requeue call site below is mode-blind.
        self._qos = qos
        self._waiting = (WeightedWaitQueue(qos) if qos is not None
                         else collections.deque())
        self._step_count = 0

        Lmax = L
        # static under jit: every paged program below compiles in the
        # selected read kernel (gather reference / fused Pallas).  The
        # fused kernel under a mesh runs per-chip via shard_map against
        # the pool's placement (tp-sharded kv heads, or the replicated
        # KH % tp hatch) — kmesh/kv_tp are compile-time constants too.
        kern = self.kernel
        kmesh = self.mesh if kern == "fused" else None
        kv_tp = self._kv_tp

        def pick_next(logits, pos, done, temps, seeds, topps,
                      use_sample, use_topp):
            """One token per row from per-row logits — ONE definition so
            the arena and paged step programs can never drift (their
            greedy-parity guarantee depends on it).  Sampling folds the
            rng by absolute position, so a preempted-and-readmitted row
            regenerates identical tokens."""
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            if use_sample:              # static: greedy-only compile

                def sample_row(seed, t, tp, lg, p):
                    key = jax.random.fold_in(jax.random.key(seed), p)
                    scaled = lg.astype(jnp.float32) / jnp.maximum(
                        t, 1e-6)
                    if use_topp:        # static: no sort when unused
                        scaled = top_p_filter(scaled, tp)
                    return jax.random.categorical(key, scaled).astype(
                        jnp.int32)

                sampled = jax.vmap(sample_row)(seeds, temps, topps,
                                               logits, pos)
                nxt = jnp.where(temps > 0.0, sampled, nxt)
            if eos_id is not None:
                nxt = jnp.where(done, jnp.int32(eos_id), nxt)
                done = done | (nxt == eos_id)
            return nxt, done

        def step_fn(ck, cv, tok, pos, done, temps, seeds, topps,
                    n_ticks, use_sample, use_topp):
            """Advance every slot ``n_ticks`` tokens in ONE device call
            (a lax.scan) — each extra tick saves a host round-trip,
            which dominates per-token cost on tunneled devices.  A slot
            that hits EOS mid-chunk freezes exactly like generate()'s
            frozen tail: it keeps stepping, fed eos.  Returns tokens
            [n_ticks, S] in emission order."""

            def one(carry, _):
                tok, pos, done, ck, cv = carry
                logits, ck, cv = model.apply(
                    variables, tok, ck, cv, pos,
                    method=TransformerLM.decode_step)
                nxt, done = pick_next(logits, pos, done, temps, seeds,
                                      topps, use_sample, use_topp)
                pos = jnp.minimum(pos + 1, Lmax - 1)
                return (nxt, pos, done, ck, cv), nxt

            (tok, pos, done, ck, cv), toks = jax.lax.scan(
                one, (tok, pos, done, ck, cv), None, length=n_ticks)
            return toks, tok, pos, done, ck, cv

        def step_fn_paged(pk, pv, tok, pos, done, tables, temps, seeds,
                          topps, n_ticks, use_sample, use_topp):
            """The paged twin of ``step_fn``: decode through per-slot
            block tables against the shared pool.  Rows holding no
            blocks (free/done slots — their table rows are all SINK)
            write and read only the sink block's garbage, which their
            frozen/ignored outputs never surface."""

            def one(carry, _):
                tok, pos, done, pk, pv = carry
                logits, pk, pv = model.apply(
                    variables, tok, pk, pv, tables, pos, kernel=kern,
                    mesh=kmesh, kv_sharded=kv_tp,
                    method=TransformerLM.decode_step_paged)
                nxt, done = pick_next(logits, pos, done, temps, seeds,
                                      topps, use_sample, use_topp)
                pos = jnp.minimum(pos + 1, Lmax - 1)
                return (nxt, pos, done, pk, pv), nxt

            (tok, pos, done, pk, pv), toks = jax.lax.scan(
                one, (tok, pos, done, pk, pv), None, length=n_ticks)
            return toks, tok, pos, done, pk, pv

        # one compiled program per (n_ticks, sampled) pair — n_ticks is
        # bounded by ticks_per_step, so the cache stays small
        self._step_cache: Dict[Tuple[int, bool, bool],
                               Callable] = {}

        def get_step(n: int, sampled: bool,
                     use_topp: bool = False) -> Callable:
            key = (n, sampled, use_topp)
            if key not in self._step_cache:
                # cache miss = a program variant XLA must build; in
                # steady state this event never fires again (the trace
                # timeline makes a late one — a retrace — stand out)
                self.telemetry.jit_build("step", key)
                fn = step_fn_paged if self.paged else step_fn
                self._step_cache[key] = jax.jit(
                    partial(fn, n_ticks=n, use_sample=sampled,
                            use_topp=use_topp),
                    donate_argnums=(0, 1))
            return self._step_cache[key]

        self._get_step = get_step

        def paged_admit_fn(pk, pv, suffixes, slens, tables, pos):
            """Paged admission prefill: each row's (unshared) prompt
            suffix runs block-causally against pool K/V its table
            already maps — prefix-matched blocks behind ``pos`` read as
            if this row had prefilled them itself.  Monolithic
            admission IS one maximal chunk, so this is just
            ``prefill_chunk_paged``: writes limited to ``pos + slens``
            (suffix padding writes nothing), padding ROWS carry
            all-sink tables, and the return is each row's
            last-real-position logits (the head applied to [kb, 1, H]
            — never the [kb, sb, V] cube)."""
            return model.apply(
                variables, suffixes, pk, pv, tables, pos, slens,
                kernel=kern, mesh=kmesh, kv_sharded=kv_tp,
                method=TransformerLM.prefill_chunk_paged)

        self._paged_admit = jax.jit(paged_admit_fn,
                                    donate_argnums=(0, 1))

        def prefill_fn(prompts, plens):
            """Batched joiner prefill: [k, Pb] prompts in ONE forward
            (bursts amortise the admission cost k-fold); returns each
            row's last-real-position logits + stacked K/V."""
            logits, ks, vs = model.apply(variables, prompts,
                                         method=TransformerLM.prefill)
            last = jnp.take_along_axis(
                logits, (plens - 1)[:, None, None], axis=1)[:, 0]
            return last, ks, vs

        self._prefill = jax.jit(prefill_fn)

        def insert_fn(ck, cv, ks, vs, slot):
            ck = jax.lax.dynamic_update_slice(
                ck, ks.astype(ck.dtype), (0, slot, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                cv, vs.astype(cv.dtype), (0, slot, 0, 0, 0))
            return ck, cv

        self._insert = jax.jit(insert_fn, donate_argnums=(0, 1))

        # ---- fused chunked tick (decode + prefill chunks, ONE call) ----
        S_arena = S

        def fused_fn(ck, cv, tok, pos, done, temps, seeds, topps,
                     ctoks, cpos, clens, cslots, ctemps, cseeds,
                     ctopps, with_decode, use_sample, use_topp,
                     read_len):
            """One budget-bounded tick: decode EVERY slot once (bitwise
            the unfused 1-tick step — PREFILLING rows ride along frozen,
            their one garbage write at the fill frontier is overwritten
            by their own chunk below, in this same program), then run
            the tick's prefill chunks block-causally at their fill
            offsets via ``prefill_chunk`` on a compact ``[kb,
            read_len]`` cache window (gathered/scattered exactly like
            ``_prefix_admit``: padding rows carry the out-of-range slot
            index S — reads clamp, writes drop).  Returns the decode
            picks AND each chunk row's next-token pick: a prompt's
            first token is chosen the tick its last chunk lands, with
            the same rng position-fold as ``_pick_first``."""
            if with_decode:
                logits, ck, cv = model.apply(
                    variables, tok, ck, cv, pos,
                    method=TransformerLM.decode_step)
                nxt, done = pick_next(logits, pos, done, temps, seeds,
                                      topps, use_sample, use_topp)
                pos = jnp.minimum(pos + 1, Lmax - 1)
            else:
                nxt = tok
            read_idx = jnp.minimum(cslots, S_arena - 1)
            rows_k = jnp.take(ck, read_idx, axis=1)[:, :, :read_len]
            rows_v = jnp.take(cv, read_idx, axis=1)[:, :, :read_len]
            clog, rows_k, rows_v = model.apply(
                variables, ctoks, rows_k, rows_v, cpos, clens,
                method=TransformerLM.prefill_chunk)
            ck = ck.at[:, cslots, :read_len].set(
                rows_k.astype(ck.dtype), mode="drop")
            cv = cv.at[:, cslots, :read_len].set(
                rows_v.astype(cv.dtype), mode="drop")
            cnxt, _ = pick_next(
                clog, cpos + clens - 1,
                jnp.zeros(clens.shape, jnp.bool_), ctemps, cseeds,
                ctopps, use_sample, use_topp)
            return nxt, pos, done, cnxt, ck, cv

        def fused_paged_fn(pk, pv, tok, pos, done, tables, temps,
                           seeds, topps, ctoks, cpos, clens, ctabs,
                           ctemps, cseeds, ctopps, with_decode,
                           use_sample, use_topp):
            """The paged twin: chunks scatter through NARROW per-row
            tables (``ctabs`` [kb, Mb], host-sliced to the fill
            frontier, bucketed) — ``prefill_chunk_paged`` limits writes
            to ``cpos + clens`` so padding columns write nothing and
            the narrow window can never clamp a stray write into a
            live block.  Padding rows carry all-sink tables."""
            if with_decode:
                logits, pk, pv = model.apply(
                    variables, tok, pk, pv, tables, pos, kernel=kern,
                    mesh=kmesh, kv_sharded=kv_tp,
                    method=TransformerLM.decode_step_paged)
                nxt, done = pick_next(logits, pos, done, temps, seeds,
                                      topps, use_sample, use_topp)
                pos = jnp.minimum(pos + 1, Lmax - 1)
            else:
                nxt = tok
            clog, pk, pv = model.apply(
                variables, ctoks, pk, pv, ctabs, cpos, clens,
                kernel=kern, mesh=kmesh, kv_sharded=kv_tp,
                method=TransformerLM.prefill_chunk_paged)
            cnxt, _ = pick_next(
                clog, cpos + clens - 1,
                jnp.zeros(clens.shape, jnp.bool_), ctemps, cseeds,
                ctopps, use_sample, use_topp)
            return nxt, pos, done, cnxt, pk, pv

        # one program per (with_decode, sampled, topp, read_len) —
        # read_len only varies on the arena path (O(log L) buckets)
        self._fused_cache: Dict[Tuple[bool, bool, bool, int],
                                Callable] = {}

        def get_fused(with_decode: bool, sampled: bool, use_topp: bool,
                      read_len: int = 0) -> Callable:
            key = (with_decode, sampled, use_topp, read_len)
            if key not in self._fused_cache:
                self.telemetry.jit_build("fused", key)
                if self.paged:
                    fn = partial(fused_paged_fn,
                                 with_decode=with_decode,
                                 use_sample=sampled, use_topp=use_topp)
                else:
                    fn = partial(fused_fn, with_decode=with_decode,
                                 use_sample=sampled, use_topp=use_topp,
                                 read_len=read_len)
                self._fused_cache[key] = jax.jit(fn,
                                                 donate_argnums=(0, 1))
            return self._fused_cache[key]

        self._get_fused = get_fused

        if draft_model is not None:
            self._init_speculative(cdtype)

        # ---- prefix caching (shared system prompts) --------------------
        # register_prefix() prefills a prompt PREFIX once; requests that
        # name it splice the stored K/V and prefill only their suffix —
        # against the spliced cache, via the same block-causal decode_k
        # the speculative verify uses (bitwise = running the full
        # concatenated prompt).
        self._prefixes: Dict[int, tuple] = {}
        self._next_prefix_id = 0

        def _prefix_admit_for(m, v, want_logits):
            def fn(ck, cv, pks, pvs, suffixes, suffix_lens, slots):
                """Splice a stored prefix [layers, 1, P, H, D] into kb
                slots and run their suffixes through decode_k against it
                in ONE forward — a burst naming the same system prompt
                (the feature's primary workload) costs one device call,
                like the plain path's bucketed prefill.  The row count
                is padded to a power of two by the caller (bounded
                compile count, like _admit's kb); padding rows carry the
                OUT-OF-RANGE slot index S — their reads clamp and their
                scatter-back is dropped (mode='drop'), so they touch no
                real slot.  Real slots must be distinct (popped from the
                free list)."""
                P = pks.shape[2]
                kb = suffixes.shape[0]
                read_idx = jnp.minimum(slots, ck.shape[1] - 1)
                rows_k = jnp.take(ck, read_idx, axis=1)
                rows_v = jnp.take(cv, read_idx, axis=1)
                pref_k = jnp.broadcast_to(
                    pks, (pks.shape[0], kb) + pks.shape[2:])
                pref_v = jnp.broadcast_to(
                    pvs, (pvs.shape[0], kb) + pvs.shape[2:])
                rows_k = jax.lax.dynamic_update_slice(
                    rows_k, pref_k.astype(rows_k.dtype), (0, 0, 0, 0, 0))
                rows_v = jax.lax.dynamic_update_slice(
                    rows_v, pref_v.astype(rows_v.dtype), (0, 0, 0, 0, 0))
                # the suffix is ONE chunk at offset P: prefill_chunk is
                # the block-causal decode_k forward this path always
                # ran, minus the [kb, sb, V] logits cube (the head only
                # touches each row's last real position)
                last, rows_k, rows_v = m.apply(
                    v, suffixes, rows_k, rows_v,
                    jnp.full((kb,), P, jnp.int32), suffix_lens,
                    method=TransformerLM.prefill_chunk)
                ck = ck.at[:, slots].set(rows_k.astype(ck.dtype),
                                         mode="drop")
                cv = cv.at[:, slots].set(rows_v.astype(cv.dtype),
                                         mode="drop")
                if not want_logits:
                    return None, ck, cv
                return last, ck, cv

            return jax.jit(fn, donate_argnums=(0, 1))

        self._prefix_admit = _prefix_admit_for(model, variables, True)
        if self.draft_model is not None:
            self._draft_prefix_admit = _prefix_admit_for(
                self.draft_model, self._draft_variables, False)

        self._register_engine_gauges()

    def _register_engine_gauges(self) -> None:
        """Scrape-time gauges over engine/pool state: nothing is
        updated per tick — each callback reads the live value when
        /metrics is actually scraped, under the same lock its mutators
        hold (``n_waiting`` -> engine lock, pool fields -> pool lock),
        so a scrape can never see a torn value."""
        m = self.telemetry.metrics
        m.gauge("zoo_engine_queue_depth",
                "requests waiting for a slot", fn=lambda: self.n_waiting)
        # pre-registered (not lazily on first shed) so dashboards see
        # the stable zero whether or not any deadline ever expires
        m.counter("zoo_engine_deadline_admission_sheds_total",
                  "requests shed at admission because their deadline "
                  "had already passed (never reached prefill)")
        m.gauge("zoo_engine_active_slots",
                "resident requests (decode + prefilling)",
                fn=lambda: self.n_active)
        m.gauge("zoo_engine_peak_resident",
                "max co-resident requests observed",
                fn=lambda: self._peak_resident)
        # storage economics: constant per engine config, exported so a
        # scrape can compute tokens/sec/HBM-byte without knowing the
        # model geometry (int8 pools halve this vs bf16)
        m.gauge("zoo_engine_kv_bytes_per_token",
                "HBM bytes one cached token position costs across all "
                "layers and tenants",
                fn=lambda: self._kv_bytes_per_token)
        if self.paged:
            m.gauge("zoo_engine_kv_pool_bytes",
                    "total HBM bytes of the paged KV pools (target + "
                    "draft, all blocks)",
                    fn=lambda: (
                        self._per_block_bytes * self._pool.n_blocks
                        + (self._draft_per_block_bytes
                           * self._dpool.n_blocks
                           if self._dpool is not None else 0)))
        if self.chunked:
            def _budget_util():
                denom = self._budget_ticks * self.tick_token_budget
                return (self._budget_tokens_used / denom) if denom \
                    else 0.0

            m.gauge("zoo_engine_budget_utilization",
                    "mean filled fraction of the tick token budget",
                    fn=_budget_util)
            m.gauge("zoo_engine_prefill_stall_ticks_total",
                    "ticks whose budget left no room for any chunk",
                    fn=lambda: self._prefill_stall_ticks,
                    kind="counter")
        if self.paged:
            def _pool_read(key):
                def read():
                    with self._pool_lock:
                        return self._pool.metrics()[key]
                return read

            for key, name, kind, hlp in (
                    ("free_blocks", "zoo_engine_free_blocks", "gauge",
                     "pool blocks on the free list"),
                    ("cached_blocks", "zoo_engine_cached_blocks",
                     "gauge",
                     "unreferenced blocks parked in the prefix LRU"),
                    ("referenced_blocks", "zoo_engine_referenced_blocks",
                     "gauge", "blocks held by live requests"),
                    ("occupancy", "zoo_engine_pool_occupancy", "gauge",
                     "referenced fraction of non-sink blocks"),
                    ("prefix_hit_rate", "zoo_engine_prefix_hit_rate",
                     "gauge", "prefix-cache block hits / queries"),
                    ("prefix_queries", "zoo_engine_prefix_queries_total",
                     "counter", "prompt blocks offered to lookup()"),
                    ("prefix_hits", "zoo_engine_prefix_hits_total",
                     "counter", "prompt blocks answered from the index"),
                    ("evictions", "zoo_engine_pool_evictions_total",
                     "counter", "LRU evictions of cached blocks"),
                    ("alloc_failures",
                     "zoo_engine_pool_alloc_failures_total", "counter",
                     "allocate() calls the pool could not serve")):
                m.gauge(name, hlp, fn=_pool_read(key), kind=kind)
            # elastic pool + disaggregation surface: registered for
            # EVERY paged engine (zero until the features engage) so
            # dashboards and the doc-drift guard see stable names
            m.gauge("zoo_engine_pool_n_blocks",
                    "current per-tenant pool size in blocks (moves "
                    "only under elastic_pool)",
                    fn=lambda: self._pool.n_blocks)
            m.gauge("zoo_engine_pool_resize_total",
                    "applied elastic pool resizes (grow + shrink)",
                    fn=lambda: self._pool_resizes, kind="counter")
            m.gauge("zoo_engine_pool_resize_clamped_total",
                    "resize requests clamped at the eviction boundary "
                    "or the floor/ceiling",
                    fn=lambda: self._pool_resize_clamps,
                    kind="counter")
            m.gauge("zoo_engine_handoffs_out_total",
                    "prefilled rows exported to a decode replica",
                    fn=lambda: self._handoffs_out, kind="counter")
            m.gauge("zoo_engine_handoffs_in_total",
                    "prefilled rows adopted from a prefill replica",
                    fn=lambda: self._handoffs_in, kind="counter")
            # tiered-KV surface (serving/kv_store.py): same contract —
            # stable names for every paged engine, zero with the host
            # store off
            m.gauge("zoo_engine_kv_spill_chains_total",
                    "evicted blocks accepted by the host KV store",
                    fn=lambda: self._kv_spills, kind="counter")
            m.gauge("zoo_engine_kv_spill_bytes_total",
                    "KV bytes spilled to the host store",
                    fn=lambda: self._kv_spill_bytes, kind="counter")
            m.gauge("zoo_engine_kv_readmit_chains_total",
                    "host-store chains adopted back into the pool at "
                    "admission",
                    fn=lambda: self._kv_readmits, kind="counter")
            m.gauge("zoo_engine_kv_readmit_tokens_saved_total",
                    "prompt tokens served host->HBM instead of "
                    "re-prefilled",
                    fn=lambda: self._kv_readmit_tokens_saved,
                    kind="counter")
            m.gauge("zoo_engine_kv_store_bytes",
                    "host KV store occupancy in bytes",
                    fn=lambda: (self._kv_store.occupancy_bytes
                                if self._kv_store is not None else 0))
            if self._dpool is not None:
                def _dpool_read(key):
                    def read():
                        with self._pool_lock:
                            return self._dpool.metrics()[key]
                    return read

                for key, name, kind, hlp in (
                        ("free_blocks", "zoo_engine_draft_free_blocks",
                         "gauge", "draft-pool blocks on the free list"),
                        ("referenced_blocks",
                         "zoo_engine_draft_referenced_blocks", "gauge",
                         "draft-pool blocks held by live requests"),
                        ("occupancy", "zoo_engine_draft_pool_occupancy",
                         "gauge",
                         "referenced fraction of the draft pool"),
                        ("alloc_failures",
                         "zoo_engine_draft_pool_alloc_failures_total",
                         "counter", "draft-pool allocate() calls it "
                         "could not serve")):
                    m.gauge(name, hlp, fn=_dpool_read(key), kind=kind)

    def _init_speculative(self, cdtype):
        """Draft cache + the jitted spec-round programs.  One round per
        device call: draft proposes k per slot (k+1 cached feeds), the
        target verifies all slots' proposals in ONE decode_k forward,
        each slot advances by its own accepted count (per-row pointers).
        Arena mode gives the draft its own [layers, S, L, DH, DD] strip;
        paged mode addresses draft K/V through the second pool tenant's
        block tables — the SAME round structure, with verify writing its
        k+1 positions through the paged write path and rejection rolling
        the pointers back (``pos + n_emit``, never a block copy: entries
        past the new pointer are dead and the next round overwrites
        them in-place before anything attends that far)."""
        draft, dvars = self.draft_model, self._draft_variables
        model, variables = self.model, self._variables
        S, L, k = self._S, self._L, self._spec_k
        eos_id = self.eos_id
        kern = self.kernel
        kmesh = self.mesh if kern == "fused" else None
        kv_tp, dkv_tp = self._kv_tp, self._dkv_tp
        self._dpos = np.zeros(S, np.int32)

        if self.paged:
            def spec_step_paged(pk, pv, dpk, dpv, tok, pos, dpos, done,
                                tables, dtables):
                # draft: k proposals via k+1 greedy cached feeds through
                # the DRAFT tenant's tables (the extra feed writes
                # d_{k-1}'s KV so a full-acceptance round leaves the
                # draft pages complete — models/speculative.py)
                def dstep(c, _):
                    t, dpk, dpv, p = c
                    lg, dpk, dpv = draft.apply(
                        dvars, t, dpk, dpv, dtables, p, kernel=kern,
                        mesh=kmesh, kv_sharded=dkv_tp,
                        method=TransformerLM.decode_step_paged)
                    nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                    return (nxt, dpk, dpv, p + 1), nxt

                (_, dpk, dpv, _), d = jax.lax.scan(
                    dstep, (tok, dpk, dpv, dpos), None, length=k + 1)
                d = d.T[:, :k]                          # [S, k]

                # verify: k+1 positions written through the paged path
                # (rows with table rows all SINK — free/frozen — write
                # only sink-block garbage)
                inputs = jnp.concatenate([tok[:, None], d], axis=1)
                logits, pk, pv = model.apply(
                    variables, inputs, pk, pv, tables, pos,
                    kernel=kern, mesh=kmesh, kv_sharded=kv_tp,
                    method=TransformerLM.verify_step_paged)
                t, n_emit, new_tok, done = accept_proposals(
                    logits, d, tok, done, k=k, eos_id=eos_id)
                # pointer rollback IS the advance: rejected positions
                # stay physically written but unreachable (< pos never
                # attends past pos+j), and the next round re-writes them
                pos = jnp.minimum(pos + n_emit, L - 1)
                dpos = jnp.minimum(dpos + n_emit, L - 1)
                # [k+1, S] to match the plain step's emission order
                return (t.T, n_emit, new_tok, pos, dpos, done,
                        pk, pv, dpk, dpv)

            self._spec_step_paged = jax.jit(
                spec_step_paged, donate_argnums=(0, 1, 2, 3))

            def draft_paged_admit_fn(dpk, dpv, suffixes, slens, dtables,
                                     pos):
                """Draft-tenant admission prefill: the same grid the
                target's ``_paged_admit`` ran, against the draft pool —
                logits are discarded (only the target picks tokens)."""
                _, dpk, dpv = draft.apply(
                    dvars, suffixes, dpk, dpv, dtables, pos, slens,
                    kernel=kern, mesh=kmesh, kv_sharded=dkv_tp,
                    method=TransformerLM.prefill_chunk_paged)
                return dpk, dpv

            self._draft_paged_admit = jax.jit(draft_paged_admit_fn,
                                              donate_argnums=(0, 1))
        else:
            DH = getattr(draft, "kv_heads", draft.num_heads)
            DD = draft.hidden_size // draft.num_heads
            dkv_sh = None
            if self._dkv_tp:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                dkv_sh = NamedSharding(self.mesh,
                                       P(None, None, None, "tp", None))
            self._dck = jnp.zeros((draft.num_layers, S, L, DH, DD),
                                  cdtype, device=dkv_sh)
            self._dcv = jnp.zeros((draft.num_layers, S, L, DH, DD),
                                  cdtype, device=dkv_sh)

            def spec_step(ck, cv, dck, dcv, tok, pos, dpos, done):
                # draft: k proposals via k+1 greedy cached feeds (the
                # extra feed writes d_{k-1}'s KV so a full-acceptance
                # round leaves the draft cache complete)
                def dstep(c, _):
                    t, dck, dcv, p = c
                    lg, dck, dcv = draft.apply(
                        dvars, t, dck, dcv, p,
                        method=TransformerLM.decode_step)
                    nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                    return (nxt, dck, dcv, p + 1), nxt

                (_, dck, dcv, _), d = jax.lax.scan(
                    dstep, (tok, dck, dcv, dpos), None, length=k + 1)
                d = d.T[:, :k]                          # [S, k]

                inputs = jnp.concatenate([tok[:, None], d], axis=1)
                logits, ck, cv = model.apply(
                    variables, inputs, ck, cv, pos,
                    method=TransformerLM.verify_step)
                t, n_emit, new_tok, done = accept_proposals(
                    logits, d, tok, done, k=k, eos_id=eos_id)
                pos = jnp.minimum(pos + n_emit, L - 1)
                dpos = jnp.minimum(dpos + n_emit, L - 1)
                # [k+1, S] to match the plain step's emission order
                return (t.T, n_emit, new_tok, pos, dpos, done,
                        ck, cv, dck, dcv)

            self._spec_step = jax.jit(spec_step,
                                      donate_argnums=(0, 1, 2, 3))

            def draft_prefill_fn(prompts):
                _, ks, vs = draft.apply(dvars, prompts,
                                        method=TransformerLM.prefill)
                return ks, vs

            self._draft_prefill = jax.jit(draft_prefill_fn)

        if not self.chunked:
            return

        # ---- spec chunk program (greedy-only, both tenants) -----------
        # A spec tick with PREFILLING rows runs TWO device calls under
        # one token budget: the spec round above for decode rows, then
        # this chunk program, which lands prompt chunks in BOTH models'
        # caches (the draft must have the prompt's K/V before it can
        # propose) and picks each completing prompt's first token from
        # the TARGET logits.  Fusing the two would square the compile
        # grid (verify shapes x chunk shapes) to save zero host syncs —
        # both results are consumed by the same host step.
        if self.paged:
            def spec_chunk_paged_fn(pk, pv, dpk, dpv, ctoks, cpos,
                                    clens, ctabs, dctabs):
                clog, pk, pv = model.apply(
                    variables, ctoks, pk, pv, ctabs, cpos, clens,
                    kernel=kern, mesh=kmesh, kv_sharded=kv_tp,
                    method=TransformerLM.prefill_chunk_paged)
                _, dpk, dpv = draft.apply(
                    dvars, ctoks, dpk, dpv, dctabs, cpos, clens,
                    kernel=kern, mesh=kmesh, kv_sharded=dkv_tp,
                    method=TransformerLM.prefill_chunk_paged)
                # greedy-only by the submit() contract, so the first
                # pick is plain argmax (pick_next minus sampling/eos —
                # _record_token handles an eos first token host-side)
                cnxt = jnp.argmax(clog, -1).astype(jnp.int32)
                return cnxt, pk, pv, dpk, dpv

            self._spec_chunk_paged = jax.jit(
                spec_chunk_paged_fn, donate_argnums=(0, 1, 2, 3))
        else:
            def spec_chunk_fn(ck, cv, dck, dcv, ctoks, cpos, clens,
                              cslots, read_len):
                read_idx = jnp.minimum(cslots, S - 1)
                rows_k = jnp.take(ck, read_idx, axis=1)[:, :, :read_len]
                rows_v = jnp.take(cv, read_idx, axis=1)[:, :, :read_len]
                clog, rows_k, rows_v = model.apply(
                    variables, ctoks, rows_k, rows_v, cpos, clens,
                    method=TransformerLM.prefill_chunk)
                ck = ck.at[:, cslots, :read_len].set(
                    rows_k.astype(ck.dtype), mode="drop")
                cv = cv.at[:, cslots, :read_len].set(
                    rows_v.astype(cv.dtype), mode="drop")
                drows_k = jnp.take(dck, read_idx,
                                   axis=1)[:, :, :read_len]
                drows_v = jnp.take(dcv, read_idx,
                                   axis=1)[:, :, :read_len]
                _, drows_k, drows_v = draft.apply(
                    dvars, ctoks, drows_k, drows_v, cpos, clens,
                    method=TransformerLM.prefill_chunk)
                dck = dck.at[:, cslots, :read_len].set(
                    drows_k.astype(dck.dtype), mode="drop")
                dcv = dcv.at[:, cslots, :read_len].set(
                    drows_v.astype(dcv.dtype), mode="drop")
                cnxt = jnp.argmax(clog, -1).astype(jnp.int32)
                return cnxt, ck, cv, dck, dcv

            self._spec_chunk = jax.jit(
                spec_chunk_fn, static_argnames=("read_len",),
                donate_argnums=(0, 1, 2, 3))

    @staticmethod
    def _kv_kernels_tp_sharded(shardings) -> bool:
        """Do the chosen rules put 'tp' on the k/v projection outputs?
        Inspected from the sharding tree itself so the arena layout can
        never drift from what the kernels actually emit."""
        import jax as _jax

        for path, sh in _jax.tree_util.tree_flatten_with_path(
                shardings)[0]:
            keys = [str(getattr(p, "key", "")) for p in path]
            if "kernel" in keys and any(k in ("key", "value")
                                        for k in keys):
                spec = getattr(sh, "spec", ())
                if any(ax == "tp" or (isinstance(ax, tuple)
                                      and "tp" in ax) for ax in spec):
                    return True
        return False

    # ---- submission ---------------------------------------------------

    def capacity_report(self) -> dict:
        """Concrete arena economics (what GQA/cache_dtype actually buy):
        bytes per slot, total arena bytes, and the multiplier vs a
        full-head model-dtype arena of the same geometry."""
        m = self.model
        if self.paged:
            # pool layout is [layers, N, KH, bs, D] (head-major for
            # the fused kernel); int8 pools are QuantKV, so bill from
            # the init-time ledger rather than re-deriving off dtypes
            H = self._pk.shape[2]
            per_block = self._per_block_bytes
            per_slot_max = per_block * self._M
            arena_equiv = (per_block // self._bs) * self._L * self._S
            return {
                "mode": "paged",
                "slots": self._S,
                "cache_len": self._L,
                "kv_heads": H,
                "cache_dtype": str(self._pk.dtype),
                "kv_dtype": self.kv_dtype,
                "kernel": self.kernel,
                "kv_bytes_per_token": self._kv_bytes_per_token,
                "block_size": self._bs,
                "n_blocks": self._pool.n_blocks,
                "blocks_per_row_max": self._M,
                "bytes_per_block": per_block,
                "bytes_per_slot": per_slot_max,   # worst case; actual
                # residency is pay-as-you-grow + shared prefixes
                "arena_bytes": per_block * self._pool.n_blocks,
                "arena_equivalent_bytes": arena_equiv,
                # per-chip pressure follows the pool's ACTUAL sharding:
                # tp shards it over the kv-heads dim, a narrow-KV
                # (MQA/GQA) override replicates it
                "tp": (int(self.mesh.shape.get("tp", 1))
                       if self.mesh is not None else 1),
                "arena_bytes_per_chip":
                    per_block * self._pool.n_blocks
                    // (self._tp if self._kv_tp else 1),
                # the draft tenant's pool (0 without a draft model);
                # pinned prefixes live IN the pools for both tenants
                "draft_arena_bytes": (
                    self._draft_per_block_bytes * self._dpool.n_blocks
                    if self._dpool is not None else 0),
                "draft_n_blocks": (self._dpool.n_blocks
                                   if self._dpool is not None else 0),
                "prefix_bytes": 0,
            }
        H_full = m.num_heads
        H = self._ck.shape[3]
        D = self._ck.shape[4]
        per_slot = 2 * m.num_layers * self._L * H * D * \
            self._ck.dtype.itemsize
        full = 2 * m.num_layers * self._L * H_full * D * \
            jnp.dtype(m.dtype).itemsize
        tp = int(self.mesh.shape.get("tp", 1)) if self.mesh is not None \
            else 1
        # per-chip pressure follows the arena's ACTUAL sharding — a
        # narrow-KV override replicates it, so /tp would overstate
        spec = getattr(self._ck.sharding, "spec", None)
        arena_tp = tp if spec is not None and len(spec) > 3 \
            and spec[3] == "tp" else 1
        return {
            "slots": self._S,
            "cache_len": self._L,
            "kv_heads": H,
            "cache_dtype": str(self._ck.dtype),
            "bytes_per_slot": per_slot,
            "arena_bytes": per_slot * self._S,
            # tp shards the arena over chips: HBM pressure per chip is
            # arena/tp, so tp slots multiply like a narrower dtype does
            "tp": tp,
            "arena_bytes_per_chip": per_slot * self._S // arena_tp,
            "capacity_multiplier_vs_mha_model_dtype":
                round(full / per_slot, 2),
            # HBM the speculative/prefix features pin beyond the arena
            "draft_arena_bytes": (
                2 * int(np.prod(self._dck.shape))
                * self._dck.dtype.itemsize
                if self.draft_model is not None else 0),
            "prefix_bytes": sum(
                int(np.prod(e.shape)) * e.dtype.itemsize
                for entry in self._prefix_snapshot()
                for e in (entry[0], entry[1], entry[3], entry[4])
                if e is not None),
        }

    def _prefix_snapshot(self):
        # register/unregister mutate the dict from client threads;
        # iterate a locked copy
        with self._lock:
            return list(self._prefixes.values())

    @property
    def n_active(self) -> int:
        return self._S - len(self._free)

    @property
    def n_waiting(self) -> int:
        with self._lock:
            return len(self._waiting)

    def register_prefix(self, tokens: np.ndarray) -> int:
        """Prefill a shared prompt PREFIX (system prompt) once; returns
        an id for ``submit(..., prefix=id)``.  Requests then ship only
        their suffix: admission splices the stored K/V and runs the
        suffix against it (block-causal decode_k — bitwise what the
        full concatenated prompt would have produced)."""
        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim != 1 or len(tokens) < 1:
            raise ValueError("prefix must be a non-empty 1-D int32 array")
        P = len(tokens)
        if P >= self.max_prompt_width:
            raise ValueError(
                f"prefix length {P} leaves no room for a suffix inside "
                f"max prompt width {self.max_prompt_width}")
        if self.paged:
            return self._register_prefix_paged(tokens)
        _, ks, vs = self.model.apply(self._variables,
                                     jnp.asarray(tokens[None], jnp.int32),
                                     method=TransformerLM.prefill)
        entry = [jax.device_put(ks), jax.device_put(vs), P, None, None]
        if self.draft_model is not None:
            _, dks, dvs = self.draft_model.apply(
                self._draft_variables,
                jnp.asarray(tokens[None], jnp.int32),
                method=TransformerLM.prefill)
            entry[3], entry[4] = jax.device_put(dks), jax.device_put(dvs)
        with self._lock:
            pid = self._next_prefix_id
            self._next_prefix_id += 1
            self._prefixes[pid] = tuple(entry)
        return pid

    def unregister_prefix(self, pid: int) -> None:
        """Release a prefix's pinned device K/V (both models').  A
        long-running server registering per-tenant prefixes must be able
        to evict them or HBM ratchets up forever.  In-flight requests
        already admitted keep their spliced copy; queued requests naming
        the id will fail admission loudly.

        Paged mode: releases the pin on the prefix's blocks — they park
        in the pool's LRU (still shareable by chain-hash lookups) until
        allocation pressure actually evicts them."""
        if self.paged:
            with self._lock:
                if pid not in self._paged_prefixes:
                    raise ValueError(f"unknown prefix id {pid}")
                _, blocks, dblocks = self._paged_prefixes.pop(pid)
            with self._pool_lock:
                for b in blocks:
                    self._pool.release(b)
                for b in dblocks:
                    self._dpool.release(b)
            return
        with self._lock:
            if pid not in self._prefixes:
                raise ValueError(f"unknown prefix id {pid}")
            del self._prefixes[pid]

    def abort(self, uri: str) -> bool:
        """Drop a request nobody will collect (an abandoned client):
        remove it from the waiting queue, or free its resident slot —
        including BOTH pool tenants' blocks for a speculative paged row
        (``_release_slot_blocks``), so an abandoned row can never strand
        draft pages.  Call from the pump thread (the serving loop's
        prune pass runs there); resident-slot teardown touches the same
        per-slot state the tick mutates.  Returns True if the uri was
        found.  No callback fires — the caller already decided nobody
        is listening."""
        with self._lock:
            for req in self._waiting:
                if req.uri == uri:
                    self._waiting.remove(req)
                    self.telemetry.req_errored(uri, "aborted")
                    return True
        for slot, st in enumerate(self._slots):
            if st is not None and st.uri == uri:
                self._slots[slot] = None
                self._done[slot] = True     # frozen until readmission
                self._free.append(slot)
                if self.paged:
                    self._release_slot_blocks(slot)
                self.telemetry.req_errored(uri, "aborted")
                return True
        return False

    def submit(self, uri: str, prompt: np.ndarray,
               on_done: Optional[Callable] = None, *,
               on_error: Optional[Callable] = None,
               temperature: float = 0.0,
               rng_seed: Optional[int] = None,
               max_new: Optional[int] = None,
               prefix: Optional[int] = None,
               top_p: float = 0.0,
               on_token: Optional[Callable] = None,
               priority: str = "standard",
               tenant: str = "",
               handoff_cb: Optional[Callable] = None,
               deadline_t: float = 0.0) -> None:
        """Queue one request.  ``prompt``: 1-D int32 token array.
        ``on_done(uri, tokens)`` fires from the pump thread when the
        request finishes (tokens: ``[max_new]`` int32, eos-padded frozen
        tail); ``on_error(uri, exc)`` fires if admission (prefill/
        splice) fails after the request left the waiting queue — without
        it a device error there would silently swallow the request.  ``max_new`` (default: the engine budget) caps THIS
        request's tokens — slot-level budgets are a capability the
        whole-batch path structurally lacks (its one scan runs every
        row to the same length).  Raises on bounds violations — the
        serving layer error-publishes per request before calling this.

        Front-door fields (serving/frontdoor.py): ``on_token(uri,
        token, index)`` streams every generated token from the pump
        thread (the index dedups re-emissions after preemption);
        ``priority`` / ``tenant`` feed the QoS scheduler when the
        engine was built with a ``qos`` policy (recorded but inert
        otherwise).

        ``handoff_cb(state)`` marks THIS engine as the request's
        prefill side of a disaggregated fleet: the tick the prompt's
        first token lands, the row's KV block chain is exported
        (host table snapshot + materialized device pool slices), the
        row is freed here, and the callback receives the
        self-contained state dict to route to a decode replica's
        ``submit_handoff``.  Paged + greedy only (docs/serving_memory.md
        'Disaggregation & elastic pools')."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1:
            raise ValueError(f"prompt must be 1-D, got {prompt.shape}")
        n = len(prompt)
        if prefix is not None:
            with self._lock:
                if self.paged:
                    if prefix not in self._paged_prefixes:
                        raise ValueError(f"unknown prefix id {prefix}")
                    plen_pref = len(self._paged_prefixes[prefix][0])
                else:
                    if prefix not in self._prefixes:
                        raise ValueError(f"unknown prefix id {prefix}")
                    plen_pref = self._prefixes[prefix][2]
            # the TRUE prompt (prefix + suffix) must fit the prompt
            # budget; the padded suffix only needs to fit the cache
            # (_suffix_width handles that), so no bucket term here
            if n < 1 or plen_pref + n > self.max_prompt_width:
                raise ValueError(
                    f"prefix({plen_pref}) + suffix({n}) exceeds max "
                    f"prompt width {self.max_prompt_width}")
        elif n < 1 or n > self.max_prompt_width:
            raise ValueError(
                f"prompt length {n} outside [1, {self.max_prompt_width}]")
        if temperature > 0.0 and rng_seed is None:
            raise ValueError("temperature > 0 needs rng_seed")
        if temperature > 0.0 and self.draft_model is not None:
            raise ValueError(
                "speculative continuous batching is greedy-only (the "
                "sampled contract needs rejection sampling); submit "
                "with temperature=0 or build the engine without a draft")
        if rng_seed is not None:
            # mask into uint32 range: an out-of-range client seed must
            # not crash the pump thread at the np.uint32 staging array
            rng_seed = int(rng_seed) & 0xFFFFFFFF
        mn = self.max_new_tokens if max_new is None else int(max_new)
        if not 1 <= mn <= self.max_new_tokens:
            raise ValueError(
                f"max_new {mn} outside [1, {self.max_new_tokens}]")
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}")
        if handoff_cb is not None:
            if not self.paged:
                raise ValueError(
                    "handoff_cb requires paged=True: a prefill/decode "
                    "handoff exports a KV BLOCK chain; the arena engine "
                    "has no block tables to rewrite")
            if temperature > 0.0:
                raise ValueError(
                    "prefill/decode handoff is greedy-only: a sampled "
                    "row's RNG stream cannot be split across replicas "
                    "bitwise; submit with temperature=0 or without "
                    "handoff_cb")
            if self.draft_model is not None:
                raise ValueError(
                    "prefill/decode handoff does not compose with "
                    "speculative decoding yet: the draft tenant's block "
                    "chain would have to ship alongside the target's "
                    "(the ROADMAP follow-on 'spec-aware KV handoff' "
                    "lifts this); serve the disaggregated fleet without "
                    "a draft model")
        deadline_t = float(deadline_t or 0.0)
        if deadline_t > 0.0:
            # deadline-aware admission sweeps cost a queue scan per
            # tick — armed only once the FIRST deadline ever arrives,
            # so deadline-free deployments pay nothing
            self._deadline_seen = True
        # stamp AFTER validation: a rejected submit never existed as
        # far as queue-wait/TTFT accounting is concerned
        self.telemetry.req_enqueued(uri)
        with self._lock:
            self._waiting.append(_Req(
                uri, prompt, on_done, on_error, float(temperature),
                rng_seed, mn, prefix, float(top_p), on_token,
                priority, str(tenant), time.monotonic(), handoff_cb,
                deadline_t=deadline_t))

    def submit_handoff(self, state: dict) -> None:
        """Adopt a prefilled request exported by another engine's
        ``handoff_cb``: queue it for admission as a DECODE row whose KV
        block chain is copied from the shipped pool slices instead of
        recomputed.  ``state`` is the self-contained dict
        ``_handoff_slot`` built on the source (prompt, emitted tokens,
        chain hashes, materialized K/V slices, completion callbacks).
        Thread-safe like ``submit`` — the source pump may call straight
        into the destination engine; all device writes happen later on
        THIS engine's pump thread at admission."""
        if not self.paged:
            raise ValueError(
                "submit_handoff requires a paged engine: the handoff "
                "wire format is a KV block chain")
        if self.draft_model is not None:
            raise ValueError(
                "prefill/decode handoff does not compose with "
                "speculative decoding yet (ROADMAP follow-on "
                "'spec-aware KV handoff'); the decode replica must "
                "serve without a draft model")
        chain = state["chain"]
        if int(chain["block_size"]) != self._bs:
            raise ValueError(
                f"handoff block_size {chain['block_size']} != this "
                f"engine's block_size {self._bs}")
        if chain["kv_dtype"] != self.kv_dtype:
            raise ValueError(
                f"handoff kv_dtype {chain['kv_dtype']!r} != this "
                f"engine's kv_dtype {self.kv_dtype!r}")
        plen = int(state["plen"])
        mn = int(state["max_new"])
        if plen > self.max_prompt_width:
            raise ValueError(
                f"handoff prompt length {plen} exceeds max prompt "
                f"width {self.max_prompt_width}")
        if mn > self.max_new_tokens:
            raise ValueError(
                f"handoff max_new {mn} exceeds engine budget "
                f"{self.max_new_tokens}")
        self.telemetry.req_enqueued(state["uri"])
        with self._lock:
            self._waiting.append(_Req(
                state["uri"], np.asarray(state["prompt"], np.int32),
                state.get("on_done"), state.get("on_error"),
                0.0, None, mn, None, 0.0, state.get("on_token"),
                state.get("priority", "standard"),
                state.get("tenant", ""), time.monotonic(),
                None, state))

    # ---- pump ---------------------------------------------------------

    def _admit(self) -> int:
        """Move waiting requests into free slots.  Joiners sharing a
        prompt bucket prefill TOGETHER in one forward (row count padded
        to a power of two so a burst costs a handful of compiles, not
        one per burst size); their K/V splice into slots one
        dynamic_update_slice each.  Returns the number admitted."""
        if self._deadline_seen:
            self._shed_expired_waiting()
        deferred = (self._brownout_defer_extract()
                    if self._brownout_level >= 1 else None)
        try:
            admitted = self._admit_pass()
            if deferred and admitted == 0 and self._free \
                    and not len(self._waiting):
                # work-conserving brownout: the ladder gates NEW
                # arrivals (front door 429s), but work already accepted
                # must not strand — with zero admissible demand and
                # slots free, idling while holding a backlog wastes the
                # very capacity the ladder protects AND latches the
                # controller (the held queue keeps the depth signal
                # above the exit threshold forever).  Serve the held
                # classes opportunistically; under real pressure the
                # first pass admits or leaves admissible waiting, so
                # this pass never runs and the shed holds.
                with self._lock:
                    for req in reversed(deferred):
                        self._waiting.appendleft(req)
                deferred = None
                admitted = self._admit_pass()
            return admitted
        finally:
            if deferred:
                # deferred classes return to the FRONT of their own
                # subqueues in original order — held, not reordered, so
                # they admit untouched the moment the ladder descends
                with self._lock:
                    for req in reversed(deferred):
                        self._waiting.appendleft(req)

    def _admit_pass(self) -> int:
        if self.chunked:
            return self._admit_chunked()
        if self.paged:
            return self._admit_paged()
        return self._admit_arena()

    def _shed_expired_waiting(self) -> None:
        """Admission-time deadline shed: every waiting request whose
        ``deadline_t`` already passed terminates NOW with a
        ``deadline_exceeded`` error — before any prefill work, before
        claiming a slot, before touching either KV pool.  An overloaded
        engine must not burn its scarcest resource (tick budget) on
        work nobody is waiting for anymore."""
        now = time.monotonic()
        with self._lock:
            expired = [r for r in self._waiting
                       if getattr(r, "deadline_t", 0.0) > 0.0
                       and now > r.deadline_t]
            for r in expired:
                self._waiting.remove(r)
        for r in expired:
            self._deadline_sheds += 1
            self.telemetry.deadline_shed(r.uri)
            late_ms = (now - r.deadline_t) * 1e3
            self._req_error(r.uri, r.on_error, DeadlineExceeded(
                f"deadline_exceeded: deadline passed {late_ms:.0f}ms "
                f"before admission"))

    def _brownout_defer_extract(self) -> list:
        """Pull every waiting request whose class the current brownout
        level sheds OUT of the queue for this admission pass (the
        caller reinserts them at the front afterwards).  Held requests
        keep aging — their enq_t is untouched — so a descending ladder
        admits them with their full waited-time priority."""
        lvl = self._brownout_level
        with self._lock:
            deferred = [r for r in self._waiting
                        if not scheduler_policy.brownout_admit(
                            lvl, getattr(r, "priority", "standard"))]
            for r in deferred:
                self._waiting.remove(r)
        return deferred

    def _admit_arena(self) -> int:
        admitted = 0
        while self._free:
            with self._lock:
                grab = min(len(self._free), len(self._waiting))
                batch = [self._waiting.popleft() for _ in range(grab)]
            if not batch:
                break
            by_bucket: Dict[int, list] = {}
            by_prefix: Dict[Tuple[int, int], list] = {}
            for req in batch:
                if req.prefix is not None:  # prefix-cached request
                    with self._lock:
                        P = self._prefixes.get(req.prefix,
                                               (None, None, 0))[2]
                    sb = self._suffix_width(len(req.prompt), P)
                    by_prefix.setdefault((req.prefix, sb),
                                         []).append(req)
                    continue
                pb = _next_bucket(len(req.prompt), self.prompt_buckets)
                by_bucket.setdefault(pb, []).append(req)
            for (pid, sb), reqs in by_prefix.items():
                try:
                    admitted += self._admit_prefix_group(pid, sb, reqs)
                except Exception as e:
                    logger.exception(
                        "prefix admission failed for %d request(s), "
                        "prefix %s", len(reqs), pid)
                    for req in reqs:
                        self._req_error(req.uri, req.on_error, e)
            for pb, reqs in by_bucket.items():
                # a failed prefill/splice must not swallow requests that
                # already left the waiting queue: surface each one to
                # its error callback and keep admitting other groups
                try:
                    k = len(reqs)
                    kb = 1 << (k - 1).bit_length()  # pad rows to pow2
                    padded = np.full((kb, pb), self.pad_id, np.int32)
                    plens = np.ones(kb, np.int32)   # dummy rows: len 1
                    for i, req in enumerate(reqs):
                        padded[i, :len(req.prompt)] = req.prompt
                        plens[i] = len(req.prompt)
                    pre = self._prefill(jnp.asarray(padded, jnp.int32),
                                        jnp.asarray(plens, jnp.int32))
                    if self.draft_model is not None:
                        pre = pre + self._draft_prefill(
                            jnp.asarray(padded, jnp.int32))
                    # ONE host fetch of the bucket's first-token logits;
                    # per-request picks below then stay on numpy
                    pre = (np.asarray(pre[0]),) + tuple(pre[1:])
                except Exception as e:
                    logger.exception(
                        "prefill failed for %d request(s), bucket %d",
                        len(reqs), pb)
                    for req in reqs:
                        self._req_error(req.uri, req.on_error, e)
                    continue
                for i, req in enumerate(reqs):
                    try:
                        self._splice_one(pre, i, req)
                        admitted += 1
                    except Exception as e:
                        logger.exception("splice failed for %r", req.uri)
                        self._req_error(req.uri, req.on_error, e)
        return admitted

    def _req_error(self, uri, on_error, exc):
        self.telemetry.req_errored(uri, f"{type(exc).__name__}: {exc}")
        if on_error is None:
            return
        try:
            on_error(uri, exc)
        except Exception:
            logger.exception("on_error callback failed for %r", uri)

    def _suffix_width(self, n: int, P: int) -> int:
        """Padded width for a prefix request's suffix: a shared prompt
        bucket when one fits after the prefix (bounded compile count),
        else the exact remaining cache room (one compile per prefix
        length — still bounded by registered prefixes).  Suffix padding
        writes dead K/V past the true prompt; they are never attended
        and later rounds overwrite them, so only the CACHE bound (L)
        applies, not the prompt budget."""
        for b in self.prompt_buckets:
            if n <= b and P + b <= self._L - 1:
                return b
        return self._L - 1 - P

    def _admit_prefix_group(self, pid: int, sb: int, reqs) -> int:
        """Admission for prefix-cached requests sharing (prefix, suffix
        width): splice the stored K/V into each group member's slot and
        run ALL their suffixes against it in one decode_k forward — the
        semantics of prefilling each concatenated prompt, at one device
        call per burst.  Returns the number admitted."""
        with self._lock:
            if pid not in self._prefixes:
                raise ValueError(f"prefix id {pid} was unregistered "
                                 f"while queued")
            pks, pvs, P, dks, dvs = self._prefixes[pid]
        n = min(len(reqs), len(self._free))
        if n < len(reqs):
            # free slots ran out mid-batch: requeue the rest in order
            with self._lock:
                for req in reversed(reqs[n:]):
                    self._waiting.appendleft(req)
            reqs = reqs[:n]
        if not reqs:
            return 0
        # pad rows to a power of two (bounded compile count, like the
        # bucketed prefill); padding rows target the out-of-range slot
        # index S — reads clamp, writes drop
        kb = 1 << (n - 1).bit_length()
        padded = np.full((kb, sb), self.pad_id, np.int32)
        lens = np.ones(kb, np.int32)
        for i, req in enumerate(reqs):
            padded[i, :len(req.prompt)] = req.prompt
            lens[i] = len(req.prompt)
        real = [self._free.popleft() for _ in range(n)]
        slots = real + [self._S] * (kb - n)
        try:
            last, self._ck, self._cv = self._prefix_admit(
                self._ck, self._cv, pks, pvs,
                jnp.asarray(padded, jnp.int32),
                jnp.asarray(lens, jnp.int32),
                jnp.asarray(slots, jnp.int32))
            if self.draft_model is not None:
                _, self._dck, self._dcv = self._draft_prefix_admit(
                    self._dck, self._dcv, dks, dvs,
                    jnp.asarray(padded, jnp.int32),
                    jnp.asarray(lens, jnp.int32),
                    jnp.asarray(slots, jnp.int32))
        except Exception:
            self._free.extend(real)
            raise
        last = np.asarray(last)     # one D2H for the whole group
        admitted = 0
        for i, req in enumerate(reqs):
            try:
                plen = P + int(lens[i])
                first = self._pick_first(last[i], plen,
                                         req.temperature, req.rng_seed,
                                         req.top_p)
                self._install_slot(real[i], req.uri, plen, req.max_new,
                                   req.on_done, req.on_error,
                                   req.temperature, req.rng_seed,
                                   first, req.top_p,
                                   on_token=req.on_token,
                                   priority=req.priority)
                admitted += 1
            except Exception as e:
                self._free.append(real[i])
                self._req_error(req.uri, req.on_error, e)
        return admitted

    # ---- chunked admission (PREFILLING slots, no device call) ---------

    def _admit_chunked(self) -> int:
        """Chunked admission runs NO prefill: it only claims a slot,
        installs it in the ``PREFILLING`` state, and (paged) attaches
        any prefix-matched blocks — the prompt feeds the cache chunk by
        chunk inside the fused tick, interleaved with decodes under
        the token budget.  A paged request the pool can't start yet
        requeues at the front and admission stops (order preserved);
        mid-prompt growth handles the rest per chunk."""
        admitted = 0
        while self._free:
            with self._lock:
                req = self._waiting.popleft() if self._waiting else None
            if req is None:
                break
            res = (self._admit_one_chunked_paged(req) if self.paged
                   else self._admit_one_chunked(req))
            if res == "admitted":
                admitted += 1
            elif res == "blocked":
                with self._lock:
                    self._waiting.appendleft(req)
                break
        return admitted

    def _admit_one_chunked(self, req: _Req) -> str:
        """Arena chunked admission: splice a named prefix's stored K/V
        (chunks then run against it block-causally, like the monolithic
        prefix path) and install the slot PREFILLING at the prefix
        boundary."""
        base = 0
        pks = pvs = dks = dvs = None
        if req.prefix is not None:
            with self._lock:
                entry = self._prefixes.get(req.prefix)
            if entry is None:
                self._req_error(req.uri, req.on_error, ValueError(
                    f"prefix id {req.prefix} was unregistered while "
                    f"queued"))
                return "error"
            pks, pvs, base = entry[0], entry[1], entry[2]
            dks, dvs = entry[3], entry[4]
        slot = self._free.popleft()
        if pks is not None:
            try:
                self._ck, self._cv = self._insert(
                    self._ck, self._cv, pks, pvs, jnp.int32(slot))
                if self.draft_model is not None:
                    # the draft's chunks run against the SAME spliced
                    # prefix boundary, so its cache needs the prefix too
                    self._dck, self._dcv = self._insert(
                        self._dck, self._dcv, dks, dvs,
                        jnp.int32(slot))
            except Exception as e:
                self._free.append(slot)
                logger.exception("chunked prefix splice failed for %r",
                                 req.uri)
                self._req_error(req.uri, req.on_error, e)
                return "error"
        self._install_prefill(slot, req, base + len(req.prompt),
                              base=base, full=req.prompt)
        return "admitted"

    def _admit_one_chunked_paged(self, req: _Req) -> str:
        """Paged chunked admission: match + acquire leading full prompt
        blocks (copy-free sharing, capped at ``(plen-1)//bs`` so the
        last token always recomputes for its first-token logits) and
        install PREFILLING at the matched boundary.  Blocks for the
        unmatched tail are allocated PER CHUNK by the tick scheduler —
        a mid-prompt dry pool preempts this prefilling row back to the
        queue, never a decoder."""
        if req.handoff_state is not None:
            return self._admit_handoff(req)
        try:
            full = self._full_prompt(req)
        except Exception as e:
            self._req_error(req.uri, req.on_error, e)
            return "error"
        plen = len(full)
        hashes = self._pool.block_hashes(full)
        total = -(-plen // self._bs)
        # errors surface AFTER the lock: on_error is arbitrary user
        # code and must never run under _pool_lock
        err: Optional[Exception] = None
        with self._pool_lock:
            matched = self._pool.lookup(
                hashes[:(plen - 1) // self._bs])
            dmatch = None
            if self._dpool is not None:
                # the fill frontier is one number for both tenants, so
                # the usable prefix match is the shorter of the two
                dmatch = self._dpool.lookup(
                    hashes[:(plen - 1) // self._bs])
                m = min(len(matched), len(dmatch))
                matched, dmatch = matched[:m], dmatch[:m]
            need = total - len(matched)
            cap = self._pool.n_blocks - 1
            if self._dpool is not None:
                cap = min(cap, self._dpool.n_blocks - 1)
            # per-chunk allocation only needs room to START (first
            # chunk block + decode headroom); monolithic admission's
            # need+1 gate would block exactly the long prompts
            # chunking exists to stream in
            dry = self._pool.allocatable() < 2 or (
                self._dpool is not None
                and self._dpool.allocatable() < 2)
            if need + 1 > cap:
                err = ValueError(
                    f"prompt needs {need} private blocks + headroom "
                    f"but the pool holds {cap}")
            elif dry:
                if self.n_active == 0:
                    err = RuntimeError(
                        f"pool dry with no residents: "
                        f"{self._pool.num_referenced()} of "
                        f"{self._pool.n_blocks} blocks are pinned "
                        f"(unregister a prefix or raise n_blocks)")
                else:
                    return "blocked"
            else:
                for b in matched:
                    self._pool.acquire(b)
                if dmatch is not None:
                    for b in dmatch:
                        self._dpool.acquire(b)
                if self._kv_store is not None:
                    # tiered KV: extend the pinned device match from
                    # the host store.  The probe window is capped so
                    # adoption leaves the >= 2 allocatable blocks the
                    # chunked dry gate just guaranteed — the first
                    # chunk must still be able to start.  (No draft
                    # tenant here: the store refuses speculative
                    # engines at construction.)
                    limit = min((plen - 1) // self._bs,
                                len(matched)
                                + max(0, self._pool.allocatable() - 2))
                    matched = matched + self._store_readmit(
                        hashes, len(matched), limit)
        if err is not None:
            self._req_error(req.uri, req.on_error, err)
            return "error"
        # adoption may have evicted (spill pending) and recorded host
        # payloads; flush both before the tick's device work
        self._drain_spills()
        self._apply_readmits()
        slot = self._free.popleft()
        self._row_blocks[slot] = list(matched)
        self._tables[slot, :] = SINK_BLOCK
        self._tables[slot, :len(matched)] = matched
        if dmatch is not None:
            self._drow_blocks[slot] = list(dmatch)
            self._dtables[slot, :] = SINK_BLOCK
            self._dtables[slot, :len(dmatch)] = dmatch
        self._install_prefill(slot, req, plen, base=0, full=full,
                              hashes=list(hashes),
                              fill=len(matched) * self._bs,
                              n_pub=len(matched))
        return "admitted"

    def _install_prefill(self, slot: int, req: _Req, plen: int, *,
                         base: int, full, hashes=None, fill=None,
                         n_pub: int = 0) -> None:
        """Install a slot in the PREFILLING state: the decode side sees
        a frozen row (done=True, fed pad) anchored at the fill frontier
        until its last chunk lands.  ``fill`` (paged) starts past
        prefix-matched blocks; arena rows start past the spliced
        prefix (``base``)."""
        self._slots[slot] = _Slot(
            uri=req.uri, plen=plen,
            max_new=self._brownout_mn(req.priority, req.max_new),
            on_done=req.on_done, on_error=req.on_error,
            temperature=req.temperature, rng_seed=req.rng_seed,
            top_p=req.top_p, req=req, admit_seq=self._admit_seq,
            on_token=req.on_token,
            state="PREFILLING",
            fill_pos=base if fill is None else fill,
            base=base, full=np.asarray(full, np.int32),
            hashes=hashes, n_pub=n_pub)
        self._admit_seq += 1
        self._tok[slot] = self.pad_id
        self._pos[slot] = self._slots[slot].fill_pos
        if self.draft_model is not None:
            self._dpos[slot] = self._slots[slot].fill_pos
        self._done[slot] = True
        self.telemetry.req_admitted(req.uri, slot, prefilling=True,
                                    priority=req.priority)

    # ---- paged mode (block-pool cache) --------------------------------

    def _full_prompt(self, req: _Req) -> np.ndarray:
        """The TRUE token sequence a paged request decodes: a
        ``prefix=`` id expands to its registered tokens + the suffix —
        the chain-hash index then shares the pinned blocks
        automatically, subsuming the arena's device-side splice."""
        if req.prefix is None:
            return req.prompt
        with self._lock:
            if req.prefix not in self._paged_prefixes:
                raise ValueError(f"prefix id {req.prefix} was "
                                 f"unregistered while queued")
            ptoks = self._paged_prefixes[req.prefix][0]
        return np.concatenate([ptoks, req.prompt])

    def _register_prefix_paged(self, tokens: np.ndarray) -> int:
        """Pin a shared prefix's FULL blocks in the pool (ref held until
        ``unregister_prefix``): prefill them once through the paged
        path, publish their chain hashes, and store the tokens so
        ``submit(prefix=id)`` requests concatenate host-side and match
        the pinned blocks at admission.  The partial tail beyond the
        last full block recomputes per request inside its suffix (a
        partial block can never be shared — it would keep growing)."""
        P = len(tokens)
        bs = self._bs
        nfull = P // bs
        hashes = self._pool.block_hashes(tokens[:nfull * bs])

        def pin(pool, admit, pk, pv):
            """Pin one tenant's full prefix blocks: match, allocate the
            rest, prefill the unmatched span through the tenant's paged
            path, publish.  Returns (blocks, pk, pv) — the buffers come
            back because ``admit`` donates its inputs."""
            with self._pool_lock:
                matched = pool.lookup(hashes)
                for b in matched:
                    pool.acquire(b)
                blocks = list(matched)
                for _ in range(nfull - len(matched)):
                    b = pool.allocate()
                    if b is None:
                        for bb in blocks:
                            pool.release(bb)
                        raise RuntimeError(
                            f"{pool.name} block pool has no room to pin "
                            f"a {nfull}-block prefix "
                            f"({pool.num_referenced()} of "
                            f"{pool.n_blocks} blocks referenced)")
                    blocks.append(b)
            # allocation may have evicted indexed blocks: gather their
            # old bytes before the admit below rewrites the ids (the
            # buffers are still self._pk/_pv here — admit's donation
            # hasn't happened yet; the draft tenant never spills)
            self._drain_spills()
            if len(matched) < nfull:
                span = tokens[len(matched) * bs:nfull * bs]
                sb = _next_bucket(len(span), self.prompt_buckets)
                padded = np.full((1, sb), self.pad_id, np.int32)
                padded[0, :len(span)] = span
                tabs = np.full((1, self._M), SINK_BLOCK, np.int32)
                tabs[0, :len(blocks)] = blocks
                # target admit returns (logits, pk, pv); draft (pk, pv)
                out = admit(pk, pv, jnp.asarray(padded, jnp.int32),
                            jnp.asarray([len(span)], jnp.int32),
                            jnp.asarray(tabs, jnp.int32),
                            jnp.asarray([len(matched) * bs], jnp.int32))
                pk, pv = out[-2:]
                with self._pool_lock:
                    for j in range(len(matched), nfull):
                        pool.insert(hashes[j], blocks[j])
            return blocks, pk, pv

        blocks, self._pk, self._pv = pin(
            self._pool, self._paged_admit, self._pk, self._pv)
        dblocks: tuple = ()
        if self._dpool is not None:
            try:
                dblocks, self._dpk, self._dpv = pin(
                    self._dpool, self._draft_paged_admit,
                    self._dpk, self._dpv)
            except Exception:
                # a half-pinned prefix would leak target blocks forever
                with self._pool_lock:
                    for b in blocks:
                        self._pool.release(b)
                raise
        with self._lock:
            pid = self._next_prefix_id
            self._next_prefix_id += 1
            self._paged_prefixes[pid] = (tokens, blocks, dblocks)
        return pid

    def _admit_handoff(self, req: _Req) -> str:
        """Adopt a prefill exported by another engine (the decode half
        of a prefill/decode handoff): allocate a same-length block
        chain via ``adopt_chain`` (carried prefix hashes republished,
        first writer wins, so the decode side keeps sharing the
        prefix), SCATTER the shipped pool slices into this engine's
        arena at the new block ids, and install the slot directly in
        DECODE at the donor's position — no prefill forward runs here.
        A pool that can't hold the chain yet blocks (requeue at the
        front), and a preemption later requeues the same request with
        its immutable ``handoff_state``, so re-adoption regenerates
        the identical row."""
        state = req.handoff_state
        chain = state["chain"]
        n = int(chain["n"])
        # errors surface AFTER the lock: on_error is arbitrary user
        # code and must never run under _pool_lock
        err: Optional[Exception] = None
        with self._pool_lock:
            # +1 headroom mirrors monolithic admission: the first
            # decode tokens must not instantly preempt the adoption
            cap = self._pool.n_blocks - 1
            if n + 1 > cap:
                err = ValueError(
                    f"handoff chain needs {n} blocks + headroom but "
                    f"the pool holds {cap}")
            elif self._pool.allocatable() < n + 1:
                if self.n_active == 0:
                    err = RuntimeError(
                        f"pool dry with no residents: "
                        f"{self._pool.num_referenced()} of "
                        f"{self._pool.n_blocks} blocks are pinned "
                        f"(unregister a prefix or raise n_blocks)")
                else:
                    return "blocked"
            else:
                blocks = self._pool.adopt_chain(chain)
                if blocks is None:
                    return "blocked"
        if err is not None:
            self._req_error(req.uri, req.on_error, err)
            return "error"
        # adoption may have evicted indexed blocks (spill pending) and
        # an adopted id may BE one — gather before the scatter below
        self._drain_spills()
        idx = jnp.asarray(blocks, jnp.int32)

        def scatter(d, s):
            out = d.at[:, idx].set(jnp.asarray(s, d.dtype))
            return jax.device_put(out, d.sharding)

        self._pk = jax.tree_util.tree_map(scatter, self._pk,
                                          state["k"])
        self._pv = jax.tree_util.tree_map(scatter, self._pv,
                                          state["v"])
        slot = self._free.popleft()
        self._row_blocks[slot] = list(blocks)
        self._tables[slot, :] = SINK_BLOCK
        self._tables[slot, :len(blocks)] = blocks
        self._slots[slot] = _Slot(
            uri=req.uri, plen=int(state["plen"]), max_new=req.max_new,
            tokens=list(state["tokens"]), on_done=req.on_done,
            on_error=req.on_error, temperature=0.0, rng_seed=None,
            top_p=0.0, on_token=req.on_token, req=req,
            admit_seq=self._admit_seq)
        self._admit_seq += 1
        # the donor already emitted token[0]; decode resumes from it
        self._tok[slot] = int(state["last_token"])
        self._pos[slot] = int(state["pos"])
        self._done[slot] = False
        self._handoffs_in += 1
        self.telemetry.req_admitted(req.uri, slot,
                                    priority=req.priority)
        # two-phase handoff ack: adoption is now durable on THIS
        # engine, so the source may release its retained state.  The
        # callback is record-only by contract (the broker pops a
        # pending-handoff entry and bumps a counter) and must never
        # re-enter this engine.
        ack = state.get("on_adopt")
        if ack is not None:
            try:
                ack(req.uri, self._replica_id)
            except Exception:
                logger.exception("handoff adoption ack failed for %r",
                                 req.uri)
        return "admitted"

    # ---- tiered KV memory (serving/kv_store.py) -----------------------

    def _store_evicted(self, hash_: int) -> None:
        """HostKVStore capacity-eviction callback: the host copy is
        gone, retract the host-tier directory claim (device-tier
        claims are untouched — the block may still be indexed)."""
        if self._prefix_directory is not None:
            self._prefix_directory.unpublish(self._replica_id, hash_,
                                             TIER_HOST)

    def _pool_index_event(self, kind: str, *, hash_: int,
                          block: int) -> None:
        """BlockPool index_cb: mirror device-index membership into the
        fleet PrefixDirectory (fires under ``_pool_lock``; the
        directory has its own lock and never re-enters the pool)."""
        if kind == "publish":
            self._prefix_directory.publish(self._replica_id, hash_,
                                           TIER_HBM)
        else:
            self._prefix_directory.unpublish(self._replica_id, hash_,
                                             TIER_HBM)

    def _spill_block(self, block: int, hash_: int) -> None:
        """BlockPool spill_cb: an indexed CACHED block is being
        evicted — record it so the pump thread copies its K/V to the
        host tier before the block id is rewritten.  Fires under
        ``_pool_lock``, so per the record-only contract
        (``paged_cache.CALLBACK_CONTRACT``) it must not touch the
        device: the D2H gather happens in ``_drain_spills``, which
        every evicting path runs before its next device write.  Until
        then ``self._pk``/``self._pv`` still hold exactly the bytes
        the hash describes — the pump thread is the only arena
        writer, and it drains before it scatters."""
        self._pending_spills.append((int(block), hash_))

    def _drain_spills(self) -> None:
        """Flush pool-eviction spills recorded by ``_spill_block``:
        ONE batched D2H gather for the whole wave (vs the per-block
        fetch the under-lock path used to make), then host-store puts
        and directory publishes — all outside ``_pool_lock``.  Must
        run before any device write that could touch an evicted block
        id (a just-allocated or adopted id may BE one): admission,
        growth, handoff scatter, and pool-shrink slicing all drain
        first.  Pump thread only, like every arena access."""
        with self._pool_lock:
            pending, self._pending_spills = self._pending_spills, []
        if not pending:
            return
        idx = jnp.asarray([b for b, _ in pending], jnp.int32)

        def gather(x):
            return jnp.take(x, idx, axis=1)

        fetched = jax.device_get({
            "k": jax.tree_util.tree_map(gather, self._pk),
            "v": jax.tree_util.tree_map(gather, self._pv),
        })      # one D2H for the whole spill wave
        for i, (_, hash_) in enumerate(pending):
            payload = jax.tree_util.tree_map(
                lambda x: x[:, i:i + 1], fetched)
            if self._kv_store.put(hash_, payload, self._per_block_bytes):
                self._kv_spills += 1
                self._kv_spill_bytes += self._per_block_bytes
                if self._prefix_directory is not None:
                    self._prefix_directory.publish(
                        self._replica_id, hash_, TIER_HOST)

    def _store_readmit(self, hashes, n_matched: int,
                       max_blocks: int) -> List[int]:
        """Extend a device-index prefix match from the host tier:
        probe the store for the hashes PAST the device match, adopt
        the hit chain back into the pool (all-or-nothing with
        rollback, carried hashes republished first-writer-wins — the
        PR 15 contract), and RECORD the host payloads for
        ``_apply_readmits`` to scatter after the lock is released
        (tpulint TZ102: no H2D under the pool lock).  Admission
        applies every recorded scatter before its prefill device call
        — and before releasing blocks on a failure — so a republished
        block is never read, shared, or recycled holding garbage.
        Returns the adopted block ids (ref=1 each, [] on miss or dry
        pool — the store entries survive either way).  Caller holds
        ``_pool_lock``; the caller already holds a reference on every
        device-matched block (adoption's allocate may evict CACHED
        blocks, and a pinned match cannot be among them)."""
        run = self._kv_store.probe(hashes[n_matched:max_blocks])
        if not run:
            return []
        chain = {"block_size": self._bs, "kv_dtype": self.kv_dtype,
                 "n": len(run), "hashes": [h for h, _ in run]}
        blocks = self._pool.adopt_chain(chain)
        if blocks is None:
            return []

        def cat(*leaves):
            return np.concatenate(leaves, axis=1)

        kcat = jax.tree_util.tree_map(cat, *[p["k"] for _, p in run])
        vcat = jax.tree_util.tree_map(cat, *[p["v"] for _, p in run])
        self._pending_readmits.append((list(blocks), kcat, vcat))
        self._kv_readmits += 1
        self._kv_readmit_tokens_saved += len(blocks) * self._bs
        return blocks

    def _apply_readmits(self) -> None:
        """Scatter host-tier payloads recorded by ``_store_readmit``
        into the device pool.  Runs outside ``_pool_lock``, AFTER
        ``_drain_spills`` (an adopted id may be a just-evicted id
        whose old content the spill must gather first) and before the
        admission's prefill call reads the blocks."""
        pending, self._pending_readmits = self._pending_readmits, []
        for blocks, kcat, vcat in pending:
            idx = jnp.asarray(blocks, jnp.int32)

            def scatter(d, s):
                out = d.at[:, idx].set(jnp.asarray(s, d.dtype))
                return jax.device_put(out, d.sharding)

            self._pk = jax.tree_util.tree_map(scatter, self._pk, kcat)
            self._pv = jax.tree_util.tree_map(scatter, self._pv, vcat)

    def _admit_paged(self) -> int:
        """Paged admission: per request, match leading FULL prompt
        blocks in the chain-hash index (copy-free sharing), allocate
        private blocks for the rest, and prefill only the unshared
        suffix — grouped by suffix bucket so a burst costs one device
        call per bucket.  A request the pool can't hold yet requeues at
        the FRONT (order preserved) and admission stops — residents
        finishing or preemption will free blocks.  The match length is
        capped at ``(plen-1)//bs`` blocks so the LAST prompt token
        always recomputes: its forward yields the first-token logits
        (a 100% cache hit would leave nothing to run)."""
        admitted = 0
        while self._free:
            with self._lock:
                grab = min(len(self._free), len(self._waiting))
                batch = [self._waiting.popleft() for _ in range(grab)]
            if not batch:
                break
            plans, blocked = [], []
            for req in batch:
                if blocked:         # keep queue order behind the block
                    blocked.append(req)
                    continue
                if req.handoff_state is not None:
                    # adopted chains never prefill — no plan, no group
                    res = self._admit_handoff(req)
                    if res == "admitted":
                        admitted += 1
                    elif res == "blocked":
                        blocked.append(req)
                    continue
                try:
                    full = self._full_prompt(req)
                except Exception as e:
                    self._req_error(req.uri, req.on_error, e)
                    continue
                plen = len(full)
                hashes = self._pool.block_hashes(full)
                total = -(-plen // self._bs)
                # errors surface AFTER the lock: on_error is arbitrary
                # user code and must never run under _pool_lock
                err: Optional[Exception] = None
                planned = False
                with self._pool_lock:
                    matched = self._pool.lookup(
                        hashes[:(plen - 1) // self._bs])
                    if self._dpool is not None:
                        # both tenants must prefill the SAME suffix, so
                        # the usable match is the shorter of the two
                        # (identical op sequences keep the pools mirror
                        # images; the min is a safety net, not a tax)
                        dmatch = self._dpool.lookup(
                            hashes[:(plen - 1) // self._bs])
                        m = min(len(matched), len(dmatch))
                        matched, dmatch = matched[:m], dmatch[:m]
                    need = total - len(matched)
                    # +1 headroom: the first decode tokens must not
                    # instantly preempt what admission just built
                    cap = self._pool.n_blocks - 1
                    if self._dpool is not None:
                        cap = min(cap, self._dpool.n_blocks - 1)
                    dry = self._pool.allocatable() < need + 1 or (
                        self._dpool is not None
                        and self._dpool.allocatable() < need + 1)
                    if need + 1 > cap:
                        err = ValueError(
                            f"prompt needs {need} private blocks + "
                            f"headroom but the pool holds {cap}")
                    elif dry:
                        if (self.n_active == 0 and not plans
                                and admitted == 0):
                            # nothing in flight will ever free blocks:
                            # only prefix pins hold the pool
                            err = RuntimeError(
                                f"pool dry with no residents: "
                                f"{self._pool.num_referenced()} of "
                                f"{self._pool.n_blocks} blocks are "
                                f"pinned (unregister a prefix or "
                                f"raise n_blocks)")
                        else:
                            blocked.append(req)
                    else:
                        for b in matched:
                            self._pool.acquire(b)
                        if self._kv_store is not None:
                            # tiered KV: extend the (now pinned — the
                            # adoption below allocates, and allocation
                            # may evict CACHED blocks, never a pinned
                            # match) device match from the host store.
                            # Adoption consumes exactly the allocatable
                            # blocks the shrunken ``need`` no longer
                            # asks for, so the dry gate above still
                            # guarantees the allocate loop below.  No
                            # draft tenant here: the store refuses
                            # speculative engines at construction.
                            matched = matched + self._store_readmit(
                                hashes, len(matched),
                                (plen - 1) // self._bs)
                            need = total - len(matched)
                        blocks = list(matched)
                        for _ in range(need):
                            blocks.append(self._pool.allocate())
                        dblocks = None
                        if self._dpool is not None:
                            for b in dmatch:
                                self._dpool.acquire(b)
                            dblocks = list(dmatch)
                            for _ in range(need):
                                dblocks.append(self._dpool.allocate())
                        planned = True
                if err is not None:
                    self._req_error(req.uri, req.on_error, err)
                    continue
                if not planned:
                    continue
                plans.append((req, full, hashes, len(matched), blocks,
                              dblocks))
            if blocked:
                with self._lock:
                    for req in reversed(blocked):
                        self._waiting.appendleft(req)
            # deferred pool-callback device work, in dependency order:
            # spills gather an evicted id's OLD bytes before the
            # readmit scatter (or the group prefill below) rewrites it
            self._drain_spills()
            self._apply_readmits()
            groups: Dict[int, list] = {}
            for plan in plans:
                slen = len(plan[1]) - plan[3] * self._bs
                sb = _next_bucket(slen, self.prompt_buckets)
                groups.setdefault(sb, []).append(plan)
            for sb, plist in groups.items():
                try:
                    admitted += self._admit_paged_group(sb, plist)
                except Exception as e:
                    logger.exception("paged admission failed for %d "
                                     "request(s)", len(plist))
                    with self._pool_lock:
                        for req, _, _, _, blocks, dblocks in plist:
                            for b in blocks:
                                self._pool.release(b)
                            for b in dblocks or ():
                                self._dpool.release(b)
                    for req, _, _, _, _, _ in plist:
                        self._req_error(req.uri, req.on_error, e)
            if blocked:
                break
        return admitted

    def _admit_paged_group(self, sb: int, plans) -> int:
        """One paged-prefill device call for every planned request
        sharing a suffix bucket (rows padded to a power of two;
        padding rows carry all-sink tables and touch nothing real).
        After the call each row's full private prompt blocks are
        published in the hash index, so the NEXT identical prompt
        shares them."""
        n = len(plans)
        kb = 1 << (n - 1).bit_length()
        padded = np.full((kb, sb), self.pad_id, np.int32)
        lens = np.ones(kb, np.int32)
        pos = np.zeros(kb, np.int32)
        tabs = np.full((kb, self._M), SINK_BLOCK, np.int32)
        dtabs = np.full((kb, self._M), SINK_BLOCK, np.int32)
        for i, (req, full, hashes, n_match, blocks,
                dblocks) in enumerate(plans):
            sfx = full[n_match * self._bs:]
            padded[i, :len(sfx)] = sfx
            lens[i] = len(sfx)
            pos[i] = n_match * self._bs
            tabs[i, :len(blocks)] = blocks
            if dblocks is not None:
                dtabs[i, :len(dblocks)] = dblocks
        last, self._pk, self._pv = self._paged_admit(
            self._pk, self._pv, jnp.asarray(padded, jnp.int32),
            jnp.asarray(lens, jnp.int32), jnp.asarray(tabs, jnp.int32),
            jnp.asarray(pos, jnp.int32))
        if self._dpool is not None:
            # the SAME suffix grid against the draft tenant (min-match
            # keeps the two prefills byte-aligned); draft logits are
            # discarded — only the target picks tokens
            self._dpk, self._dpv = self._draft_paged_admit(
                self._dpk, self._dpv, jnp.asarray(padded, jnp.int32),
                jnp.asarray(lens, jnp.int32),
                jnp.asarray(dtabs, jnp.int32),
                jnp.asarray(pos, jnp.int32))
        last = np.asarray(last)     # one D2H for the whole group
        admitted = 0
        for i, (req, full, hashes, n_match, blocks,
                dblocks) in enumerate(plans):
            plen = len(full)
            slot = self._free.popleft()
            self._row_blocks[slot] = blocks
            self._tables[slot, :] = SINK_BLOCK
            self._tables[slot, :len(blocks)] = blocks
            if dblocks is not None:
                self._drow_blocks[slot] = dblocks
                self._dtables[slot, :] = SINK_BLOCK
                self._dtables[slot, :len(dblocks)] = dblocks
            # publish BEFORE install: the prefill succeeded, so the
            # blocks' content is valid for sharing even if this
            # particular install fails below
            with self._pool_lock:
                for j in range(n_match, plen // self._bs):
                    self._pool.insert(hashes[j], blocks[j])
                if dblocks is not None:
                    for j in range(n_match, plen // self._bs):
                        self._dpool.insert(hashes[j], dblocks[j])
            try:
                first = self._pick_first(last[i], plen,
                                         req.temperature, req.rng_seed,
                                         req.top_p)
                self._install_slot(slot, req.uri, plen, req.max_new,
                                   req.on_done, req.on_error,
                                   req.temperature, req.rng_seed,
                                   first, req.top_p, req=req,
                                   on_token=req.on_token,
                                   priority=req.priority)
                admitted += 1
            except Exception as e:
                self._free.append(slot)
                self._release_slot_blocks(slot)
                self._req_error(req.uri, req.on_error, e)
        return admitted

    def _ensure_blocks(self, active) -> list:
        """Grow each resident's block table to cover the positions the
        coming chunk will write.  When the pool is dry, PREEMPT the
        latest admission (never the oldest — earliest requests keep
        strict forward progress, so this terminates): its blocks free
        up, its request requeues at the queue front, and its tokens
        regenerate deterministically on readmission.  Returns the
        still-active subset."""
        for i in list(active):
            st = self._slots[i]
            if st is None:
                continue
            if self.draft_model is not None:
                # a spec round writes k+1 verify positions pos..pos+k
                # (both tenants — dpos == pos)
                last_write = min(int(self._pos[i]) + self._spec_k,
                                 self._L - 1)
            else:
                ticks = max(1, min(self.ticks_per_step,
                                   st.max_new - len(st.tokens)))
                last_write = min(int(self._pos[i]) + ticks - 1,
                                 self._L - 1)
            self._grow_row(i, last_write // self._bs + 1)
        # growth allocations may have evicted indexed blocks: gather
        # their bytes before the coming step writes the reused ids
        self._drain_spills()
        return [i for i in active if self._slots[i] is not None]

    def _grow_row(self, i: int, need: int) -> None:
        """Grow row ``i``'s block table(s) to ``need`` blocks,
        preempting (latest admission, prefilling rows first) whenever a
        pool is dry — including row ``i`` itself, which ends the loop.
        With a draft model the two tenants grow in LOCKSTEP to the same
        block count: either pool running dry preempts the victim from
        BOTH (``_release_slot_blocks``), so a row's verify pointer can
        never outrun its draft pages."""
        self._grow_tenant(i, need, self._pool, self._row_blocks,
                          self._tables)
        if self._dpool is not None:
            self._grow_tenant(i, need, self._dpool, self._drow_blocks,
                              self._dtables)

    def _grow_tenant(self, i: int, need: int, pool, row_blocks,
                     tables) -> None:
        while (self._slots[i] is not None
               and len(row_blocks[i]) < need):
            with self._pool_lock:
                b = pool.allocate()
            if b is None:
                self._preempt(self._pick_victim())
                continue
            j = len(row_blocks[i])
            row_blocks[i].append(b)
            tables[i, j] = b

    def _grow_chunk_blocks(self, decode_rows, chunks) -> None:
        """Per-tick paged growth for the fused step: decode rows need
        their one write position covered; each chunk row needs blocks
        through its chunk's last write.  Pool-dry preemption targets
        the LATEST PREFILLING row first (``_pick_victim``) — decoders
        that already emitted tokens are never evicted to feed a
        joiner's prompt."""
        for i in decode_rows:
            if self._slots[i] is None:
                continue
            # spec decode rows write k+1 verify positions (spec_k is 0
            # without a draft, reducing to the single decode write)
            last_write = min(int(self._pos[i]) + self._spec_k,
                             self._L - 1)
            self._grow_row(i, last_write // self._bs + 1)
        for i, clen in chunks:
            st = self._slots[i]
            if st is None:
                continue
            self._grow_row(i, (st.fill_pos + clen - 1) // self._bs + 1)
        # growth allocations may have evicted indexed blocks: gather
        # their bytes before the fused step writes the reused ids
        self._drain_spills()

    def _publish_chunk_blocks(self, i: int, st: _Slot) -> None:
        """Hash-publish the prompt blocks a landed chunk fully covered
        (never the frontier block — a partially written block must not
        be shared), so the NEXT identical prompt attaches copy-free,
        exactly like monolithic admission's post-prefill publish."""
        if st.hashes is None:
            return
        hi = min(st.fill_pos // self._bs, st.plen // self._bs)
        if hi <= st.n_pub:
            return
        blocks = self._row_blocks[i]
        with self._pool_lock:
            for j in range(st.n_pub, hi):
                self._pool.insert(st.hashes[j], blocks[j])
            if self._dpool is not None:
                # same hashes (keys are token chains, not tenant-
                # specific); lockstep growth keeps the lists aligned
                dblocks = self._drow_blocks[i]
                for j in range(st.n_pub, hi):
                    self._dpool.insert(st.hashes[j], dblocks[j])
        st.n_pub = hi

    def _table_width(self, need: int) -> int:
        """Pow2-bucketed narrow table width for a chunk grid: wide
        enough for every position the chunks write/attend, capped at
        the full table width M."""
        v = 1
        while v < need:
            v *= 2
        return min(v, self._M)

    def _pick_victim(self) -> int:
        # the choice itself is pure policy (serving/policy.py): the
        # simulator makes the identical decision from modelled state
        return scheduler_policy.pick_victim(
            (i, s.state, s.admit_seq)
            for i, s in enumerate(self._slots) if s is not None)

    def _preempt(self, slot: int) -> None:
        """Evict a resident back to the WAITING queue (front, original
        request intact, partial tokens discarded) and free its blocks.
        Readmission recomputes the prompt — recompute-not-swap, the
        vLLM default — and regenerates the same tokens (greedy argmax;
        sampled rows fold the rng by absolute position)."""
        st = self._slots[slot]
        self._slots[slot] = None
        self._done[slot] = True
        self._free.append(slot)
        self._release_slot_blocks(slot)
        self._preemptions += 1
        if st.state == "PREFILLING":
            self._prefill_preemptions += 1
        logger.warning("block pool dry: preempted %r (recompute on "
                       "readmission)", st.uri)
        with self._lock:
            self._waiting.appendleft(st.req)
        # TTFT keeps the original arrival; partial tokens are
        # discarded, so their stamps go too (telemetry mirrors both)
        self.telemetry.req_preempted(
            st.uri, slot, prefilling=st.state == "PREFILLING")

    def _release_slot_blocks(self, slot: int) -> None:
        """Drop a finished/preempted row's block references and point
        its whole table row at the sink, so the frozen row's future
        writes can NEVER touch a block the pool hands to someone else
        — the paged form of the arena's recycled-slot isolation.  Both
        tenants release together: a row never holds draft pages after
        its target pages are gone (or vice versa)."""
        blocks = self._row_blocks[slot]
        self._row_blocks[slot] = []
        self._tables[slot, :] = SINK_BLOCK
        dblocks = []
        if self._dpool is not None:
            dblocks = self._drow_blocks[slot]
            self._drow_blocks[slot] = []
            self._dtables[slot, :] = SINK_BLOCK
        with self._pool_lock:
            for b in blocks:
                self._pool.release(b)
            for b in dblocks:
                self._dpool.release(b)

    def resize_pool(self, target: int) -> int:
        """Grow or shrink BOTH tenants' block pools toward ``target``
        blocks (clamped to [floor, ceiling]) and pad/slice the device
        arenas to match.  Shrink only sheds the contiguous
        unreferenced TAIL of the id space — the arena is dense in
        block id, so the eviction boundary (``BlockPool.shrink``)
        stops at the first referenced block: cached tail blocks are
        evicted, a referenced block NEVER is, and a deeper request is
        clamped and counted rather than raised.  Both tenants move in
        lockstep (the min of their shrinkable tails) so the mirror-
        image invariant the speculative path relies on survives.
        Pump thread only: the arenas are donated through the step
        programs, so no device call may be in flight.  Returns the
        signed block delta actually applied."""
        if not self.paged:
            raise ValueError("resize_pool requires paged=True")
        want = int(target)
        target = max(self._pool_floor,
                     min(want, self._pool_ceiling or want))
        clamped = target != want
        with self._pool_lock:
            n = self._pool.n_blocks
            if target > n:
                applied = self._pool.grow(target - n)
                if self._dpool is not None:
                    self._dpool.grow(target - n)
            elif target < n:
                m = min(n - target, self._pool.shrinkable())
                if self._dpool is not None:
                    m = min(m, self._dpool.shrinkable())
                if m < n - target:
                    clamped = True
                applied = -self._pool.shrink(m) if m else 0
                if m and self._dpool is not None:
                    self._dpool.shrink(m)
            else:
                applied = 0
        # shrink evicts the cached tail: gather those blocks' bytes
        # into the host tier BEFORE fit() slices them off the arena
        self._drain_spills()
        if clamped:
            self._pool_resize_clamps += 1
        if applied == 0:
            return 0
        new_n = n + applied

        def fit(x):
            if applied > 0:
                pad = [(0, 0)] * x.ndim
                pad[1] = (0, applied)
                out = jnp.pad(x, pad)
            else:
                out = x[:, :new_n]
            # keep the mesh layout: a resized pool must land exactly
            # where the step programs expect their donated operands
            return jax.device_put(out, x.sharding)

        self._pk = jax.tree_util.tree_map(fit, self._pk)
        self._pv = jax.tree_util.tree_map(fit, self._pv)
        if self._dpool is not None:
            self._dpk = jax.tree_util.tree_map(fit, self._dpk)
            self._dpv = jax.tree_util.tree_map(fit, self._dpv)
        self._pool_resizes += 1
        logger.info("elastic pool resized %d -> %d blocks (%+d)",
                    n, new_n, applied)
        return applied

    def maybe_autoresize(self,
                         goodput: Optional[Dict[str, float]] = None
                         ) -> int:
        """One elastic-pool control step (pump thread): feed the
        current pool pressure — allocatable blocks and fresh
        allocation failures since the last call — plus the caller's
        per-class goodput map into the pure ``plan_pool_resize``
        policy, and execute any non-zero delta via ``resize_pool``.
        No-op (returns 0) unless built with ``elastic_pool=True``."""
        if not (self.paged and self.elastic_pool):
            return 0
        with self._pool_lock:
            n = self._pool.n_blocks
            alloc = self._pool.allocatable()
            fails = self._pool.alloc_failures
            if self._dpool is not None:
                alloc = min(alloc, self._dpool.allocatable())
                fails += self._dpool.alloc_failures
        streak = fails - self._autoresize_last_fails
        self._autoresize_last_fails = fails
        delta = scheduler_policy.plan_pool_resize(
            n_blocks=n, allocatable=alloc, alloc_fail_streak=streak,
            step=self._resize_step, floor=self._pool_floor,
            ceiling=self._pool_ceiling, goodput=goodput)
        if delta == 0:
            return 0
        return self.resize_pool(n + delta)

    def cache_metrics(self) -> dict:
        """Serving-visible cache counters (bench_serving.py columns).

        The snapshot is taken under the ENGINE lock (and, for the pool
        merge, the pool lock), so a caller on another thread can never
        see torn state — e.g. a queue depth from before a preemption
        merged with pool occupancy from after it.  Field semantics:

        - **cumulative** (monotonic since construction): ``preemptions``,
          ``prefill_stall_ticks``, ``prefill_preemptions``, and the
          pool's ``prefix_queries`` / ``prefix_hits`` / ``evictions`` /
          ``alloc_failures``.  ``peak_resident`` and
          ``budget_utilization`` are cumulative aggregates (running max
          / running mean), not resettable rates.
        - **instantaneous** (value at snapshot time):
          ``prefill_queue_depth``, ``chunks_in_flight``, and the pool's
          ``free_blocks`` / ``cached_blocks`` / ``referenced_blocks`` /
          ``occupancy`` (plus the static ``mode`` / ``chunked`` /
          ``tick_token_budget`` / ``n_blocks`` / ``block_size``).

        The same values are exported continuously (and individually
        documented) by the telemetry registry — this dict remains for
        callers that want one coherent point-in-time snapshot."""
        with self._lock:
            out = {
                "mode": "paged" if self.paged else "arena",
                "preemptions": self._preemptions,
                "peak_resident": self._peak_resident,
                "qos": self._qos is not None,
            }
            if self._qos is not None:
                out["qos_waiting"] = {
                    f"{cls}/{tenant}": d for (cls, tenant), d in
                    self._waiting.depths().items()}
            if self.chunked:
                denom = self._budget_ticks * self.tick_token_budget
                out.update({
                    "chunked": True,
                    "tick_token_budget": self.tick_token_budget,
                    # mean fraction of each fused tick's budget
                    # actually filled with decode rows + chunk tokens
                    "budget_utilization": (
                        self._budget_tokens_used / denom
                        if denom else 0.0),
                    # len() directly: self.n_waiting re-acquires the
                    # non-reentrant engine lock we already hold
                    "prefill_queue_depth": len(self._waiting),
                    "chunks_in_flight": sum(
                        1 for s in self._slots
                        if s is not None and s.state == "PREFILLING"),
                    "prefill_stall_ticks": self._prefill_stall_ticks,
                    "prefill_preemptions": self._prefill_preemptions,
                })
            if self.draft_model is not None:
                out.update({
                    "speculation_k": self._spec_k,
                    "spec_rounds": getattr(self, "_spec_rounds", 0),
                    "spec_emitted": getattr(self, "_spec_emitted", 0),
                    # cumulative draft proposals / acceptances (same
                    # counters /metrics exports); the ratio is the
                    # acceptance rate the bench records
                    "spec_proposed": self.telemetry.c_spec_proposed.value,
                    "spec_accepted": self.telemetry.c_spec_accepted.value,
                })
        if self.paged:
            with self._pool_lock:
                out.update(self._pool.metrics())
                if self._dpool is not None:
                    # draft tenant, prefixed — one snapshot shows both
                    # pools' pressure side by side
                    out.update({"draft_" + kk: vv for kk, vv in
                                self._dpool.metrics().items()})
            out.update({
                "pool_resizes": self._pool_resizes,
                "pool_resize_clamps": self._pool_resize_clamps,
                "pool_floor": self._pool_floor,
                "pool_ceiling": self._pool_ceiling,
                "handoffs_out": self._handoffs_out,
                "handoffs_in": self._handoffs_in,
                "kv_spills": self._kv_spills,
                "kv_spill_bytes": self._kv_spill_bytes,
                "kv_readmits": self._kv_readmits,
                "kv_readmit_tokens_saved":
                    self._kv_readmit_tokens_saved,
                "kv_store_bytes": (self._kv_store.occupancy_bytes
                                   if self._kv_store is not None
                                   else 0),
            })
        return out

    @property
    def record_timings(self) -> bool:
        """Back-compat shim: raw per-request stamp retention now lives
        in the telemetry facade (the percentile histograms are always
        on regardless — this flag only controls the unbounded per-uri
        store ``pop_request_timings`` drains)."""
        return self.telemetry.keep_request_stamps

    @record_timings.setter
    def record_timings(self, v: bool) -> None:
        self.telemetry.keep_request_stamps = bool(v)

    def pop_request_timings(self) -> Dict[str, dict]:
        """Drain per-request wall-clock stamps collected under
        ``record_timings=True``: uri -> {"arrival": t, "token_times":
        [t0, t1, ...]} (``time.monotonic()`` seconds).  TTFT =
        token_times[0] - arrival; TPOT = consecutive token_times
        deltas.  Clears the store — the bench pops once per run.
        The stamps are written by the SAME telemetry hooks that feed
        the always-on histograms, so the two surfaces agree by
        construction."""
        return self.telemetry.pop_request_stamps()

    def _install_slot(self, slot, uri, plen, mn, on_done, on_error,
                      temp, seed, first, top_p=0.0, req=None,
                      on_token=None, priority=None):
        """Shared slot-state installation for every admission path —
        plain bucket splice and prefix admission must never drift."""
        self._slots[slot] = _Slot(
            uri=uri, plen=plen, max_new=self._brownout_mn(priority, mn),
            on_done=on_done,
            on_error=on_error, temperature=temp, rng_seed=seed,
            top_p=top_p, req=req, admit_seq=self._admit_seq,
            on_token=on_token)
        self._admit_seq += 1
        self._tok[slot] = first
        self._pos[slot] = plen
        if self.draft_model is not None:
            self._dpos[slot] = plen
        self._done[slot] = False
        self.telemetry.req_admitted(uri, slot, priority=priority)
        self._record_token(slot, int(first))

    def _splice_one(self, pre, i: int, req) -> None:
        """Insert one prefetched joiner into a free slot; the slot goes
        back to the free list if the splice fails."""
        last_logits, ks, vs = pre[0], pre[1], pre[2]
        uri, prompt = req.uri, req.prompt
        temp, seed, tp = req.temperature, req.rng_seed, req.top_p
        mn, on_done, on_error = req.max_new, req.on_done, req.on_error
        slot = self._free.popleft()
        try:
            self._ck, self._cv = self._insert(
                self._ck, self._cv, ks[:, i:i + 1], vs[:, i:i + 1],
                jnp.int32(slot))
            if self.draft_model is not None:
                dks, dvs = pre[3], pre[4]
                self._dck, self._dcv = self._insert(
                    self._dck, self._dcv, dks[:, i:i + 1],
                    dvs[:, i:i + 1], jnp.int32(slot))
            plen = len(prompt)
            first = self._pick_first(last_logits[i], plen, temp, seed,
                                     tp)
        except Exception:
            self._free.append(slot)
            raise
        self._install_slot(slot, uri, plen, mn, on_done, on_error,
                           temp, seed, first, tp,
                           on_token=req.on_token, priority=req.priority)

    def _pick_first(self, last_logits, plen: int, temp: float,
                    seed, top_p: float = 0.0) -> int:
        """The prefill's last-position logits produce the request's first
        token — same pick semantics (and rng position-fold) as
        ``generate``'s step at t = plen-1.  ``last_logits`` arrives as
        host numpy: every admission path fetches its whole group's
        logits in ONE transfer, so the common greedy pick costs zero
        device round-trips per request."""
        if temp <= 0.0:
            return int(np.argmax(last_logits))
        key = jax.random.fold_in(jax.random.key(int(seed)), plen - 1)
        scaled = jnp.asarray(last_logits, jnp.float32) / temp
        if top_p > 0.0:
            scaled = top_p_filter(scaled, jnp.float32(top_p))
        # sampled admission must reproduce pick_next's categorical
        # bitwise (a preempted-and-readmitted row regenerates the same
        # token), so the draw stays on device: one sync per SAMPLED
        # admission only (baselined).
        return int(jax.random.categorical(key, scaled))

    def _handoff_slot(self, slot: int, st: _Slot) -> None:
        """Export a just-prefilled row for adoption by another engine
        (the prefill half of a prefill/decode handoff).  Runs on the
        pump thread at first-token time: snapshot the block chain +
        published hashes (``export_chain``), GATHER the row's pool
        slices into fresh device buffers (the live pool is DONATED
        through later step programs, so the copy must materialize
        now), then free the slot exactly like a completion.  The
        state dict is self-contained — the destination engine needs
        nothing further from this one."""
        blocks = list(self._row_blocks[slot])
        with self._pool_lock:
            chain = self._pool.export_chain(blocks)
        idx = jnp.asarray(blocks, jnp.int32)

        def gather(x):
            return jnp.take(x, idx, axis=1)

        state = {
            "uri": st.uri,
            "prompt": np.asarray(self._full_prompt(st.req), np.int32),
            "plen": st.plen,
            "pos": int(self._pos[slot]),
            "tokens": list(st.tokens),
            "last_token": int(st.tokens[-1]),
            "max_new": st.max_new,
            "priority": st.req.priority,
            "tenant": st.req.tenant,
            "chain": chain,
            "k": jax.tree_util.tree_map(gather, self._pk),
            "v": jax.tree_util.tree_map(gather, self._pv),
            "on_done": st.on_done,
            "on_error": st.on_error,
            "on_token": st.on_token,
        }
        self._slots[slot] = None
        self._done[slot] = True
        self._free.append(slot)
        self._release_slot_blocks(slot)
        self._handoffs_out += 1
        # this engine's part of the request is over — the destination
        # runs its own full enqueue->admit->finish telemetry lifecycle
        self.telemetry.req_finished(st.uri, slot, len(st.tokens))
        try:
            st.req.handoff_cb(state)
        except Exception as e:
            logger.exception("handoff callback failed for %r", st.uri)
            self._req_error(st.uri, st.on_error, e)

    def _record_token(self, slot: int, token: int):
        """Append one generated token; finish + free the slot when done."""
        st = self._slots[slot]
        st.tokens.append(token)
        self.telemetry.req_token(st.uri, slot)
        if st.on_token is not None:
            # host-side emission hook (streaming): two list appends in
            # the serving emitter — no Redis I/O, no device sync here
            try:
                st.on_token(st.uri, token, len(st.tokens) - 1)
            except Exception:
                logger.exception("continuous-batching on_token callback "
                                 "failed for %r", st.uri)
        done = len(st.tokens) >= st.max_new or \
            (self.eos_id is not None and token == self.eos_id)
        if not done:
            if (len(st.tokens) == 1 and st.req is not None
                    and st.req.handoff_cb is not None):
                # prefill role: the first token is this engine's LAST —
                # export the row instead of decoding it here
                self._handoff_slot(slot, st)
            return
        out = np.full(st.max_new,
                      self.eos_id if self.eos_id is not None else 0,
                      np.int32)
        out[:len(st.tokens)] = st.tokens      # frozen tail: eos padding
        self._slots[slot] = None
        self._done[slot] = True     # terminal state until readmission
        self._free.append(slot)
        if self.paged:
            # refcounts drop + table row -> sink BEFORE the next device
            # step, so a recycled block can never see this row's writes
            self._release_slot_blocks(slot)
        self.telemetry.req_finished(st.uri, slot, len(st.tokens))
        if st.on_done is not None:
            try:
                st.on_done(st.uri, out)
            except Exception:
                logger.exception("continuous-batching on_done callback "
                                 "failed for %r", st.uri)

    def step(self) -> int:
        """One engine iteration: admit joiners, then advance every
        resident by up to ``ticks_per_step`` tokens in one device call
        (capped by the largest remaining token budget among residents —
        a nearly-finished slot must not throttle the arena to 1-tick
        device calls; its surplus tokens are dropped host-side in
        ``_record_token``, and EOS mid-chunk freezes on-device like
        generate()'s frozen tail).  Returns the number of active
        slots afterwards (0 = idle; the caller decides how to wait).
        Higher ``ticks_per_step`` trades admission latency granularity
        for fewer host round-trips — the dominant per-token cost on
        tunneled devices."""
        if self.n_active == 0 and not self._waiting:
            # idle poll (the serving pump spins on step()): no work to
            # do or measure, and no tick event to spam the ring with
            return 0
        if self._fault is not None:
            self._fault_tick()
        t0 = time.monotonic()
        n = self._step_impl()
        dur = time.monotonic() - t0
        samples = self._tick_samples(n)
        self.telemetry.tick(t0, dur, samples)
        if self.flight is not None:
            self._flight_record(t0, dur, samples)
        return n

    def _fault_tick(self) -> None:
        """Apply the due engine-side fault actions for this BUSY tick
        (serving/fault.py): a ``freeze_tick`` sleeps here (a wedged
        device — the pump misses heartbeats), an ``alloc_storm`` tick
        records a pool allocation failure (driving the alloc-fail
        streak, anomaly trigger, and router pressure without draining
        the pool), and a ``raise_step`` escapes as
        :class:`~analytics_zoo_tpu.serving.fault.InjectedFault` out of
        ``step()`` — the pump's crash handler path."""
        acts = self._fault.tick_actions(self._replica_id)
        if not acts:
            return
        freeze = acts.get("freeze_s", 0.0)
        if freeze > 0:
            time.sleep(freeze)
        if acts.get("alloc_fail") and self._pool is not None:
            with self._pool_lock:
                self._pool.alloc_failures += 1
        msg = acts.get("raise_step")
        if msg:
            from .fault import InjectedFault
            raise InjectedFault(msg)

    def _tick_samples(self, n_active: int) -> dict:
        """Post-tick residency mix + queue/pool pressure, as plain host
        ints — the per-tick sample row of the ISSUE's event-log spec."""
        decode = prefill = 0
        for s in self._slots:
            if s is not None:
                if s.state == "DECODE":
                    decode += 1
                else:
                    prefill += 1
        samples = {"active": n_active, "decode_rows": decode,
                   "prefill_rows": prefill,
                   "queue_depth": len(self._waiting)}
        if self._pool is not None:
            with self._pool_lock:
                samples["free_blocks"] = self._pool.allocatable()
                if self._dpool is not None:
                    samples["draft_free_blocks"] = \
                        self._dpool.allocatable()
        return samples

    def _flight_record(self, ts: float, dur: float,
                       samples: dict) -> None:
        """Append one tick snapshot to the flight ring: the telemetry
        samples plus resident row sets, tick kind, and the per-tick
        DELTAS of every cumulative counter an incident reader wants on
        a timeline (preemptions, compiles, chunk/budget consumption,
        spec acceptance, pool allocation failures).  All host ints
        already in hand — O(slots) work, no locks beyond one pool
        read, no device interaction."""
        last = self._flight_last

        def delta(key: str, cur: int) -> int:
            d = cur - last[key]
            last[key] = cur
            return d

        rec = dict(samples)
        rec["seq"] = self.flight.next_seq()
        rec["ts"] = round(ts, 6)
        rec["dur_ms"] = round(dur * 1e3, 3)
        rec["kind"] = self._tick_kind
        # which read path / storage mode this tick ran on — a bundle
        # reader's first question when a regression bisects to config
        rec["kernel"] = self.kernel if self.paged else "dense"
        rec["kv_dtype"] = self.kv_dtype
        rec["kv_bytes_per_token"] = self._kv_bytes_per_token
        rec["decode_uris"] = [s.uri for s in self._slots
                              if s is not None and s.state == "DECODE"]
        rec["prefill_uris"] = [s.uri for s in self._slots
                               if s is not None and s.state != "DECODE"]
        rec["preempted"] = delta("preempt", self._preemptions)
        rec["compiles"] = delta(
            "compiles", self.telemetry.c_jit_builds.value
            + self.telemetry.c_retraces.value)
        if self.chunked:
            rec["budget"] = self.tick_token_budget
            rec["budget_used"] = delta("budget_tokens",
                                       self._budget_tokens_used)
            rec["chunks"] = delta("chunks",
                                  self.telemetry.c_chunks.value)
        if self.draft_model is not None:
            rec["spec_proposed"] = delta(
                "spec_proposed", self.telemetry.c_spec_proposed.value)
            rec["spec_accepted"] = delta(
                "spec_accepted", self.telemetry.c_spec_accepted.value)
        if self._pool is not None:
            with self._pool_lock:
                af = self._pool.alloc_failures
                rec["used_blocks"] = self._pool.num_referenced()
                # schema v2: per-tenant pool SIZE per tick, so elastic
                # resizes are visible on the flight timeline
                rec["n_blocks"] = self._pool.n_blocks
                daf = (self._dpool.alloc_failures
                       if self._dpool is not None else 0)
                if self._dpool is not None:
                    rec["draft_used_blocks"] = \
                        self._dpool.num_referenced()
                    rec["draft_n_blocks"] = self._dpool.n_blocks
            rec["pool_resizes"] = delta("pool_resizes",
                                        self._pool_resizes)
            rec["handoffs_out"] = delta("handoffs_out",
                                        self._handoffs_out)
            rec["handoffs_in"] = delta("handoffs_in",
                                       self._handoffs_in)
            # schema v3: host-tier traffic per tick (tiered KV memory)
            rec["kv_spills"] = delta("kv_spills", self._kv_spills)
            rec["kv_readmits"] = delta("kv_readmits",
                                       self._kv_readmits)
            fails = delta("alloc_fail", af) \
                + delta("draft_alloc_fail", daf)
            rec["alloc_failures"] = fails
            # consecutive ticks with at least one failed allocation —
            # the anomaly monitor's "pool is dry and STAYING dry"
            self._alloc_fail_streak = \
                self._alloc_fail_streak + 1 if fails else 0
            rec["alloc_fail_streak"] = self._alloc_fail_streak
        if self._qos is not None:
            rec["qos_depths"] = {f"{c}/{t}" if t else c: n
                                 for (c, t), n in
                                 self._waiting.depths().items()}
        # schema v3 pure additions: brownout/deadline fields appear
        # only once the feature is live, so records from untouched
        # engines stay byte-identical to the pre-brownout build
        if self._brownout_enabled:
            rec["brownout_level"] = self._brownout_level
        if self._deadline_seen:
            rec["deadline_sheds"] = delta("deadline_sheds",
                                          self._deadline_sheds)
        self.flight.record(rec)

    @property
    def alloc_fail_streak(self) -> int:
        """Consecutive ticks whose flight record saw >= 1 block-pool
        allocation failure (0 when not paged or currently healthy)."""
        return self._alloc_fail_streak

    # ---- overload brownout (docs/serving_qos.md) ----------------------

    @property
    def brownout_level(self) -> int:
        return self._brownout_level

    @property
    def deadline_sheds(self) -> int:
        """Requests shed at admission because their deadline already
        passed (separate from the supervisor's in-flight give-ups)."""
        return self._deadline_sheds

    def set_brownout(self, level: int,
                     standard_max_new: int = 0) -> None:
        """Push the broker controller's ladder level into per-tick
        engine state (thread-safe: plain int stores the pump reads at
        tick boundaries).  Level >= 1 defers batch-class admission,
        >= 2 clamps standard-class ``max_new`` to ``standard_max_new``,
        >= 3 drops speculative rounds (the target decodes alone — the
        draft cache goes cold for in-flight rows, which costs
        acceptance after recovery, never correctness: the verify step
        is what picks tokens), >= 4 admits interactive only.  Never
        calling this keeps every gate at 0 and the engine bit-identical
        to the pre-brownout build."""
        self._brownout_enabled = True
        self._brownout_level = max(
            0, min(int(level), scheduler_policy.BROWNOUT_MAX_LEVEL))
        self._brownout_clamp = max(0, int(standard_max_new))

    def _brownout_mn(self, priority, mn: int) -> int:
        """Level-2 token clamp at slot install (one choke point per
        admission family; handoff adoption is exempt — its token count
        is already mid-flight)."""
        if self._brownout_level < 2:
            return mn
        return scheduler_policy.brownout_max_new(
            self._brownout_level, priority, mn, self._brownout_clamp)

    def spec_acceptance(self) -> Optional[dict]:
        """The recorded speculative-acceptance distribution (exact
        counts of accepted draft tokens per row per verify round,
        0..k), or None when the engine has no draft model.  This is
        the calibration section ``dump_bundle`` ships so the
        discrete-event simulator (docs/simulation.md) models
        acceptance from RECORDED data instead of re-deriving it from
        raw ticks."""
        if self.draft_model is None:
            return None
        section = self.telemetry.spec_acceptance()
        section["k"] = self._spec_k
        return section

    def _step_impl(self) -> int:
        self._tick_kind = "decode"
        self._admit()
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return 0
        # brownout level >= 3: speculative rounds are dropped — the
        # dispatch below falls through to the target-only tick paths.
        # Mechanically safe: _ensure_blocks/_grow_chunk_blocks still
        # cover pos + spec_k writes, draft tables grow in lockstep, and
        # _dpos merely goes stale (proposals degrade after recovery;
        # the target verify alone picks tokens, so outputs stay exact).
        spec_on = (self.draft_model is not None
                   and scheduler_policy.brownout_spec_enabled(
                       self._brownout_level))
        if spec_on:
            if self.chunked and any(
                    self._slots[i].state == "PREFILLING"
                    for i in active):
                self._tick_kind = "spec_chunked"
                return self._spec_chunked_tick(active)
            self._tick_kind = "spec"
            if self.paged:
                # grow BOTH tenants' tables to cover the round's k+1
                # verify writes; may preempt
                active = self._ensure_blocks(active)
                if not active:
                    self._admit()   # preemptions freed blocks
                    return self.n_active
            return self._spec_tick(active)
        if self.chunked and any(self._slots[i].state == "PREFILLING"
                                for i in active):
            self._tick_kind = "chunked"
            return self._chunked_tick(active)
        # a chunked engine with NO prefill in flight decodes on the
        # ORIGINAL (multi-tick, scan-amortised) path below — chunking
        # costs nothing in steady state
        if self.paged:
            # grow block tables for the coming chunk; may preempt
            active = self._ensure_blocks(active)
            if not active:
                self._admit()   # preemptions freed blocks: retry now
                return self.n_active
        self._peak_resident = max(self._peak_resident, len(active))
        sampled = any(self._slots[i].temperature > 0.0 for i in active)
        use_topp = any(self._slots[i].top_p > 0.0 for i in active)
        temps = np.zeros(self._S, np.float32)
        seeds = np.zeros(self._S, np.uint32)
        topps = np.zeros(self._S, np.float32)
        for i in active:
            temps[i] = self._slots[i].temperature
            seeds[i] = self._slots[i].rng_seed or 0
            topps[i] = self._slots[i].top_p
        n_eff = max(1, min(
            self.ticks_per_step,
            max(self._slots[i].max_new - len(self._slots[i].tokens)
                for i in active)))
        if self.draft_model is not None:
            # only reachable with spec browned out (level >= 3):
            # single-tick steps keep the write frontier inside the
            # pos + spec_k coverage _ensure_blocks grants this engine
            n_eff = 1
        step = self._get_step(n_eff, sampled, use_topp)
        if self.paged:
            toks, tok, pos, done, self._pk, self._pv = step(
                self._pk, self._pv, jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(self._tables, jnp.int32),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32))
        else:
            toks, tok, pos, done, self._ck, self._cv = step(
                self._ck, self._cv, jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32))
        toks = np.asarray(toks)                     # [n_eff, S]
        # np.asarray of a jax array is a read-only view; _admit writes
        # per-slot entries, so take mutable copies
        self._tok = np.array(tok)
        self._pos = np.array(pos)
        self._done = np.array(done)
        for i in active:
            for j in range(n_eff):
                if self._slots[i] is None:
                    break       # finished mid-chunk; the rest is frozen
                self._record_token(i, int(toks[j, i]))
        self._admit()       # freed slots recycle on the SAME iteration
        return self.n_active

    def _sampling_vectors(self, rows):
        """[S]-wide temperature/seed/top_p staging vectors with entries
        only at ``rows`` (other rows are frozen or empty — their picks
        are discarded, so zeros are fine)."""
        temps = np.zeros(self._S, np.float32)
        seeds = np.zeros(self._S, np.uint32)
        topps = np.zeros(self._S, np.float32)
        for i in rows:
            temps[i] = self._slots[i].temperature
            seeds[i] = self._slots[i].rng_seed or 0
            topps[i] = self._slots[i].top_p
        return temps, seeds, topps

    def _reanchor_prefill(self) -> None:
        """Re-pin every still-PREFILLING row's decode-side state after
        a device step: frozen (done=True), fed pad, positioned at the
        fill frontier — the decode part of the next fused tick then
        writes its one dead K/V entry exactly where the row's own next
        chunk will overwrite it."""
        for i, st in enumerate(self._slots):
            if st is not None and st.state == "PREFILLING":
                self._done[i] = True
                self._pos[i] = st.fill_pos
                if self.draft_model is not None:
                    self._dpos[i] = st.fill_pos
                self._tok[i] = self.pad_id

    def _grant_rank(self, slot: int):
        """Prefill-grant sort key for the chunked ticks.  QoS off: the
        admission sequence number — bit-identical FIFO to the
        pre-front-door engine (the parity guarantee).  QoS on: aged
        priority class first, FIFO within a class, so an interactive
        prompt's chunks land ahead of a batch prompt admitted earlier
        while aging still bounds how long batch can be outranked.
        Delegates to the pure ``serving/policy.py`` key — the
        simulator sorts with the same function on virtual time."""
        st = self._slots[slot]
        req = st.req
        if req is None:
            return scheduler_policy.grant_rank(
                self._qos, None, 0.0, st.admit_seq)
        return scheduler_policy.grant_rank(
            self._qos, req.priority, time.monotonic() - req.enq_t,
            st.admit_seq)

    def _chunked_tick(self, active) -> int:
        """One budget-bounded fused iteration (the tentpole): every
        DECODE row advances one token AND up to ``tick_token_budget -
        n_decode`` tokens of PREFILLING prompts land, in ONE device
        call.  Chunks are granted FIFO by admission order (aged
        priority class first under a QoS policy — ``_grant_rank``); a
        prompt's final chunk also picks its first token inside the same
        program (no extra admission forward, no decode stall)."""
        decode_rows = [i for i in active
                       if self._slots[i].state == "DECODE"]
        prefill_rows = sorted(
            (i for i in active
             if self._slots[i].state == "PREFILLING"),
            key=self._grant_rank)
        # budget billing is pure policy (serving/policy.py): decode
        # rows cost 1 position each, the remainder grants chunks in
        # grant order
        chunks, stalled = scheduler_policy.plan_chunks(
            self.tick_token_budget, 1, len(decode_rows),
            [(i, self._slots[i].plen - self._slots[i].fill_pos)
             for i in prefill_rows],
            self._chunk_buckets[-1])
        if stalled:
            # budget fully consumed by decode rows: prefill waits
            self._prefill_stall_ticks += 1
        if self.paged:
            self._grow_chunk_blocks(decode_rows, chunks)  # may preempt
            decode_rows = [i for i in decode_rows
                           if self._slots[i] is not None]
            chunks = [(i, c) for i, c in chunks
                      if self._slots[i] is not None]
        if not decode_rows and not chunks:
            self._admit()       # preemptions may have freed blocks
            return self.n_active
        self._peak_resident = max(self._peak_resident, len(active))
        self._budget_ticks += 1
        self._budget_tokens_used += len(decode_rows) \
            + sum(c for _, c in chunks)
        if not chunks:
            return self._decode_only_tick(decode_rows)
        with_decode = bool(decode_rows)
        crows = [i for i, _ in chunks]
        sampled = any(self._slots[i].temperature > 0.0
                      for i in decode_rows + crows)
        use_topp = any(self._slots[i].top_p > 0.0
                       for i in decode_rows + crows)
        temps, seeds, topps = self._sampling_vectors(decode_rows)
        # ---- chunk grid: pow2 rows x bucketed width ----
        k = len(chunks)
        kb = 1 << (k - 1).bit_length()
        Cb = _next_bucket(max(c for _, c in chunks),
                          self._chunk_buckets)
        ctoks = np.full((kb, Cb), self.pad_id, np.int32)
        cpos = np.zeros(kb, np.int32)
        clens = np.ones(kb, np.int32)
        cslots = np.full(kb, self._S, np.int32)     # pad rows: drop
        ctemps = np.zeros(kb, np.float32)
        cseeds = np.zeros(kb, np.uint32)
        ctopps = np.zeros(kb, np.float32)
        for j, (i, clen) in enumerate(chunks):
            st = self._slots[i]
            off = st.fill_pos - st.base
            ctoks[j, :clen] = st.full[off:off + clen]
            cpos[j] = st.fill_pos
            clens[j] = clen
            cslots[j] = i
            ctemps[j] = st.temperature
            cseeds[j] = st.rng_seed or 0
            ctopps[j] = st.top_p
        need = int((cpos + clens).max())
        t_fused = time.monotonic()
        if self.paged:
            Mb = self._table_width(-(-need // self._bs))
            ctabs = np.full((kb, Mb), SINK_BLOCK, np.int32)
            for j, (i, _) in enumerate(chunks):
                ctabs[j] = self._tables[i, :Mb]
            fused = self._get_fused(with_decode, sampled, use_topp)
            nxt, pos2, done2, cnxt, self._pk, self._pv = fused(
                self._pk, self._pv,
                jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(self._tables, jnp.int32),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32),
                jnp.asarray(ctoks, jnp.int32),
                jnp.asarray(cpos, jnp.int32),
                jnp.asarray(clens, jnp.int32),
                jnp.asarray(ctabs, jnp.int32),
                jnp.asarray(ctemps, jnp.float32),
                jnp.asarray(cseeds, jnp.uint32),
                jnp.asarray(ctopps, jnp.float32))
        else:
            read_len = next(b for b in self._read_buckets
                            if b >= need)
            fused = self._get_fused(with_decode, sampled, use_topp,
                                    read_len)
            nxt, pos2, done2, cnxt, self._ck, self._cv = fused(
                self._ck, self._cv,
                jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32),
                jnp.asarray(ctoks, jnp.int32),
                jnp.asarray(cpos, jnp.int32),
                jnp.asarray(clens, jnp.int32),
                jnp.asarray(cslots, jnp.int32),
                jnp.asarray(ctemps, jnp.float32),
                jnp.asarray(cseeds, jnp.uint32),
                jnp.asarray(ctopps, jnp.float32))
        # one host sync for decode picks + chunk first-token picks
        nxt, pos2, done2, cnxt = jax.device_get(
            (nxt, pos2, done2, cnxt))
        # all of a tick's chunks land in the one fused call above, so
        # they share its span (per-chunk device timing doesn't exist)
        dur_fused = time.monotonic() - t_fused
        for i, clen in chunks:
            self.telemetry.events.span(
                "prefill_chunk", t_fused, dur_fused, i,
                {"uri": self._slots[i].uri, "tokens": int(clen),
                 "fill_pos": int(self._slots[i].fill_pos)})
        self.telemetry.c_chunks.inc(len(chunks))
        if with_decode:
            self._tok = np.array(nxt)
            self._pos = np.array(pos2)
            self._done = np.array(done2)
        completed: List[Tuple[int, int]] = []
        for j, (i, clen) in enumerate(chunks):
            st = self._slots[i]
            st.fill_pos += clen
            if self.paged:
                self._publish_chunk_blocks(i, st)
            if st.fill_pos >= st.plen:
                completed.append((i, int(cnxt[j])))
        for i, first in completed:
            st = self._slots[i]
            st.state = "DECODE"
            st.full = st.hashes = None
            self._tok[i] = first
            self._pos[i] = st.plen
            self._done[i] = False
            self._record_token(i, first)    # the request's FIRST token
        self._reanchor_prefill()
        for i in decode_rows:
            if self._slots[i] is not None:
                self._record_token(i, int(nxt[i]))
        self._admit()       # freed slots recycle on the SAME iteration
        return self.n_active

    def _decode_only_tick(self, decode_rows) -> int:
        """Budget tick with no chunk grants (budget exhausted by decode
        rows, or every prefill row preempted): one unfused 1-tick step
        — the SAME compiled program as the non-chunked path, so no
        extra compile — then re-anchor the frozen PREFILLING rows."""
        sampled = any(self._slots[i].temperature > 0.0
                      for i in decode_rows)
        use_topp = any(self._slots[i].top_p > 0.0 for i in decode_rows)
        temps, seeds, topps = self._sampling_vectors(decode_rows)
        step = self._get_step(1, sampled, use_topp)
        if self.paged:
            toks, tok, pos, done, self._pk, self._pv = step(
                self._pk, self._pv, jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(self._tables, jnp.int32),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32))
        else:
            toks, tok, pos, done, self._ck, self._cv = step(
                self._ck, self._cv, jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32))
        toks = np.asarray(toks)
        self._tok = np.array(tok)
        self._pos = np.array(pos)
        self._done = np.array(done)
        self._reanchor_prefill()
        for i in decode_rows:
            if self._slots[i] is not None:
                self._record_token(i, int(toks[0, i]))
        self._admit()
        return self.n_active

    def precompile_chunked(self, sampled: bool = False,
                           use_topp: bool = False,
                           max_chunk_rows: Optional[int] = None) -> int:
        """Eagerly compile the chunked scheduler's whole fused-program
        shape grid, so steady-state serving compiles NOTHING regardless
        of arrival timing — a cold-start aid for latency-sensitive
        deployments (and for benchmarks, where a first-encounter
        compile inside a percentile would be measured as a stall).

        The grid is exactly the bounded space ``_chunked_tick`` can
        reach: chunk-row counts (pow2 up to ``max_chunk_rows``, default
        ``max_slots``), chunk widths (the prompt buckets that fit the
        budget), with/without live decode rows, and per shape the arena
        read window (pow2 buckets, capped at the largest prompt bucket)
        or the paged narrow-table width (pow2, same cap).  Unreachable
        combinations are pruned: a chunk width bucket ``Cb`` implies
        some chunk longer than the previous bucket, so windows that
        cannot contain such a chunk are skipped.  Returns the number of
        (program, shape) variants visited.  Dummy buffers are used
        throughout — engine state is untouched."""
        if not self.chunked:
            raise ValueError("precompile_chunked requires chunked=True")
        S = self._S
        kmax = min(max_chunk_rows or S, S)
        kbs, kb = [], 1
        while kb < kmax:
            kbs.append(kb)
            kb *= 2
        kbs.append(kb)
        max_prompt = self.prompt_buckets[-1]
        tok = jnp.zeros(S, jnp.int32)
        pos = jnp.zeros(S, jnp.int32)
        done = jnp.ones(S, jnp.bool_)
        temps = jnp.zeros(S, jnp.float32)
        seeds = jnp.zeros(S, jnp.uint32)
        topps = jnp.zeros(S, jnp.float32)
        count = 0
        for ci, Cb in enumerate(self._chunk_buckets):
            prev = self._chunk_buckets[ci - 1] if ci else 0
            # the need (max fill frontier) that selects this Cb spans
            # (prev, max_prompt]: every window bucket covering part of
            # that range is reachable, nothing else is
            if self.paged:
                lo = self._table_width(-(-(prev + 1) // self._bs))
                hi = self._table_width(-(-max_prompt // self._bs))
                widths = []
                v = lo
                while v <= hi:
                    widths.append(v)
                    if v >= self._M:
                        break
                    v *= 2
            else:
                # window b serves need in (previous bucket, b]; keep it
                # iff that range overlaps the reachable (prev,
                # max_prompt]
                widths = [b for bi, b in enumerate(self._read_buckets)
                          if b > prev
                          and (self._read_buckets[bi - 1] if bi else 0)
                          < max_prompt]
            for kb in kbs:
                ctoks = jnp.full((kb, Cb), self.pad_id, jnp.int32)
                cpos = jnp.zeros(kb, jnp.int32)
                clens = jnp.ones(kb, jnp.int32)
                cslots = jnp.full(kb, S, jnp.int32)
                czeros = (jnp.zeros(kb, jnp.float32),
                          jnp.zeros(kb, jnp.uint32),
                          jnp.zeros(kb, jnp.float32))
                for width in widths:
                    if self.draft_model is not None:
                        # spec engines never run the fused program —
                        # their chunk half is the two-tenant spec chunk
                        # program (one variant per grid shape, no
                        # with_decode/sampled axes: greedy-only, and
                        # the decode half is the separate spec round)
                        if self.paged:
                            self._spec_chunk_paged(
                                _zeros_like(self._pk),
                                _zeros_like(self._pv),
                                _zeros_like(self._dpk),
                                _zeros_like(self._dpv),
                                ctoks, cpos, clens,
                                jnp.full((kb, width), SINK_BLOCK,
                                         jnp.int32),
                                jnp.full((kb, width), SINK_BLOCK,
                                         jnp.int32))
                        else:
                            self._spec_chunk(
                                jnp.zeros_like(self._ck),
                                jnp.zeros_like(self._cv),
                                jnp.zeros_like(self._dck),
                                jnp.zeros_like(self._dcv),
                                ctoks, cpos, clens, cslots,
                                read_len=width)
                        count += 1
                        continue
                    for wd in (False, True):
                        if self.paged:
                            fn = self._get_fused(wd, sampled, use_topp)
                            fn(_zeros_like(self._pk),
                               _zeros_like(self._pv),
                               tok, pos, done,
                               jnp.full((S, self._M), SINK_BLOCK,
                                        jnp.int32),
                               temps, seeds, topps, ctoks, cpos,
                               clens,
                               jnp.full((kb, width), SINK_BLOCK,
                                        jnp.int32),
                               *czeros)
                        else:
                            fn = self._get_fused(wd, sampled,
                                                 use_topp, width)
                            fn(jnp.zeros_like(self._ck),
                               jnp.zeros_like(self._cv),
                               tok, pos, done, temps, seeds, topps,
                               ctoks, cpos, clens, cslots, *czeros)
                        count += 1
        if self.draft_model is not None:
            # the decode half of a spec chunk tick: one shape-stable
            # spec-round program
            if self.paged:
                self._spec_step_paged(
                    _zeros_like(self._pk), _zeros_like(self._pv),
                    _zeros_like(self._dpk),
                    _zeros_like(self._dpv),
                    tok, pos, pos, done,
                    jnp.full((S, self._M), SINK_BLOCK, jnp.int32),
                    jnp.full((S, self._M), SINK_BLOCK, jnp.int32))
            else:
                self._spec_step(
                    jnp.zeros_like(self._ck), jnp.zeros_like(self._cv),
                    jnp.zeros_like(self._dck),
                    jnp.zeros_like(self._dcv),
                    tok, pos, pos, done)
            count += 1
        return count

    def _spec_tick(self, active) -> int:
        """One speculative round for the whole batch: every resident
        advances by its own accepted count (1..k+1 tokens) in one device
        call.  Paged dispatch already grew both tenants' block tables
        (``_ensure_blocks``)."""
        self._peak_resident = max(self._peak_resident, len(active))
        self._spec_round(active)
        self._admit()       # freed slots recycle on the SAME iteration
        return self.n_active

    def _spec_round(self, rows) -> None:
        """Run the spec-round program (arena or paged) and record each
        row's emitted tokens.  Emission recording mirrors the plain
        path: per slot, in order, stopping when the slot finishes
        (budget surplus dropped host-side).  PREFILLING rows ride along
        frozen (done=True -> n_emit=0); their k+1 garbage writes land at
        or past the fill frontier, where their own chunks (and, after
        the flip, their first verify) overwrite them before anything
        attends that far."""
        if self.paged:
            (toks, n_emit, tok, pos, dpos, done, self._pk, self._pv,
             self._dpk, self._dpv) = self._spec_step_paged(
                self._pk, self._pv, self._dpk, self._dpv,
                jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._dpos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(self._tables, jnp.int32),
                jnp.asarray(self._dtables, jnp.int32))
        else:
            (toks, n_emit, tok, pos, dpos, done, self._ck, self._cv,
             self._dck, self._dcv) = self._spec_step(
                self._ck, self._cv, self._dck, self._dcv,
                jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._dpos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_))
        toks = np.asarray(toks)                 # [k+1, S]
        n_emit = np.asarray(n_emit)
        self._tok = np.array(tok)
        self._pos = np.array(pos)
        self._dpos = np.array(dpos)
        self._done = np.array(done)
        self._spec_rounds = getattr(self, "_spec_rounds", 0) + 1
        self._spec_emitted = getattr(self, "_spec_emitted", 0) + int(
            n_emit[rows].sum())
        # acceptance accounting: every live row consumed k proposals;
        # n_emit-1 of them matched (eos clipping only shortens usage)
        emitting = [i for i in rows if int(n_emit[i]) > 0]
        lens = [int(n_emit[i]) - 1 for i in emitting]
        self.telemetry.spec_round(self._spec_k * len(emitting),
                                  sum(lens), lens)
        for i in rows:
            for j in range(int(n_emit[i])):
                if self._slots[i] is None:
                    break       # finished mid-round; the rest is frozen
                self._record_token(i, int(toks[j, i]))

    def _spec_chunked_tick(self, active) -> int:
        """Chunked tick with a draft model: ONE token budget covers
        both work-item kinds — each DECODE row costs ``k+1`` verify
        positions, the remainder grants prefill chunks FIFO by
        admission order, exactly like ``_chunked_tick``.  Two device
        calls (spec round + spec chunk program, see
        ``_init_speculative``); with no PREFILLING rows in flight the
        dispatcher never enters here, so steady-state decoding pays
        the plain one-call spec tick."""
        decode_rows = [i for i in active
                       if self._slots[i].state == "DECODE"]
        prefill_rows = sorted(
            (i for i in active
             if self._slots[i].state == "PREFILLING"),
            key=self._grant_rank)
        per_row = self._spec_k + 1
        # same pure billing as _chunked_tick, with every decode row
        # costing its k+1 verify positions
        chunks, stalled = scheduler_policy.plan_chunks(
            self.tick_token_budget, per_row, len(decode_rows),
            [(i, self._slots[i].plen - self._slots[i].fill_pos)
             for i in prefill_rows],
            self._chunk_buckets[-1])
        if stalled:
            # budget fully consumed by verify rows: prefill waits
            self._prefill_stall_ticks += 1
        if self.paged:
            self._grow_chunk_blocks(decode_rows, chunks)  # may preempt
            decode_rows = [i for i in decode_rows
                           if self._slots[i] is not None]
            chunks = [(i, c) for i, c in chunks
                      if self._slots[i] is not None]
        if not decode_rows and not chunks:
            self._admit()       # preemptions may have freed blocks
            return self.n_active
        self._peak_resident = max(self._peak_resident, len(active))
        self._budget_ticks += 1
        self._budget_tokens_used += per_row * len(decode_rows) \
            + sum(c for _, c in chunks)
        if decode_rows:
            self._spec_round(decode_rows)
        # a round can finish rows but never kills chunk rows (they are
        # PREFILLING — frozen in the round); re-filter for safety
        chunks = [(i, c) for i, c in chunks
                  if self._slots[i] is not None]
        if chunks:
            self._spec_chunks(chunks)
        self._reanchor_prefill()
        self._admit()       # freed slots recycle on the SAME iteration
        return self.n_active

    def _spec_chunks(self, chunks) -> None:
        """Land this tick's prefill chunks in BOTH models' caches (one
        device call) and flip prompts whose last chunk landed into
        DECODE with their first token — the spec twin of
        ``_chunked_tick``'s chunk half, greedy-only."""
        k = len(chunks)
        kb = 1 << (k - 1).bit_length()
        Cb = _next_bucket(max(c for _, c in chunks),
                          self._chunk_buckets)
        ctoks = np.full((kb, Cb), self.pad_id, np.int32)
        cpos = np.zeros(kb, np.int32)
        clens = np.ones(kb, np.int32)
        cslots = np.full(kb, self._S, np.int32)     # pad rows: drop
        for j, (i, clen) in enumerate(chunks):
            st = self._slots[i]
            off = st.fill_pos - st.base
            ctoks[j, :clen] = st.full[off:off + clen]
            cpos[j] = st.fill_pos
            clens[j] = clen
            cslots[j] = i
        need = int((cpos + clens).max())
        t_chunk = time.monotonic()
        if self.paged:
            Mb = self._table_width(-(-need // self._bs))
            ctabs = np.full((kb, Mb), SINK_BLOCK, np.int32)
            dctabs = np.full((kb, Mb), SINK_BLOCK, np.int32)
            for j, (i, _) in enumerate(chunks):
                ctabs[j] = self._tables[i, :Mb]
                dctabs[j] = self._dtables[i, :Mb]
            (cnxt, self._pk, self._pv, self._dpk,
             self._dpv) = self._spec_chunk_paged(
                self._pk, self._pv, self._dpk, self._dpv,
                jnp.asarray(ctoks, jnp.int32),
                jnp.asarray(cpos, jnp.int32),
                jnp.asarray(clens, jnp.int32),
                jnp.asarray(ctabs, jnp.int32),
                jnp.asarray(dctabs, jnp.int32))
        else:
            read_len = next(b for b in self._read_buckets
                            if b >= need)
            (cnxt, self._ck, self._cv, self._dck,
             self._dcv) = self._spec_chunk(
                self._ck, self._cv, self._dck, self._dcv,
                jnp.asarray(ctoks, jnp.int32),
                jnp.asarray(cpos, jnp.int32),
                jnp.asarray(clens, jnp.int32),
                jnp.asarray(cslots, jnp.int32),
                read_len=read_len)
        cnxt = np.asarray(cnxt)     # one host sync for first-token picks
        dur_chunk = time.monotonic() - t_chunk
        for i, clen in chunks:
            self.telemetry.events.span(
                "prefill_chunk", t_chunk, dur_chunk, i,
                {"uri": self._slots[i].uri, "tokens": int(clen),
                 "fill_pos": int(self._slots[i].fill_pos)})
        self.telemetry.c_chunks.inc(len(chunks))
        completed: List[Tuple[int, int]] = []
        for j, (i, clen) in enumerate(chunks):
            st = self._slots[i]
            st.fill_pos += clen
            if self.paged:
                self._publish_chunk_blocks(i, st)
            if st.fill_pos >= st.plen:
                completed.append((i, int(cnxt[j])))
        for i, first in completed:
            st = self._slots[i]
            st.state = "DECODE"
            st.full = st.hashes = None
            self._tok[i] = first
            self._pos[i] = st.plen
            self._dpos[i] = st.plen
            self._done[i] = False
            self._record_token(i, first)    # the request's FIRST token

    def drain(self, max_ticks: int = 100_000) -> None:
        """Run ticks until every submitted request has finished (tests /
        batch use)."""
        for _ in range(max_ticks):
            if self.step() == 0 and self.n_waiting == 0:
                return
        raise RuntimeError("drain did not converge")

"""Continuous batching for generative serving.

SURVEY.md §2.6's TPU mapping names "continuous batching" as the serving
bar; the reference's Flink engine (upstream ``serving/engine/``) stops at
request-level micro-batching — a batch of prompts runs its whole
generation before the next batch starts, so a 2-token request convoys
behind a 32-token neighbour.  This module is the beyond-parity engine:

- A fixed-size **slot arena**: KV caches ``[n_layers, S, L, H, D]`` for
  ``S`` co-resident requests, allocated once.  Static shapes — the decode
  step compiles exactly once, no matter how requests come and go.
- **In-flight joining**: a new request PREFILLS with one MXU-friendly
  forward (``TransformerLM.prefill``) and its K/V are spliced into a free
  slot while other slots are mid-generation; the next engine tick decodes
  all residents together at their own positions (``decode_step`` with a
  per-row position vector).
- **Slot recycling**: a request that hits EOS or its token budget frees
  its slot immediately; the next waiting request takes it on the same
  tick.  Stale cache entries need no scrubbing — a resident only attends
  positions ``<= pos`` it has itself written (prompt prefill + its own
  decode steps), so a recycled slot never reads its predecessor's K/V.

Per-request results match ``models.lm.generate`` run solo: same frozen
tail EOS semantics, same ``[max_new_tokens]`` output shape (eos-padded),
greedy or per-request-temperature sampling with ``generate``-compatible
position-folded rngs.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import (Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Tuple)

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.learn.inference_model import (
    _next_bucket, filter_prompt_buckets)
from analytics_zoo_tpu.models.lm import (TransformerLM,
                                         top_p_filter)
from analytics_zoo_tpu.serving.paged_cache import (BlockPool,
                                                   SINK_BLOCK)
from analytics_zoo_tpu.serving.telemetry import Telemetry

logger = logging.getLogger("analytics_zoo_tpu")


class _Req(NamedTuple):
    """One waiting-queue entry — named fields, because positional
    indexing across three consumers silently breaks when a field is
    added."""

    uri: str
    prompt: np.ndarray
    on_done: Optional[Callable]
    on_error: Optional[Callable]
    temperature: float
    rng_seed: Optional[int]
    max_new: int
    prefix: Optional[int]
    top_p: float


@dataclass
class _Slot:
    uri: str
    plen: int
    max_new: int
    tokens: List[int] = field(default_factory=list)
    on_done: Optional[Callable] = None
    on_error: Optional[Callable] = None
    temperature: float = 0.0
    rng_seed: Optional[int] = None
    top_p: float = 0.0
    # paged mode: the original request (requeued verbatim on
    # preemption) and an admission sequence number (the preemption
    # victim is always the LATEST admission — earliest admissions keep
    # making forward progress, so preemption can never livelock)
    req: Optional[_Req] = None
    admit_seq: int = 0
    # chunked-prefill state machine: a slot admits as "PREFILLING" and
    # feeds its prompt to the cache chunk by chunk (fill_pos = next
    # cache position to write, starting past any spliced/shared
    # prefix); the tick its last chunk lands it emits its first token
    # and flips to "DECODE".  ``full`` holds the not-yet-fed tokens
    # (positions base..plen-1); ``hashes``/``n_pub`` track which full
    # prompt blocks the paged path has already published for sharing.
    state: str = "DECODE"
    fill_pos: int = 0
    base: int = 0
    full: Optional[np.ndarray] = None
    hashes: Optional[list] = None
    n_pub: int = 0


class ContinuousEngine:
    """Slot-arena generation engine over one ``TransformerLM``.

    Host-side control loop + three jitted device programs: the step
    program (advance every slot ``ticks_per_step`` tokens at per-slot
    positions in one lax.scan call; compiled per (n_ticks, sampled) via
    ``_get_step``), the bucketed batched prefill (one forward for ALL
    joiners sharing a prompt bucket), and the per-slot K/V splice.  The
    arena buffers are donated through step/insert so XLA updates them in
    place instead of copying ``S*L`` of KV per token.

    **KV memory.** The cache stores only ``model.kv_heads`` heads per
    position: a grouped-query model (``num_kv_heads < num_heads``)
    shrinks every resident's K/V ``num_heads/num_kv_heads``-fold, which
    is proportionally more co-resident requests for the same HBM
    (``capacity_report()`` quantifies it); ``cache_dtype`` narrows it
    further (e.g. a bfloat16 cache under an f32 model halves it again —
    attention upcasts via the einsums' f32 accumulation).

    **``paged=True``** replaces the per-slot arena with a block-pool
    cache (serving/paged_cache.py): K/V live in one flat pool of
    ``block_size``-token blocks, each resident holds only the blocks it
    has actually filled (via a per-slot block table), full prompt
    blocks are hash-indexed so requests sharing a prompt prefix attach
    to the same physical blocks copy-free (subsuming the manual
    ``register_prefix`` splice), and when the pool runs dry the engine
    PREEMPTS the latest admission back to the queue front instead of
    OOMing — its partial tokens are discarded and regenerate
    deterministically on readmission (greedy argmax, and sampled rows
    fold the rng by absolute position).  ``cache_metrics()`` reports
    occupancy/hit-rate/preemptions.  Paged limitations (ROADMAP open
    items): no draft-model speculation, no mesh; paged
    ``register_prefix`` must run before the pump starts (it updates
    the donated pool buffers — racing a live ``step()`` is undefined).

    Not thread-safe by itself: ``submit`` may be called from any thread,
    but ``step``/``drain`` must run on ONE pump thread (the serving loop).
    """

    def __init__(self, model: TransformerLM, variables, *,
                 max_new_tokens: int, max_slots: int = 8,
                 prompt_buckets: Sequence[int] = (16, 32, 64, 128),
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 ticks_per_step: int = 1,
                 cache_dtype=None,
                 mesh=None, partition_rules=None,
                 draft_model: Optional[TransformerLM] = None,
                 draft_variables=None, speculation_k: int = 4,
                 paged: bool = False, block_size: int = 16,
                 n_blocks: Optional[int] = None,
                 hbm_fraction: Optional[float] = None,
                 enable_prefix_cache: bool = True,
                 chunked: bool = False,
                 tick_token_budget: Optional[int] = None,
                 record_timings: bool = False,
                 telemetry: Optional[Telemetry] = None):
        """``mesh`` (with a ``tp`` axis) serves a model LARGER than one
        chip's HBM: weights shard per ``partition_rules`` (default
        ``LM_PARTITION_RULES`` — Megatron layout), the KV arena shards
        over tp on the kv-heads axis (each chip holds 1/tp of every
        slot's cache), and slot bookkeeping (tok/pos/done) replicates.
        XLA propagates the shardings through the jitted step/prefill/
        splice programs — decode runs as one SPMD program with the tp
        collectives the weight layout implies."""
        if model.pp_stages > 0:
            raise ValueError("continuous batching serves pp_stages=0 "
                             "models (models.lm.unstack_pp_params)")
        self.model = model
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        # ---- telemetry (always-on; serving/telemetry.py) ---------------
        # one facade per engine unless the serving layer passes its own
        # (to merge registries under one scrape).  Every hook below is
        # host-side floats/ints only: nothing telemetry does enters a
        # jitted program, so it can neither sync the device nor retrace.
        self.telemetry = telemetry if telemetry is not None \
            else Telemetry()
        # ---- speculative mode (draft arena) ----------------------------
        # the slot arena is ALREADY per-row-positioned, which is exactly
        # what per-slot acceptance rates need: each verify round advances
        # every slot by its own accepted count.  Greedy-only (a sampled
        # slot's speculative contract needs rejection sampling — not
        # implemented; submit() rejects temperature > 0 in this mode).
        self.draft_model = draft_model
        self._draft_variables = draft_variables
        self._spec_k = int(speculation_k) if draft_model is not None else 0
        if draft_model is not None:
            if draft_variables is None:
                raise ValueError("draft_model needs draft_variables")
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.vocab_size} != target "
                    f"vocab {model.vocab_size}")
            if draft_model.pp_stages > 0:
                raise ValueError("draft must be pp_stages=0")
            if mesh is not None:
                raise NotImplementedError(
                    "speculative continuous batching is single-chip for "
                    "now; drop either mesh or draft_model")
            if self._spec_k < 1:
                raise ValueError("speculation_k must be >= 1")
        # speculative verify writes k+1 entries past the pointer and
        # looks up positions there, so the bucket limit tightens by k+1
        # and must fit BOTH models' position tables
        eff_max_pos = model.max_position if draft_model is None else \
            min(model.max_position, draft_model.max_position)
        self.prompt_buckets = filter_prompt_buckets(
            prompt_buckets, eff_max_pos,
            max_new_tokens + (self._spec_k + 1 if draft_model else 0))
        self.max_prompt_width = self.prompt_buckets[-1]
        S = int(max_slots)
        L = self.max_prompt_width + self.max_new_tokens \
            + (self._spec_k + 1 if draft_model is not None else 0)
        self._S, self._L = S, L
        # GQA models store only kv_heads in the cache: the arena shrinks
        # num_heads/kv_heads-fold, which is more co-resident requests
        # for the same HBM.  cache_dtype narrows it further (e.g.
        # bfloat16 arena under an f32 model: 2x more slots; attention
        # reads upcast via the einsums' f32 accumulation).
        H = getattr(model, "kv_heads", model.num_heads)
        D = model.hidden_size // model.num_heads
        # validate cache_dtype EAGERLY with a serving-level message — a
        # bad value must not surface as a bare jnp.dtype TypeError deep
        # inside arena allocation
        if cache_dtype is None:
            cdtype = jnp.dtype(model.dtype)
        else:
            try:
                cdtype = jnp.dtype(cache_dtype)
            except TypeError:
                raise ValueError(
                    f"cache_dtype {cache_dtype!r} is not a dtype the KV "
                    f"cache can be allocated with; pass a floating "
                    f"dtype like 'bfloat16' or 'float32' (or None to "
                    f"follow model.dtype "
                    f"{jnp.dtype(model.dtype).name})") from None
            if not jnp.issubdtype(cdtype, jnp.floating):
                raise ValueError(
                    f"cache_dtype {cache_dtype!r} resolves to "
                    f"{cdtype.name}, which is not a floating dtype — "
                    f"K/V projections cannot be stored in it without "
                    f"corrupting attention")
        self.mesh = mesh
        # ---- paged mode (block-pool cache, serving/paged_cache.py) -----
        self.paged = bool(paged)
        self._preemptions = 0
        self._peak_resident = 0
        self._admit_seq = 0
        self._pool: Optional[BlockPool] = None
        self._pk = self._pv = None
        self._paged_prefixes: Dict[int, tuple] = {}
        if self.paged:
            if draft_model is not None:
                raise NotImplementedError(
                    "paged + speculative decoding is a ROADMAP open "
                    "item; build the paged engine without a draft")
            if mesh is not None:
                raise NotImplementedError(
                    "paged mode is single-chip for now (multi-replica "
                    "routing is a ROADMAP open item); drop mesh")
            bs = int(block_size)
            if bs < 1:
                raise ValueError(f"block_size must be >= 1, got {bs}")
            M = -(-L // bs)         # logical blocks per row, ceil(L/bs)
            if n_blocks is None:
                per_block = 2 * model.num_layers * bs * H * D \
                    * cdtype.itemsize
                lim = 0
                if hbm_fraction is not None:
                    try:
                        stats = jax.devices()[0].memory_stats() or {}
                        lim = int(stats.get("bytes_limit", 0))
                    except Exception:
                        lim = 0
                if lim:
                    n_blocks = max(M + 1,
                                   int(lim * float(hbm_fraction))
                                   // per_block)
                else:
                    if hbm_fraction is not None:
                        logger.warning(
                            "hbm_fraction=%s ignored: device exposes no "
                            "memory_stats (CPU backend?); sizing the "
                            "pool arena-equivalent (S*M+1 blocks)",
                            hbm_fraction)
                    # arena-equivalent capacity: every slot can run to
                    # full length — paged still wins whenever real
                    # traffic doesn't (shorter prompts, prefix sharing)
                    n_blocks = S * M + 1
            n_blocks = int(n_blocks)
            if n_blocks < M + 1:
                raise ValueError(
                    f"n_blocks={n_blocks} cannot hold one full-length "
                    f"sequence: need >= {M + 1} ({M} logical blocks of "
                    f"{bs} positions + the sink block 0)")
            self._bs, self._M = bs, M
            self._pool = BlockPool(n_blocks, bs, enable_prefix_cache,
                                   event_cb=self.telemetry.pool_event)
            # pool-mutation guard: admission/growth run on the pump
            # thread, but unregister_prefix releases from client threads
            self._pool_lock = threading.Lock()
            self._pk = jnp.zeros((model.num_layers, n_blocks, bs, H, D),
                                 cdtype)
            self._pv = jnp.zeros_like(self._pk)
            # per-slot block tables; SINK everywhere a row holds no
            # block, so stray writes land in storage nothing attends
            self._tables = np.full((S, M), SINK_BLOCK, np.int32)
            self._row_blocks: List[List[int]] = [[] for _ in range(S)]
        # ---- chunked prefill (token-budget tick scheduler) -------------
        # chunked=True replaces monolithic admission prefill with
        # incremental chunks packed alongside decodes under a per-tick
        # token budget — long prompts stop stalling active decoders.
        self.chunked = bool(chunked)
        self.record_timings = bool(record_timings)
        self._prefill_stall_ticks = 0
        self._prefill_preemptions = 0
        self._budget_tokens_used = 0
        self._budget_ticks = 0
        self.tick_token_budget: Optional[int] = None
        if self.chunked:
            if draft_model is not None:
                raise NotImplementedError(
                    "chunked prefill + speculative decoding is not "
                    "implemented; drop either chunked or draft_model")
            if mesh is not None:
                raise NotImplementedError(
                    "chunked prefill is single-chip for now; drop mesh")
            if tick_token_budget is None:
                # default: roughly one decode-bucket of MXU work — all S
                # decode rows plus at least one smallest-bucket chunk
                # (and at least one paged block) fit in a tick
                budget = max(self.prompt_buckets[0] + S, 2 * S)
                if self.paged:
                    budget = max(budget, self._bs)
            else:
                budget = int(tick_token_budget)
                if budget < self.prompt_buckets[0]:
                    raise ValueError(
                        f"tick_token_budget={budget} is below the "
                        f"smallest chunk bucket "
                        f"{self.prompt_buckets[0]}: no prefill chunk "
                        f"could ever be scheduled and admission would "
                        f"livelock; raise the budget or add a smaller "
                        f"prompt bucket")
                if self.paged and budget < self._bs:
                    raise ValueError(
                        f"tick_token_budget={budget} is below "
                        f"block_size={self._bs}: a chunk could never "
                        f"cover one paged block per tick; raise the "
                        f"budget or shrink block_size")
            self.tick_token_budget = budget
            # chunk widths reuse the prompt buckets (bounded compile
            # count), trimmed to what the budget can ever schedule
            self._chunk_buckets = tuple(
                b for b in self.prompt_buckets if b <= budget)
            # arena chunk attention reads a [kb, read_len] cache window
            # that tracks the fill frontier — pow2 buckets keep the
            # compile count O(log L) instead of one per frontier
            rb: List[int] = []
            v = 8
            while v < L:
                rb.append(v)
                v *= 2
            rb.append(L)
            self._read_buckets = tuple(rb)
        tp = int(mesh.shape.get("tp", 1)) if mesh is not None else 1
        if self.paged:
            self._ck = self._cv = None  # pool replaces the slot arena
        elif tp > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from analytics_zoo_tpu.models.lm import LM_PARTITION_RULES
            from analytics_zoo_tpu.parallel.partition import state_sharding

            if H % tp and partition_rules is None:
                raise ValueError(
                    f"kv_heads={H} must divide by tp={tp} to shard the "
                    f"KV arena under the default LM_PARTITION_RULES; "
                    f"narrow-KV (MQA/GQA) models pass partition_rules "
                    f"with the key/value kernels replicated (P()) — the "
                    f"arena then replicates too")
            rules = partition_rules or LM_PARTITION_RULES
            shardings = state_sharding(mesh, variables, rules)
            variables = jax.device_put(variables, shardings)
            # the arena must MATCH what the kv projections emit under
            # the chosen rules — custom rules that replicate the k/v
            # kernels (even on a divisible-heads model) need a
            # replicated arena, or every decode step pays resharding
            # collectives the layout never required
            kv_tp = H % tp == 0 and self._kv_kernels_tp_sharded(
                shardings)
            kv_sh = NamedSharding(
                mesh, P(None, None, None, "tp", None) if kv_tp
                else P())
            # allocate sharded-from-BIRTH: materialising the full arena
            # on one chip first would OOM exactly the beyond-one-chip
            # models this path exists for
            self._ck = jnp.zeros((model.num_layers, S, L, H, D), cdtype,
                                 device=kv_sh)
            self._cv = jnp.zeros((model.num_layers, S, L, H, D), cdtype,
                                 device=kv_sh)
        else:
            self._ck = jnp.zeros((model.num_layers, S, L, H, D), cdtype)
            self._cv = jnp.zeros_like(self._ck)
        self._variables = variables
        self.ticks_per_step = max(1, int(ticks_per_step))
        # host-side per-slot state (device copies travel as step args)
        self._tok = np.zeros(S, np.int32)
        self._pos = np.zeros(S, np.int32)
        self._done = np.zeros(S, bool)
        self._slots: List[Optional[_Slot]] = [None] * S
        self._free = collections.deque(range(S))
        self._lock = threading.Lock()
        self._waiting: collections.deque = collections.deque()
        self._step_count = 0

        Lmax = L

        def pick_next(logits, pos, done, temps, seeds, topps,
                      use_sample, use_topp):
            """One token per row from per-row logits — ONE definition so
            the arena and paged step programs can never drift (their
            greedy-parity guarantee depends on it).  Sampling folds the
            rng by absolute position, so a preempted-and-readmitted row
            regenerates identical tokens."""
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            if use_sample:              # static: greedy-only compile

                def sample_row(seed, t, tp, lg, p):
                    key = jax.random.fold_in(jax.random.key(seed), p)
                    scaled = lg.astype(jnp.float32) / jnp.maximum(
                        t, 1e-6)
                    if use_topp:        # static: no sort when unused
                        scaled = top_p_filter(scaled, tp)
                    return jax.random.categorical(key, scaled).astype(
                        jnp.int32)

                sampled = jax.vmap(sample_row)(seeds, temps, topps,
                                               logits, pos)
                nxt = jnp.where(temps > 0.0, sampled, nxt)
            if eos_id is not None:
                nxt = jnp.where(done, jnp.int32(eos_id), nxt)
                done = done | (nxt == eos_id)
            return nxt, done

        def step_fn(ck, cv, tok, pos, done, temps, seeds, topps,
                    n_ticks, use_sample, use_topp):
            """Advance every slot ``n_ticks`` tokens in ONE device call
            (a lax.scan) — each extra tick saves a host round-trip,
            which dominates per-token cost on tunneled devices.  A slot
            that hits EOS mid-chunk freezes exactly like generate()'s
            frozen tail: it keeps stepping, fed eos.  Returns tokens
            [n_ticks, S] in emission order."""

            def one(carry, _):
                tok, pos, done, ck, cv = carry
                logits, ck, cv = model.apply(
                    variables, tok, ck, cv, pos,
                    method=TransformerLM.decode_step)
                nxt, done = pick_next(logits, pos, done, temps, seeds,
                                      topps, use_sample, use_topp)
                pos = jnp.minimum(pos + 1, Lmax - 1)
                return (nxt, pos, done, ck, cv), nxt

            (tok, pos, done, ck, cv), toks = jax.lax.scan(
                one, (tok, pos, done, ck, cv), None, length=n_ticks)
            return toks, tok, pos, done, ck, cv

        def step_fn_paged(pk, pv, tok, pos, done, tables, temps, seeds,
                          topps, n_ticks, use_sample, use_topp):
            """The paged twin of ``step_fn``: decode through per-slot
            block tables against the shared pool.  Rows holding no
            blocks (free/done slots — their table rows are all SINK)
            write and read only the sink block's garbage, which their
            frozen/ignored outputs never surface."""

            def one(carry, _):
                tok, pos, done, pk, pv = carry
                logits, pk, pv = model.apply(
                    variables, tok, pk, pv, tables, pos,
                    method=TransformerLM.decode_step_paged)
                nxt, done = pick_next(logits, pos, done, temps, seeds,
                                      topps, use_sample, use_topp)
                pos = jnp.minimum(pos + 1, Lmax - 1)
                return (nxt, pos, done, pk, pv), nxt

            (tok, pos, done, pk, pv), toks = jax.lax.scan(
                one, (tok, pos, done, pk, pv), None, length=n_ticks)
            return toks, tok, pos, done, pk, pv

        # one compiled program per (n_ticks, sampled) pair — n_ticks is
        # bounded by ticks_per_step, so the cache stays small
        self._step_cache: Dict[Tuple[int, bool, bool],
                               Callable] = {}

        def get_step(n: int, sampled: bool,
                     use_topp: bool = False) -> Callable:
            key = (n, sampled, use_topp)
            if key not in self._step_cache:
                # cache miss = a program variant XLA must build; in
                # steady state this event never fires again (the trace
                # timeline makes a late one — a retrace — stand out)
                self.telemetry.jit_build("step", key)
                fn = step_fn_paged if self.paged else step_fn
                self._step_cache[key] = jax.jit(
                    partial(fn, n_ticks=n, use_sample=sampled,
                            use_topp=use_topp),
                    donate_argnums=(0, 1))
            return self._step_cache[key]

        self._get_step = get_step

        def paged_admit_fn(pk, pv, suffixes, slens, tables, pos):
            """Paged admission prefill: each row's (unshared) prompt
            suffix runs block-causally against pool K/V its table
            already maps — prefix-matched blocks behind ``pos`` read as
            if this row had prefilled them itself.  Monolithic
            admission IS one maximal chunk, so this is just
            ``prefill_chunk_paged``: writes limited to ``pos + slens``
            (suffix padding writes nothing), padding ROWS carry
            all-sink tables, and the return is each row's
            last-real-position logits (the head applied to [kb, 1, H]
            — never the [kb, sb, V] cube)."""
            return model.apply(
                variables, suffixes, pk, pv, tables, pos, slens,
                method=TransformerLM.prefill_chunk_paged)

        self._paged_admit = jax.jit(paged_admit_fn,
                                    donate_argnums=(0, 1))

        def prefill_fn(prompts, plens):
            """Batched joiner prefill: [k, Pb] prompts in ONE forward
            (bursts amortise the admission cost k-fold); returns each
            row's last-real-position logits + stacked K/V."""
            logits, ks, vs = model.apply(variables, prompts,
                                         method=TransformerLM.prefill)
            last = jnp.take_along_axis(
                logits, (plens - 1)[:, None, None], axis=1)[:, 0]
            return last, ks, vs

        self._prefill = jax.jit(prefill_fn)

        def insert_fn(ck, cv, ks, vs, slot):
            ck = jax.lax.dynamic_update_slice(
                ck, ks.astype(ck.dtype), (0, slot, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                cv, vs.astype(cv.dtype), (0, slot, 0, 0, 0))
            return ck, cv

        self._insert = jax.jit(insert_fn, donate_argnums=(0, 1))

        # ---- fused chunked tick (decode + prefill chunks, ONE call) ----
        S_arena = S

        def fused_fn(ck, cv, tok, pos, done, temps, seeds, topps,
                     ctoks, cpos, clens, cslots, ctemps, cseeds,
                     ctopps, with_decode, use_sample, use_topp,
                     read_len):
            """One budget-bounded tick: decode EVERY slot once (bitwise
            the unfused 1-tick step — PREFILLING rows ride along frozen,
            their one garbage write at the fill frontier is overwritten
            by their own chunk below, in this same program), then run
            the tick's prefill chunks block-causally at their fill
            offsets via ``prefill_chunk`` on a compact ``[kb,
            read_len]`` cache window (gathered/scattered exactly like
            ``_prefix_admit``: padding rows carry the out-of-range slot
            index S — reads clamp, writes drop).  Returns the decode
            picks AND each chunk row's next-token pick: a prompt's
            first token is chosen the tick its last chunk lands, with
            the same rng position-fold as ``_pick_first``."""
            if with_decode:
                logits, ck, cv = model.apply(
                    variables, tok, ck, cv, pos,
                    method=TransformerLM.decode_step)
                nxt, done = pick_next(logits, pos, done, temps, seeds,
                                      topps, use_sample, use_topp)
                pos = jnp.minimum(pos + 1, Lmax - 1)
            else:
                nxt = tok
            read_idx = jnp.minimum(cslots, S_arena - 1)
            rows_k = jnp.take(ck, read_idx, axis=1)[:, :, :read_len]
            rows_v = jnp.take(cv, read_idx, axis=1)[:, :, :read_len]
            clog, rows_k, rows_v = model.apply(
                variables, ctoks, rows_k, rows_v, cpos, clens,
                method=TransformerLM.prefill_chunk)
            ck = ck.at[:, cslots, :read_len].set(
                rows_k.astype(ck.dtype), mode="drop")
            cv = cv.at[:, cslots, :read_len].set(
                rows_v.astype(cv.dtype), mode="drop")
            cnxt, _ = pick_next(
                clog, cpos + clens - 1,
                jnp.zeros(clens.shape, jnp.bool_), ctemps, cseeds,
                ctopps, use_sample, use_topp)
            return nxt, pos, done, cnxt, ck, cv

        def fused_paged_fn(pk, pv, tok, pos, done, tables, temps,
                           seeds, topps, ctoks, cpos, clens, ctabs,
                           ctemps, cseeds, ctopps, with_decode,
                           use_sample, use_topp):
            """The paged twin: chunks scatter through NARROW per-row
            tables (``ctabs`` [kb, Mb], host-sliced to the fill
            frontier, bucketed) — ``prefill_chunk_paged`` limits writes
            to ``cpos + clens`` so padding columns write nothing and
            the narrow window can never clamp a stray write into a
            live block.  Padding rows carry all-sink tables."""
            if with_decode:
                logits, pk, pv = model.apply(
                    variables, tok, pk, pv, tables, pos,
                    method=TransformerLM.decode_step_paged)
                nxt, done = pick_next(logits, pos, done, temps, seeds,
                                      topps, use_sample, use_topp)
                pos = jnp.minimum(pos + 1, Lmax - 1)
            else:
                nxt = tok
            clog, pk, pv = model.apply(
                variables, ctoks, pk, pv, ctabs, cpos, clens,
                method=TransformerLM.prefill_chunk_paged)
            cnxt, _ = pick_next(
                clog, cpos + clens - 1,
                jnp.zeros(clens.shape, jnp.bool_), ctemps, cseeds,
                ctopps, use_sample, use_topp)
            return nxt, pos, done, cnxt, pk, pv

        # one program per (with_decode, sampled, topp, read_len) —
        # read_len only varies on the arena path (O(log L) buckets)
        self._fused_cache: Dict[Tuple[bool, bool, bool, int],
                                Callable] = {}

        def get_fused(with_decode: bool, sampled: bool, use_topp: bool,
                      read_len: int = 0) -> Callable:
            key = (with_decode, sampled, use_topp, read_len)
            if key not in self._fused_cache:
                self.telemetry.jit_build("fused", key)
                if self.paged:
                    fn = partial(fused_paged_fn,
                                 with_decode=with_decode,
                                 use_sample=sampled, use_topp=use_topp)
                else:
                    fn = partial(fused_fn, with_decode=with_decode,
                                 use_sample=sampled, use_topp=use_topp,
                                 read_len=read_len)
                self._fused_cache[key] = jax.jit(fn,
                                                 donate_argnums=(0, 1))
            return self._fused_cache[key]

        self._get_fused = get_fused

        if draft_model is not None:
            self._init_speculative(cdtype)

        # ---- prefix caching (shared system prompts) --------------------
        # register_prefix() prefills a prompt PREFIX once; requests that
        # name it splice the stored K/V and prefill only their suffix —
        # against the spliced cache, via the same block-causal decode_k
        # the speculative verify uses (bitwise = running the full
        # concatenated prompt).
        self._prefixes: Dict[int, tuple] = {}
        self._next_prefix_id = 0

        def _prefix_admit_for(m, v, want_logits):
            def fn(ck, cv, pks, pvs, suffixes, suffix_lens, slots):
                """Splice a stored prefix [layers, 1, P, H, D] into kb
                slots and run their suffixes through decode_k against it
                in ONE forward — a burst naming the same system prompt
                (the feature's primary workload) costs one device call,
                like the plain path's bucketed prefill.  The row count
                is padded to a power of two by the caller (bounded
                compile count, like _admit's kb); padding rows carry the
                OUT-OF-RANGE slot index S — their reads clamp and their
                scatter-back is dropped (mode='drop'), so they touch no
                real slot.  Real slots must be distinct (popped from the
                free list)."""
                P = pks.shape[2]
                kb = suffixes.shape[0]
                read_idx = jnp.minimum(slots, ck.shape[1] - 1)
                rows_k = jnp.take(ck, read_idx, axis=1)
                rows_v = jnp.take(cv, read_idx, axis=1)
                pref_k = jnp.broadcast_to(
                    pks, (pks.shape[0], kb) + pks.shape[2:])
                pref_v = jnp.broadcast_to(
                    pvs, (pvs.shape[0], kb) + pvs.shape[2:])
                rows_k = jax.lax.dynamic_update_slice(
                    rows_k, pref_k.astype(rows_k.dtype), (0, 0, 0, 0, 0))
                rows_v = jax.lax.dynamic_update_slice(
                    rows_v, pref_v.astype(rows_v.dtype), (0, 0, 0, 0, 0))
                # the suffix is ONE chunk at offset P: prefill_chunk is
                # the block-causal decode_k forward this path always
                # ran, minus the [kb, sb, V] logits cube (the head only
                # touches each row's last real position)
                last, rows_k, rows_v = m.apply(
                    v, suffixes, rows_k, rows_v,
                    jnp.full((kb,), P, jnp.int32), suffix_lens,
                    method=TransformerLM.prefill_chunk)
                ck = ck.at[:, slots].set(rows_k.astype(ck.dtype),
                                         mode="drop")
                cv = cv.at[:, slots].set(rows_v.astype(cv.dtype),
                                         mode="drop")
                if not want_logits:
                    return None, ck, cv
                return last, ck, cv

            return jax.jit(fn, donate_argnums=(0, 1))

        self._prefix_admit = _prefix_admit_for(model, variables, True)
        if self.draft_model is not None:
            self._draft_prefix_admit = _prefix_admit_for(
                self.draft_model, self._draft_variables, False)

        self._register_engine_gauges()

    def _register_engine_gauges(self) -> None:
        """Scrape-time gauges over engine/pool state: nothing is
        updated per tick — each callback reads the live value when
        /metrics is actually scraped, under the same lock its mutators
        hold (``n_waiting`` -> engine lock, pool fields -> pool lock),
        so a scrape can never see a torn value."""
        m = self.telemetry.metrics
        m.gauge("zoo_engine_queue_depth",
                "requests waiting for a slot", fn=lambda: self.n_waiting)
        m.gauge("zoo_engine_active_slots",
                "resident requests (decode + prefilling)",
                fn=lambda: self.n_active)
        m.gauge("zoo_engine_peak_resident",
                "max co-resident requests observed",
                fn=lambda: self._peak_resident)
        if self.chunked:
            def _budget_util():
                denom = self._budget_ticks * self.tick_token_budget
                return (self._budget_tokens_used / denom) if denom \
                    else 0.0

            m.gauge("zoo_engine_budget_utilization",
                    "mean filled fraction of the tick token budget",
                    fn=_budget_util)
            m.gauge("zoo_engine_prefill_stall_ticks_total",
                    "ticks whose budget left no room for any chunk",
                    fn=lambda: self._prefill_stall_ticks,
                    kind="counter")
        if self.paged:
            def _pool_read(key):
                def read():
                    with self._pool_lock:
                        return self._pool.metrics()[key]
                return read

            for key, name, kind, hlp in (
                    ("free_blocks", "zoo_engine_free_blocks", "gauge",
                     "pool blocks on the free list"),
                    ("cached_blocks", "zoo_engine_cached_blocks",
                     "gauge",
                     "unreferenced blocks parked in the prefix LRU"),
                    ("referenced_blocks", "zoo_engine_referenced_blocks",
                     "gauge", "blocks held by live requests"),
                    ("occupancy", "zoo_engine_pool_occupancy", "gauge",
                     "referenced fraction of non-sink blocks"),
                    ("prefix_hit_rate", "zoo_engine_prefix_hit_rate",
                     "gauge", "prefix-cache block hits / queries"),
                    ("prefix_queries", "zoo_engine_prefix_queries_total",
                     "counter", "prompt blocks offered to lookup()"),
                    ("prefix_hits", "zoo_engine_prefix_hits_total",
                     "counter", "prompt blocks answered from the index"),
                    ("evictions", "zoo_engine_pool_evictions_total",
                     "counter", "LRU evictions of cached blocks"),
                    ("alloc_failures",
                     "zoo_engine_pool_alloc_failures_total", "counter",
                     "allocate() calls the pool could not serve")):
                m.gauge(name, hlp, fn=_pool_read(key), kind=kind)

    def _init_speculative(self, cdtype):
        """Draft arena + the jitted spec-round program.  One round per
        device call: draft proposes k per slot (k+1 cached feeds), the
        target verifies all slots' proposals in ONE decode_k forward,
        each slot advances by its own accepted count (per-row pointers —
        the arena layout the engine already has)."""
        draft, dvars = self.draft_model, self._draft_variables
        model, variables = self.model, self._variables
        S, L, k = self._S, self._L, self._spec_k
        eos_id = self.eos_id
        DH = getattr(draft, "kv_heads", draft.num_heads)
        DD = draft.hidden_size // draft.num_heads
        self._dck = jnp.zeros((draft.num_layers, S, L, DH, DD), cdtype)
        self._dcv = jnp.zeros_like(self._dck)
        self._dpos = np.zeros(S, np.int32)

        def spec_step(ck, cv, dck, dcv, tok, pos, dpos, done):
            # draft: k proposals via k+1 greedy cached feeds (the extra
            # feed writes d_{k-1}'s KV so a full-acceptance round leaves
            # the draft cache complete — models/speculative.py)
            def dstep(c, _):
                t, dck, dcv, p = c
                lg, dck, dcv = draft.apply(
                    dvars, t, dck, dcv, p,
                    method=TransformerLM.decode_step)
                nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                return (nxt, dck, dcv, p + 1), nxt

            (_, dck, dcv, _), d = jax.lax.scan(
                dstep, (tok, dck, dcv, dpos), None, length=k + 1)
            d = d.T[:, :k]                              # [S, k]

            inputs = jnp.concatenate([tok[:, None], d], axis=1)
            logits, ck, cv = model.apply(
                variables, inputs, ck, cv, pos,
                method=TransformerLM.verify_step)
            t = jnp.argmax(logits, -1).astype(jnp.int32)  # [S, k+1]

            match = (t[:, :k] == d)
            a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                        axis=1)
            n_emit = a + 1
            if eos_id is not None:
                js = jnp.arange(k + 1)[None, :]
                is_eos = (t == eos_id) & (js < n_emit[:, None])
                first_eos = jnp.where(is_eos.any(axis=1),
                                      jnp.argmax(is_eos, axis=1), k + 1)
                n_emit = jnp.minimum(n_emit, first_eos + 1)
                # frozen tail on-device, like the plain step: everything
                # after a slot's first eos reads as eos
                t = jnp.where(js > first_eos[:, None],
                              jnp.int32(eos_id), t)
            n_emit = jnp.where(done, 0, n_emit)
            new_tok = jnp.where(
                n_emit > 0,
                jnp.take_along_axis(
                    t, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0],
                tok)
            if eos_id is not None:
                done = done | ((n_emit > 0) & (new_tok == eos_id))
            pos = jnp.minimum(pos + n_emit, L - 1)
            dpos = jnp.minimum(dpos + n_emit, L - 1)
            # [k+1, S] to match the plain step's emission-order layout
            return (t.T, n_emit, new_tok, pos, dpos, done,
                    ck, cv, dck, dcv)

        self._spec_step = jax.jit(spec_step, donate_argnums=(0, 1, 2, 3))

        def draft_prefill_fn(prompts):
            _, ks, vs = draft.apply(dvars, prompts,
                                    method=TransformerLM.prefill)
            return ks, vs

        self._draft_prefill = jax.jit(draft_prefill_fn)

    @staticmethod
    def _kv_kernels_tp_sharded(shardings) -> bool:
        """Do the chosen rules put 'tp' on the k/v projection outputs?
        Inspected from the sharding tree itself so the arena layout can
        never drift from what the kernels actually emit."""
        import jax as _jax

        for path, sh in _jax.tree_util.tree_flatten_with_path(
                shardings)[0]:
            keys = [str(getattr(p, "key", "")) for p in path]
            if "kernel" in keys and any(k in ("key", "value")
                                        for k in keys):
                spec = getattr(sh, "spec", ())
                if any(ax == "tp" or (isinstance(ax, tuple)
                                      and "tp" in ax) for ax in spec):
                    return True
        return False

    # ---- submission ---------------------------------------------------

    def capacity_report(self) -> dict:
        """Concrete arena economics (what GQA/cache_dtype actually buy):
        bytes per slot, total arena bytes, and the multiplier vs a
        full-head model-dtype arena of the same geometry."""
        m = self.model
        if self.paged:
            H = self._pk.shape[3]
            D = self._pk.shape[4]
            per_block = 2 * m.num_layers * self._bs * H * D \
                * self._pk.dtype.itemsize
            per_slot_max = per_block * self._M
            arena_equiv = 2 * m.num_layers * self._L * H * D \
                * self._pk.dtype.itemsize * self._S
            return {
                "mode": "paged",
                "slots": self._S,
                "cache_len": self._L,
                "kv_heads": H,
                "cache_dtype": str(self._pk.dtype),
                "block_size": self._bs,
                "n_blocks": self._pool.n_blocks,
                "blocks_per_row_max": self._M,
                "bytes_per_block": per_block,
                "bytes_per_slot": per_slot_max,   # worst case; actual
                # residency is pay-as-you-grow + shared prefixes
                "arena_bytes": per_block * self._pool.n_blocks,
                "arena_equivalent_bytes": arena_equiv,
                "tp": 1,
                "arena_bytes_per_chip": per_block * self._pool.n_blocks,
                "draft_arena_bytes": 0,
                "prefix_bytes": 0,  # pinned prefixes live IN the pool
            }
        H_full = m.num_heads
        H = self._ck.shape[3]
        D = self._ck.shape[4]
        per_slot = 2 * m.num_layers * self._L * H * D * \
            self._ck.dtype.itemsize
        full = 2 * m.num_layers * self._L * H_full * D * \
            jnp.dtype(m.dtype).itemsize
        tp = int(self.mesh.shape.get("tp", 1)) if self.mesh is not None \
            else 1
        # per-chip pressure follows the arena's ACTUAL sharding — a
        # narrow-KV override replicates it, so /tp would overstate
        spec = getattr(self._ck.sharding, "spec", None)
        arena_tp = tp if spec is not None and len(spec) > 3 \
            and spec[3] == "tp" else 1
        return {
            "slots": self._S,
            "cache_len": self._L,
            "kv_heads": H,
            "cache_dtype": str(self._ck.dtype),
            "bytes_per_slot": per_slot,
            "arena_bytes": per_slot * self._S,
            # tp shards the arena over chips: HBM pressure per chip is
            # arena/tp, so tp slots multiply like a narrower dtype does
            "tp": tp,
            "arena_bytes_per_chip": per_slot * self._S // arena_tp,
            "capacity_multiplier_vs_mha_model_dtype":
                round(full / per_slot, 2),
            # HBM the speculative/prefix features pin beyond the arena
            "draft_arena_bytes": (
                2 * int(np.prod(self._dck.shape))
                * self._dck.dtype.itemsize
                if self.draft_model is not None else 0),
            "prefix_bytes": sum(
                int(np.prod(e.shape)) * e.dtype.itemsize
                for entry in self._prefix_snapshot()
                for e in (entry[0], entry[1], entry[3], entry[4])
                if e is not None),
        }

    def _prefix_snapshot(self):
        # register/unregister mutate the dict from client threads;
        # iterate a locked copy
        with self._lock:
            return list(self._prefixes.values())

    @property
    def n_active(self) -> int:
        return self._S - len(self._free)

    @property
    def n_waiting(self) -> int:
        with self._lock:
            return len(self._waiting)

    def register_prefix(self, tokens: np.ndarray) -> int:
        """Prefill a shared prompt PREFIX (system prompt) once; returns
        an id for ``submit(..., prefix=id)``.  Requests then ship only
        their suffix: admission splices the stored K/V and runs the
        suffix against it (block-causal decode_k — bitwise what the
        full concatenated prompt would have produced)."""
        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim != 1 or len(tokens) < 1:
            raise ValueError("prefix must be a non-empty 1-D int32 array")
        P = len(tokens)
        if P >= self.max_prompt_width:
            raise ValueError(
                f"prefix length {P} leaves no room for a suffix inside "
                f"max prompt width {self.max_prompt_width}")
        if self.paged:
            return self._register_prefix_paged(tokens)
        _, ks, vs = self.model.apply(self._variables,
                                     jnp.asarray(tokens[None], jnp.int32),
                                     method=TransformerLM.prefill)
        entry = [jax.device_put(ks), jax.device_put(vs), P, None, None]
        if self.draft_model is not None:
            _, dks, dvs = self.draft_model.apply(
                self._draft_variables,
                jnp.asarray(tokens[None], jnp.int32),
                method=TransformerLM.prefill)
            entry[3], entry[4] = jax.device_put(dks), jax.device_put(dvs)
        with self._lock:
            pid = self._next_prefix_id
            self._next_prefix_id += 1
            self._prefixes[pid] = tuple(entry)
        return pid

    def unregister_prefix(self, pid: int) -> None:
        """Release a prefix's pinned device K/V (both models').  A
        long-running server registering per-tenant prefixes must be able
        to evict them or HBM ratchets up forever.  In-flight requests
        already admitted keep their spliced copy; queued requests naming
        the id will fail admission loudly.

        Paged mode: releases the pin on the prefix's blocks — they park
        in the pool's LRU (still shareable by chain-hash lookups) until
        allocation pressure actually evicts them."""
        if self.paged:
            with self._lock:
                if pid not in self._paged_prefixes:
                    raise ValueError(f"unknown prefix id {pid}")
                _, blocks = self._paged_prefixes.pop(pid)
            with self._pool_lock:
                for b in blocks:
                    self._pool.release(b)
            return
        with self._lock:
            if pid not in self._prefixes:
                raise ValueError(f"unknown prefix id {pid}")
            del self._prefixes[pid]

    def submit(self, uri: str, prompt: np.ndarray,
               on_done: Optional[Callable] = None, *,
               on_error: Optional[Callable] = None,
               temperature: float = 0.0,
               rng_seed: Optional[int] = None,
               max_new: Optional[int] = None,
               prefix: Optional[int] = None,
               top_p: float = 0.0) -> None:
        """Queue one request.  ``prompt``: 1-D int32 token array.
        ``on_done(uri, tokens)`` fires from the pump thread when the
        request finishes (tokens: ``[max_new]`` int32, eos-padded frozen
        tail); ``on_error(uri, exc)`` fires if admission (prefill/
        splice) fails after the request left the waiting queue — without
        it a device error there would silently swallow the request.  ``max_new`` (default: the engine budget) caps THIS
        request's tokens — slot-level budgets are a capability the
        whole-batch path structurally lacks (its one scan runs every
        row to the same length).  Raises on bounds violations — the
        serving layer error-publishes per request before calling this."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1:
            raise ValueError(f"prompt must be 1-D, got {prompt.shape}")
        n = len(prompt)
        if prefix is not None:
            with self._lock:
                if self.paged:
                    if prefix not in self._paged_prefixes:
                        raise ValueError(f"unknown prefix id {prefix}")
                    plen_pref = len(self._paged_prefixes[prefix][0])
                else:
                    if prefix not in self._prefixes:
                        raise ValueError(f"unknown prefix id {prefix}")
                    plen_pref = self._prefixes[prefix][2]
            # the TRUE prompt (prefix + suffix) must fit the prompt
            # budget; the padded suffix only needs to fit the cache
            # (_suffix_width handles that), so no bucket term here
            if n < 1 or plen_pref + n > self.max_prompt_width:
                raise ValueError(
                    f"prefix({plen_pref}) + suffix({n}) exceeds max "
                    f"prompt width {self.max_prompt_width}")
        elif n < 1 or n > self.max_prompt_width:
            raise ValueError(
                f"prompt length {n} outside [1, {self.max_prompt_width}]")
        if temperature > 0.0 and rng_seed is None:
            raise ValueError("temperature > 0 needs rng_seed")
        if temperature > 0.0 and self.draft_model is not None:
            raise ValueError(
                "speculative continuous batching is greedy-only (the "
                "sampled contract needs rejection sampling); submit "
                "with temperature=0 or build the engine without a draft")
        if rng_seed is not None:
            # mask into uint32 range: an out-of-range client seed must
            # not crash the pump thread at the np.uint32 staging array
            rng_seed = int(rng_seed) & 0xFFFFFFFF
        mn = self.max_new_tokens if max_new is None else int(max_new)
        if not 1 <= mn <= self.max_new_tokens:
            raise ValueError(
                f"max_new {mn} outside [1, {self.max_new_tokens}]")
        # stamp AFTER validation: a rejected submit never existed as
        # far as queue-wait/TTFT accounting is concerned
        self.telemetry.req_enqueued(uri)
        with self._lock:
            self._waiting.append(_Req(
                uri, prompt, on_done, on_error, float(temperature),
                rng_seed, mn, prefix, float(top_p)))

    # ---- pump ---------------------------------------------------------

    def _admit(self) -> int:
        """Move waiting requests into free slots.  Joiners sharing a
        prompt bucket prefill TOGETHER in one forward (row count padded
        to a power of two so a burst costs a handful of compiles, not
        one per burst size); their K/V splice into slots one
        dynamic_update_slice each.  Returns the number admitted."""
        if self.chunked:
            return self._admit_chunked()
        if self.paged:
            return self._admit_paged()
        admitted = 0
        while self._free:
            with self._lock:
                grab = min(len(self._free), len(self._waiting))
                batch = [self._waiting.popleft() for _ in range(grab)]
            if not batch:
                break
            by_bucket: Dict[int, list] = {}
            by_prefix: Dict[Tuple[int, int], list] = {}
            for req in batch:
                if req.prefix is not None:  # prefix-cached request
                    with self._lock:
                        P = self._prefixes.get(req.prefix,
                                               (None, None, 0))[2]
                    sb = self._suffix_width(len(req.prompt), P)
                    by_prefix.setdefault((req.prefix, sb),
                                         []).append(req)
                    continue
                pb = _next_bucket(len(req.prompt), self.prompt_buckets)
                by_bucket.setdefault(pb, []).append(req)
            for (pid, sb), reqs in by_prefix.items():
                try:
                    admitted += self._admit_prefix_group(pid, sb, reqs)
                except Exception as e:
                    logger.exception(
                        "prefix admission failed for %d request(s), "
                        "prefix %s", len(reqs), pid)
                    for req in reqs:
                        self._req_error(req.uri, req.on_error, e)
            for pb, reqs in by_bucket.items():
                # a failed prefill/splice must not swallow requests that
                # already left the waiting queue: surface each one to
                # its error callback and keep admitting other groups
                try:
                    k = len(reqs)
                    kb = 1 << (k - 1).bit_length()  # pad rows to pow2
                    padded = np.full((kb, pb), self.pad_id, np.int32)
                    plens = np.ones(kb, np.int32)   # dummy rows: len 1
                    for i, req in enumerate(reqs):
                        padded[i, :len(req.prompt)] = req.prompt
                        plens[i] = len(req.prompt)
                    pre = self._prefill(jnp.asarray(padded, jnp.int32),
                                        jnp.asarray(plens, jnp.int32))
                    if self.draft_model is not None:
                        pre = pre + self._draft_prefill(
                            jnp.asarray(padded, jnp.int32))
                    # ONE host fetch of the bucket's first-token logits;
                    # per-request picks below then stay on numpy
                    pre = (np.asarray(pre[0]),) + tuple(pre[1:])
                except Exception as e:
                    logger.exception(
                        "prefill failed for %d request(s), bucket %d",
                        len(reqs), pb)
                    for req in reqs:
                        self._req_error(req.uri, req.on_error, e)
                    continue
                for i, req in enumerate(reqs):
                    try:
                        self._splice_one(pre, i, req)
                        admitted += 1
                    except Exception as e:
                        logger.exception("splice failed for %r", req.uri)
                        self._req_error(req.uri, req.on_error, e)
        return admitted

    def _req_error(self, uri, on_error, exc):
        self.telemetry.req_errored(uri, f"{type(exc).__name__}: {exc}")
        if on_error is None:
            return
        try:
            on_error(uri, exc)
        except Exception:
            logger.exception("on_error callback failed for %r", uri)

    def _suffix_width(self, n: int, P: int) -> int:
        """Padded width for a prefix request's suffix: a shared prompt
        bucket when one fits after the prefix (bounded compile count),
        else the exact remaining cache room (one compile per prefix
        length — still bounded by registered prefixes).  Suffix padding
        writes dead K/V past the true prompt; they are never attended
        and later rounds overwrite them, so only the CACHE bound (L)
        applies, not the prompt budget."""
        for b in self.prompt_buckets:
            if n <= b and P + b <= self._L - 1:
                return b
        return self._L - 1 - P

    def _admit_prefix_group(self, pid: int, sb: int, reqs) -> int:
        """Admission for prefix-cached requests sharing (prefix, suffix
        width): splice the stored K/V into each group member's slot and
        run ALL their suffixes against it in one decode_k forward — the
        semantics of prefilling each concatenated prompt, at one device
        call per burst.  Returns the number admitted."""
        with self._lock:
            if pid not in self._prefixes:
                raise ValueError(f"prefix id {pid} was unregistered "
                                 f"while queued")
            pks, pvs, P, dks, dvs = self._prefixes[pid]
        n = min(len(reqs), len(self._free))
        if n < len(reqs):
            # free slots ran out mid-batch: requeue the rest in order
            with self._lock:
                for req in reversed(reqs[n:]):
                    self._waiting.appendleft(req)
            reqs = reqs[:n]
        if not reqs:
            return 0
        # pad rows to a power of two (bounded compile count, like the
        # bucketed prefill); padding rows target the out-of-range slot
        # index S — reads clamp, writes drop
        kb = 1 << (n - 1).bit_length()
        padded = np.full((kb, sb), self.pad_id, np.int32)
        lens = np.ones(kb, np.int32)
        for i, req in enumerate(reqs):
            padded[i, :len(req.prompt)] = req.prompt
            lens[i] = len(req.prompt)
        real = [self._free.popleft() for _ in range(n)]
        slots = real + [self._S] * (kb - n)
        try:
            last, self._ck, self._cv = self._prefix_admit(
                self._ck, self._cv, pks, pvs,
                jnp.asarray(padded, jnp.int32),
                jnp.asarray(lens, jnp.int32),
                jnp.asarray(slots, jnp.int32))
            if self.draft_model is not None:
                _, self._dck, self._dcv = self._draft_prefix_admit(
                    self._dck, self._dcv, dks, dvs,
                    jnp.asarray(padded, jnp.int32),
                    jnp.asarray(lens, jnp.int32),
                    jnp.asarray(slots, jnp.int32))
        except Exception:
            self._free.extend(real)
            raise
        last = np.asarray(last)     # one D2H for the whole group
        admitted = 0
        for i, req in enumerate(reqs):
            try:
                plen = P + int(lens[i])
                first = self._pick_first(last[i], plen,
                                         req.temperature, req.rng_seed,
                                         req.top_p)
                self._install_slot(real[i], req.uri, plen, req.max_new,
                                   req.on_done, req.on_error,
                                   req.temperature, req.rng_seed,
                                   first, req.top_p)
                admitted += 1
            except Exception as e:
                self._free.append(real[i])
                self._req_error(req.uri, req.on_error, e)
        return admitted

    # ---- chunked admission (PREFILLING slots, no device call) ---------

    def _admit_chunked(self) -> int:
        """Chunked admission runs NO prefill: it only claims a slot,
        installs it in the ``PREFILLING`` state, and (paged) attaches
        any prefix-matched blocks — the prompt feeds the cache chunk by
        chunk inside the fused tick, interleaved with decodes under
        the token budget.  A paged request the pool can't start yet
        requeues at the front and admission stops (order preserved);
        mid-prompt growth handles the rest per chunk."""
        admitted = 0
        while self._free:
            with self._lock:
                req = self._waiting.popleft() if self._waiting else None
            if req is None:
                break
            res = (self._admit_one_chunked_paged(req) if self.paged
                   else self._admit_one_chunked(req))
            if res == "admitted":
                admitted += 1
            elif res == "blocked":
                with self._lock:
                    self._waiting.appendleft(req)
                break
        return admitted

    def _admit_one_chunked(self, req: _Req) -> str:
        """Arena chunked admission: splice a named prefix's stored K/V
        (chunks then run against it block-causally, like the monolithic
        prefix path) and install the slot PREFILLING at the prefix
        boundary."""
        base = 0
        pks = pvs = None
        if req.prefix is not None:
            with self._lock:
                entry = self._prefixes.get(req.prefix)
            if entry is None:
                self._req_error(req.uri, req.on_error, ValueError(
                    f"prefix id {req.prefix} was unregistered while "
                    f"queued"))
                return "error"
            pks, pvs, base = entry[0], entry[1], entry[2]
        slot = self._free.popleft()
        if pks is not None:
            try:
                self._ck, self._cv = self._insert(
                    self._ck, self._cv, pks, pvs, jnp.int32(slot))
            except Exception as e:
                self._free.append(slot)
                logger.exception("chunked prefix splice failed for %r",
                                 req.uri)
                self._req_error(req.uri, req.on_error, e)
                return "error"
        self._install_prefill(slot, req, base + len(req.prompt),
                              base=base, full=req.prompt)
        return "admitted"

    def _admit_one_chunked_paged(self, req: _Req) -> str:
        """Paged chunked admission: match + acquire leading full prompt
        blocks (copy-free sharing, capped at ``(plen-1)//bs`` so the
        last token always recomputes for its first-token logits) and
        install PREFILLING at the matched boundary.  Blocks for the
        unmatched tail are allocated PER CHUNK by the tick scheduler —
        a mid-prompt dry pool preempts this prefilling row back to the
        queue, never a decoder."""
        try:
            full = self._full_prompt(req)
        except Exception as e:
            self._req_error(req.uri, req.on_error, e)
            return "error"
        plen = len(full)
        hashes = self._pool.block_hashes(full)
        total = -(-plen // self._bs)
        with self._pool_lock:
            matched = self._pool.lookup(
                hashes[:(plen - 1) // self._bs])
            need = total - len(matched)
            if need + 1 > self._pool.n_blocks - 1:
                self._req_error(req.uri, req.on_error, ValueError(
                    f"prompt needs {need} private blocks + headroom "
                    f"but the pool holds {self._pool.n_blocks - 1}"))
                return "error"
            # per-chunk allocation only needs room to START (first
            # chunk block + decode headroom); monolithic admission's
            # need+1 gate would block exactly the long prompts
            # chunking exists to stream in
            if self._pool.allocatable() < 2:
                if self.n_active == 0:
                    self._req_error(req.uri, req.on_error, RuntimeError(
                        f"pool dry with no residents: "
                        f"{self._pool.num_referenced()} of "
                        f"{self._pool.n_blocks} blocks are pinned "
                        f"(unregister a prefix or raise n_blocks)"))
                    return "error"
                return "blocked"
            for b in matched:
                self._pool.acquire(b)
        slot = self._free.popleft()
        self._row_blocks[slot] = list(matched)
        self._tables[slot, :] = SINK_BLOCK
        self._tables[slot, :len(matched)] = matched
        self._install_prefill(slot, req, plen, base=0, full=full,
                              hashes=list(hashes),
                              fill=len(matched) * self._bs,
                              n_pub=len(matched))
        return "admitted"

    def _install_prefill(self, slot: int, req: _Req, plen: int, *,
                         base: int, full, hashes=None, fill=None,
                         n_pub: int = 0) -> None:
        """Install a slot in the PREFILLING state: the decode side sees
        a frozen row (done=True, fed pad) anchored at the fill frontier
        until its last chunk lands.  ``fill`` (paged) starts past
        prefix-matched blocks; arena rows start past the spliced
        prefix (``base``)."""
        self._slots[slot] = _Slot(
            uri=req.uri, plen=plen, max_new=req.max_new,
            on_done=req.on_done, on_error=req.on_error,
            temperature=req.temperature, rng_seed=req.rng_seed,
            top_p=req.top_p, req=req, admit_seq=self._admit_seq,
            state="PREFILLING",
            fill_pos=base if fill is None else fill,
            base=base, full=np.asarray(full, np.int32),
            hashes=hashes, n_pub=n_pub)
        self._admit_seq += 1
        self._tok[slot] = self.pad_id
        self._pos[slot] = self._slots[slot].fill_pos
        self._done[slot] = True
        self.telemetry.req_admitted(req.uri, slot, prefilling=True)

    # ---- paged mode (block-pool cache) --------------------------------

    def _full_prompt(self, req: _Req) -> np.ndarray:
        """The TRUE token sequence a paged request decodes: a
        ``prefix=`` id expands to its registered tokens + the suffix —
        the chain-hash index then shares the pinned blocks
        automatically, subsuming the arena's device-side splice."""
        if req.prefix is None:
            return req.prompt
        with self._lock:
            if req.prefix not in self._paged_prefixes:
                raise ValueError(f"prefix id {req.prefix} was "
                                 f"unregistered while queued")
            ptoks = self._paged_prefixes[req.prefix][0]
        return np.concatenate([ptoks, req.prompt])

    def _register_prefix_paged(self, tokens: np.ndarray) -> int:
        """Pin a shared prefix's FULL blocks in the pool (ref held until
        ``unregister_prefix``): prefill them once through the paged
        path, publish their chain hashes, and store the tokens so
        ``submit(prefix=id)`` requests concatenate host-side and match
        the pinned blocks at admission.  The partial tail beyond the
        last full block recomputes per request inside its suffix (a
        partial block can never be shared — it would keep growing)."""
        P = len(tokens)
        bs = self._bs
        nfull = P // bs
        hashes = self._pool.block_hashes(tokens[:nfull * bs])
        with self._pool_lock:
            matched = self._pool.lookup(hashes)
            for b in matched:
                self._pool.acquire(b)
            blocks = list(matched)
            for _ in range(nfull - len(matched)):
                b = self._pool.allocate()
                if b is None:
                    for bb in blocks:
                        self._pool.release(bb)
                    raise RuntimeError(
                        f"block pool has no room to pin a {nfull}-block "
                        f"prefix ({self._pool.num_referenced()} of "
                        f"{self._pool.n_blocks} blocks referenced)")
                blocks.append(b)
        if len(matched) < nfull:
            span = tokens[len(matched) * bs:nfull * bs]
            sb = _next_bucket(len(span), self.prompt_buckets)
            padded = np.full((1, sb), self.pad_id, np.int32)
            padded[0, :len(span)] = span
            tabs = np.full((1, self._M), SINK_BLOCK, np.int32)
            tabs[0, :len(blocks)] = blocks
            _, self._pk, self._pv = self._paged_admit(
                self._pk, self._pv, jnp.asarray(padded, jnp.int32),
                jnp.asarray([len(span)], jnp.int32),
                jnp.asarray(tabs, jnp.int32),
                jnp.asarray([len(matched) * bs], jnp.int32))
            with self._pool_lock:
                for j in range(len(matched), nfull):
                    self._pool.insert(hashes[j], blocks[j])
        with self._lock:
            pid = self._next_prefix_id
            self._next_prefix_id += 1
            self._paged_prefixes[pid] = (tokens, blocks)
        return pid

    def _admit_paged(self) -> int:
        """Paged admission: per request, match leading FULL prompt
        blocks in the chain-hash index (copy-free sharing), allocate
        private blocks for the rest, and prefill only the unshared
        suffix — grouped by suffix bucket so a burst costs one device
        call per bucket.  A request the pool can't hold yet requeues at
        the FRONT (order preserved) and admission stops — residents
        finishing or preemption will free blocks.  The match length is
        capped at ``(plen-1)//bs`` blocks so the LAST prompt token
        always recomputes: its forward yields the first-token logits
        (a 100% cache hit would leave nothing to run)."""
        admitted = 0
        while self._free:
            with self._lock:
                grab = min(len(self._free), len(self._waiting))
                batch = [self._waiting.popleft() for _ in range(grab)]
            if not batch:
                break
            plans, blocked = [], []
            for req in batch:
                if blocked:         # keep queue order behind the block
                    blocked.append(req)
                    continue
                try:
                    full = self._full_prompt(req)
                except Exception as e:
                    self._req_error(req.uri, req.on_error, e)
                    continue
                plen = len(full)
                hashes = self._pool.block_hashes(full)
                total = -(-plen // self._bs)
                with self._pool_lock:
                    matched = self._pool.lookup(
                        hashes[:(plen - 1) // self._bs])
                    need = total - len(matched)
                    # +1 headroom: the first decode tokens must not
                    # instantly preempt what admission just built
                    if need + 1 > self._pool.n_blocks - 1:
                        self._req_error(req.uri, req.on_error, ValueError(
                            f"prompt needs {need} private blocks + "
                            f"headroom but the pool holds "
                            f"{self._pool.n_blocks - 1}"))
                        continue
                    if self._pool.allocatable() < need + 1:
                        if (self.n_active == 0 and not plans
                                and admitted == 0):
                            # nothing in flight will ever free blocks:
                            # only prefix pins hold the pool
                            self._req_error(
                                req.uri, req.on_error, RuntimeError(
                                    f"pool dry with no residents: "
                                    f"{self._pool.num_referenced()} of "
                                    f"{self._pool.n_blocks} blocks are "
                                    f"pinned (unregister a prefix or "
                                    f"raise n_blocks)"))
                            continue
                        blocked.append(req)
                        continue
                    for b in matched:
                        self._pool.acquire(b)
                    blocks = list(matched)
                    for _ in range(need):
                        blocks.append(self._pool.allocate())
                plans.append((req, full, hashes, len(matched), blocks))
            if blocked:
                with self._lock:
                    for req in reversed(blocked):
                        self._waiting.appendleft(req)
            groups: Dict[int, list] = {}
            for plan in plans:
                slen = len(plan[1]) - plan[3] * self._bs
                sb = _next_bucket(slen, self.prompt_buckets)
                groups.setdefault(sb, []).append(plan)
            for sb, plist in groups.items():
                try:
                    admitted += self._admit_paged_group(sb, plist)
                except Exception as e:
                    logger.exception("paged admission failed for %d "
                                     "request(s)", len(plist))
                    with self._pool_lock:
                        for req, _, _, _, blocks in plist:
                            for b in blocks:
                                self._pool.release(b)
                    for req, _, _, _, _ in plist:
                        self._req_error(req.uri, req.on_error, e)
            if blocked:
                break
        return admitted

    def _admit_paged_group(self, sb: int, plans) -> int:
        """One paged-prefill device call for every planned request
        sharing a suffix bucket (rows padded to a power of two;
        padding rows carry all-sink tables and touch nothing real).
        After the call each row's full private prompt blocks are
        published in the hash index, so the NEXT identical prompt
        shares them."""
        n = len(plans)
        kb = 1 << (n - 1).bit_length()
        padded = np.full((kb, sb), self.pad_id, np.int32)
        lens = np.ones(kb, np.int32)
        pos = np.zeros(kb, np.int32)
        tabs = np.full((kb, self._M), SINK_BLOCK, np.int32)
        for i, (req, full, hashes, n_match, blocks) in enumerate(plans):
            sfx = full[n_match * self._bs:]
            padded[i, :len(sfx)] = sfx
            lens[i] = len(sfx)
            pos[i] = n_match * self._bs
            tabs[i, :len(blocks)] = blocks
        last, self._pk, self._pv = self._paged_admit(
            self._pk, self._pv, jnp.asarray(padded, jnp.int32),
            jnp.asarray(lens, jnp.int32), jnp.asarray(tabs, jnp.int32),
            jnp.asarray(pos, jnp.int32))
        last = np.asarray(last)     # one D2H for the whole group
        admitted = 0
        for i, (req, full, hashes, n_match, blocks) in enumerate(plans):
            plen = len(full)
            slot = self._free.popleft()
            self._row_blocks[slot] = blocks
            self._tables[slot, :] = SINK_BLOCK
            self._tables[slot, :len(blocks)] = blocks
            # publish BEFORE install: the prefill succeeded, so the
            # blocks' content is valid for sharing even if this
            # particular install fails below
            with self._pool_lock:
                for j in range(n_match, plen // self._bs):
                    self._pool.insert(hashes[j], blocks[j])
            try:
                first = self._pick_first(last[i], plen,
                                         req.temperature, req.rng_seed,
                                         req.top_p)
                self._install_slot(slot, req.uri, plen, req.max_new,
                                   req.on_done, req.on_error,
                                   req.temperature, req.rng_seed,
                                   first, req.top_p, req=req)
                admitted += 1
            except Exception as e:
                self._free.append(slot)
                self._release_slot_blocks(slot)
                self._req_error(req.uri, req.on_error, e)
        return admitted

    def _ensure_blocks(self, active) -> list:
        """Grow each resident's block table to cover the positions the
        coming chunk will write.  When the pool is dry, PREEMPT the
        latest admission (never the oldest — earliest requests keep
        strict forward progress, so this terminates): its blocks free
        up, its request requeues at the queue front, and its tokens
        regenerate deterministically on readmission.  Returns the
        still-active subset."""
        for i in list(active):
            st = self._slots[i]
            if st is None:
                continue
            ticks = max(1, min(self.ticks_per_step,
                               st.max_new - len(st.tokens)))
            last_write = min(int(self._pos[i]) + ticks - 1, self._L - 1)
            self._grow_row(i, last_write // self._bs + 1)
        return [i for i in active if self._slots[i] is not None]

    def _grow_row(self, i: int, need: int) -> None:
        """Grow row ``i``'s block table to ``need`` blocks, preempting
        (latest admission, prefilling rows first) whenever the pool is
        dry — including row ``i`` itself, which ends the loop."""
        while (self._slots[i] is not None
               and len(self._row_blocks[i]) < need):
            with self._pool_lock:
                b = self._pool.allocate()
            if b is None:
                self._preempt(self._pick_victim())
                continue
            j = len(self._row_blocks[i])
            self._row_blocks[i].append(b)
            self._tables[i, j] = b

    def _grow_chunk_blocks(self, decode_rows, chunks) -> None:
        """Per-tick paged growth for the fused step: decode rows need
        their one write position covered; each chunk row needs blocks
        through its chunk's last write.  Pool-dry preemption targets
        the LATEST PREFILLING row first (``_pick_victim``) — decoders
        that already emitted tokens are never evicted to feed a
        joiner's prompt."""
        for i in decode_rows:
            if self._slots[i] is None:
                continue
            last_write = min(int(self._pos[i]), self._L - 1)
            self._grow_row(i, last_write // self._bs + 1)
        for i, clen in chunks:
            st = self._slots[i]
            if st is None:
                continue
            self._grow_row(i, (st.fill_pos + clen - 1) // self._bs + 1)

    def _publish_chunk_blocks(self, i: int, st: _Slot) -> None:
        """Hash-publish the prompt blocks a landed chunk fully covered
        (never the frontier block — a partially written block must not
        be shared), so the NEXT identical prompt attaches copy-free,
        exactly like monolithic admission's post-prefill publish."""
        if st.hashes is None:
            return
        hi = min(st.fill_pos // self._bs, st.plen // self._bs)
        if hi <= st.n_pub:
            return
        blocks = self._row_blocks[i]
        with self._pool_lock:
            for j in range(st.n_pub, hi):
                self._pool.insert(st.hashes[j], blocks[j])
        st.n_pub = hi

    def _table_width(self, need: int) -> int:
        """Pow2-bucketed narrow table width for a chunk grid: wide
        enough for every position the chunks write/attend, capped at
        the full table width M."""
        v = 1
        while v < need:
            v *= 2
        return min(v, self._M)

    def _pick_victim(self) -> int:
        live = [i for i in range(self._S) if self._slots[i] is not None]
        pre = [i for i in live
               if self._slots[i].state == "PREFILLING"]
        # prefilling rows first: they lost no emitted tokens and
        # requeue cheaply; among candidates, always the LATEST
        # admission (earliest admissions keep strict forward progress)
        return max(pre or live, key=lambda i: self._slots[i].admit_seq)

    def _preempt(self, slot: int) -> None:
        """Evict a resident back to the WAITING queue (front, original
        request intact, partial tokens discarded) and free its blocks.
        Readmission recomputes the prompt — recompute-not-swap, the
        vLLM default — and regenerates the same tokens (greedy argmax;
        sampled rows fold the rng by absolute position)."""
        st = self._slots[slot]
        self._slots[slot] = None
        self._done[slot] = True
        self._free.append(slot)
        self._release_slot_blocks(slot)
        self._preemptions += 1
        if st.state == "PREFILLING":
            self._prefill_preemptions += 1
        logger.warning("block pool dry: preempted %r (recompute on "
                       "readmission)", st.uri)
        with self._lock:
            self._waiting.appendleft(st.req)
        # TTFT keeps the original arrival; partial tokens are
        # discarded, so their stamps go too (telemetry mirrors both)
        self.telemetry.req_preempted(
            st.uri, slot, prefilling=st.state == "PREFILLING")

    def _release_slot_blocks(self, slot: int) -> None:
        """Drop a finished/preempted row's block references and point
        its whole table row at the sink, so the frozen row's future
        writes can NEVER touch a block the pool hands to someone else
        — the paged form of the arena's recycled-slot isolation."""
        blocks = self._row_blocks[slot]
        self._row_blocks[slot] = []
        self._tables[slot, :] = SINK_BLOCK
        with self._pool_lock:
            for b in blocks:
                self._pool.release(b)

    def cache_metrics(self) -> dict:
        """Serving-visible cache counters (bench_serving.py columns).

        The snapshot is taken under the ENGINE lock (and, for the pool
        merge, the pool lock), so a caller on another thread can never
        see torn state — e.g. a queue depth from before a preemption
        merged with pool occupancy from after it.  Field semantics:

        - **cumulative** (monotonic since construction): ``preemptions``,
          ``prefill_stall_ticks``, ``prefill_preemptions``, and the
          pool's ``prefix_queries`` / ``prefix_hits`` / ``evictions`` /
          ``alloc_failures``.  ``peak_resident`` and
          ``budget_utilization`` are cumulative aggregates (running max
          / running mean), not resettable rates.
        - **instantaneous** (value at snapshot time):
          ``prefill_queue_depth``, ``chunks_in_flight``, and the pool's
          ``free_blocks`` / ``cached_blocks`` / ``referenced_blocks`` /
          ``occupancy`` (plus the static ``mode`` / ``chunked`` /
          ``tick_token_budget`` / ``n_blocks`` / ``block_size``).

        The same values are exported continuously (and individually
        documented) by the telemetry registry — this dict remains for
        callers that want one coherent point-in-time snapshot."""
        with self._lock:
            out = {
                "mode": "paged" if self.paged else "arena",
                "preemptions": self._preemptions,
                "peak_resident": self._peak_resident,
            }
            if self.chunked:
                denom = self._budget_ticks * self.tick_token_budget
                out.update({
                    "chunked": True,
                    "tick_token_budget": self.tick_token_budget,
                    # mean fraction of each fused tick's budget
                    # actually filled with decode rows + chunk tokens
                    "budget_utilization": (
                        self._budget_tokens_used / denom
                        if denom else 0.0),
                    # len() directly: self.n_waiting re-acquires the
                    # non-reentrant engine lock we already hold
                    "prefill_queue_depth": len(self._waiting),
                    "chunks_in_flight": sum(
                        1 for s in self._slots
                        if s is not None and s.state == "PREFILLING"),
                    "prefill_stall_ticks": self._prefill_stall_ticks,
                    "prefill_preemptions": self._prefill_preemptions,
                })
        if self.paged:
            with self._pool_lock:
                out.update(self._pool.metrics())
        return out

    @property
    def record_timings(self) -> bool:
        """Back-compat shim: raw per-request stamp retention now lives
        in the telemetry facade (the percentile histograms are always
        on regardless — this flag only controls the unbounded per-uri
        store ``pop_request_timings`` drains)."""
        return self.telemetry.keep_request_stamps

    @record_timings.setter
    def record_timings(self, v: bool) -> None:
        self.telemetry.keep_request_stamps = bool(v)

    def pop_request_timings(self) -> Dict[str, dict]:
        """Drain per-request wall-clock stamps collected under
        ``record_timings=True``: uri -> {"arrival": t, "token_times":
        [t0, t1, ...]} (``time.monotonic()`` seconds).  TTFT =
        token_times[0] - arrival; TPOT = consecutive token_times
        deltas.  Clears the store — the bench pops once per run.
        The stamps are written by the SAME telemetry hooks that feed
        the always-on histograms, so the two surfaces agree by
        construction."""
        return self.telemetry.pop_request_stamps()

    def _install_slot(self, slot, uri, plen, mn, on_done, on_error,
                      temp, seed, first, top_p=0.0, req=None):
        """Shared slot-state installation for every admission path —
        plain bucket splice and prefix admission must never drift."""
        self._slots[slot] = _Slot(
            uri=uri, plen=plen, max_new=mn, on_done=on_done,
            on_error=on_error, temperature=temp, rng_seed=seed,
            top_p=top_p, req=req, admit_seq=self._admit_seq)
        self._admit_seq += 1
        self._tok[slot] = first
        self._pos[slot] = plen
        if self.draft_model is not None:
            self._dpos[slot] = plen
        self._done[slot] = False
        self.telemetry.req_admitted(uri, slot)
        self._record_token(slot, int(first))

    def _splice_one(self, pre, i: int, req) -> None:
        """Insert one prefetched joiner into a free slot; the slot goes
        back to the free list if the splice fails."""
        last_logits, ks, vs = pre[0], pre[1], pre[2]
        uri, prompt = req.uri, req.prompt
        temp, seed, tp = req.temperature, req.rng_seed, req.top_p
        mn, on_done, on_error = req.max_new, req.on_done, req.on_error
        slot = self._free.popleft()
        try:
            self._ck, self._cv = self._insert(
                self._ck, self._cv, ks[:, i:i + 1], vs[:, i:i + 1],
                jnp.int32(slot))
            if self.draft_model is not None:
                dks, dvs = pre[3], pre[4]
                self._dck, self._dcv = self._insert(
                    self._dck, self._dcv, dks[:, i:i + 1],
                    dvs[:, i:i + 1], jnp.int32(slot))
            plen = len(prompt)
            first = self._pick_first(last_logits[i], plen, temp, seed,
                                     tp)
        except Exception:
            self._free.append(slot)
            raise
        self._install_slot(slot, uri, plen, mn, on_done, on_error,
                           temp, seed, first, tp)

    def _pick_first(self, last_logits, plen: int, temp: float,
                    seed, top_p: float = 0.0) -> int:
        """The prefill's last-position logits produce the request's first
        token — same pick semantics (and rng position-fold) as
        ``generate``'s step at t = plen-1.  ``last_logits`` arrives as
        host numpy: every admission path fetches its whole group's
        logits in ONE transfer, so the common greedy pick costs zero
        device round-trips per request."""
        if temp <= 0.0:
            return int(np.argmax(last_logits))
        key = jax.random.fold_in(jax.random.key(int(seed)), plen - 1)
        scaled = jnp.asarray(last_logits, jnp.float32) / temp
        if top_p > 0.0:
            scaled = top_p_filter(scaled, jnp.float32(top_p))
        # sampled admission must reproduce pick_next's categorical
        # bitwise (a preempted-and-readmitted row regenerates the same
        # token), so the draw stays on device: one sync per SAMPLED
        # admission only (baselined).
        return int(jax.random.categorical(key, scaled))

    def _record_token(self, slot: int, token: int):
        """Append one generated token; finish + free the slot when done."""
        st = self._slots[slot]
        st.tokens.append(token)
        self.telemetry.req_token(st.uri, slot)
        done = len(st.tokens) >= st.max_new or \
            (self.eos_id is not None and token == self.eos_id)
        if not done:
            return
        out = np.full(st.max_new,
                      self.eos_id if self.eos_id is not None else 0,
                      np.int32)
        out[:len(st.tokens)] = st.tokens      # frozen tail: eos padding
        self._slots[slot] = None
        self._done[slot] = True     # terminal state until readmission
        self._free.append(slot)
        if self.paged:
            # refcounts drop + table row -> sink BEFORE the next device
            # step, so a recycled block can never see this row's writes
            self._release_slot_blocks(slot)
        self.telemetry.req_finished(st.uri, slot, len(st.tokens))
        if st.on_done is not None:
            try:
                st.on_done(st.uri, out)
            except Exception:
                logger.exception("continuous-batching on_done callback "
                                 "failed for %r", st.uri)

    def step(self) -> int:
        """One engine iteration: admit joiners, then advance every
        resident by up to ``ticks_per_step`` tokens in one device call
        (capped by the largest remaining token budget among residents —
        a nearly-finished slot must not throttle the arena to 1-tick
        device calls; its surplus tokens are dropped host-side in
        ``_record_token``, and EOS mid-chunk freezes on-device like
        generate()'s frozen tail).  Returns the number of active
        slots afterwards (0 = idle; the caller decides how to wait).
        Higher ``ticks_per_step`` trades admission latency granularity
        for fewer host round-trips — the dominant per-token cost on
        tunneled devices."""
        if self.n_active == 0 and not self._waiting:
            # idle poll (the serving pump spins on step()): no work to
            # do or measure, and no tick event to spam the ring with
            return 0
        t0 = time.monotonic()
        n = self._step_impl()
        self.telemetry.tick(t0, time.monotonic() - t0,
                            self._tick_samples(n))
        return n

    def _tick_samples(self, n_active: int) -> dict:
        """Post-tick residency mix + queue/pool pressure, as plain host
        ints — the per-tick sample row of the ISSUE's event-log spec."""
        decode = prefill = 0
        for s in self._slots:
            if s is not None:
                if s.state == "DECODE":
                    decode += 1
                else:
                    prefill += 1
        samples = {"active": n_active, "decode_rows": decode,
                   "prefill_rows": prefill,
                   "queue_depth": len(self._waiting)}
        if self._pool is not None:
            with self._pool_lock:
                samples["free_blocks"] = self._pool.allocatable()
        return samples

    def _step_impl(self) -> int:
        self._admit()
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return 0
        if self.draft_model is not None:
            return self._spec_tick(active)
        if self.chunked and any(self._slots[i].state == "PREFILLING"
                                for i in active):
            return self._chunked_tick(active)
        # a chunked engine with NO prefill in flight decodes on the
        # ORIGINAL (multi-tick, scan-amortised) path below — chunking
        # costs nothing in steady state
        if self.paged:
            # grow block tables for the coming chunk; may preempt
            active = self._ensure_blocks(active)
            if not active:
                self._admit()   # preemptions freed blocks: retry now
                return self.n_active
        self._peak_resident = max(self._peak_resident, len(active))
        sampled = any(self._slots[i].temperature > 0.0 for i in active)
        use_topp = any(self._slots[i].top_p > 0.0 for i in active)
        temps = np.zeros(self._S, np.float32)
        seeds = np.zeros(self._S, np.uint32)
        topps = np.zeros(self._S, np.float32)
        for i in active:
            temps[i] = self._slots[i].temperature
            seeds[i] = self._slots[i].rng_seed or 0
            topps[i] = self._slots[i].top_p
        n_eff = max(1, min(
            self.ticks_per_step,
            max(self._slots[i].max_new - len(self._slots[i].tokens)
                for i in active)))
        step = self._get_step(n_eff, sampled, use_topp)
        if self.paged:
            toks, tok, pos, done, self._pk, self._pv = step(
                self._pk, self._pv, jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(self._tables, jnp.int32),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32))
        else:
            toks, tok, pos, done, self._ck, self._cv = step(
                self._ck, self._cv, jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32))
        toks = np.asarray(toks)                     # [n_eff, S]
        # np.asarray of a jax array is a read-only view; _admit writes
        # per-slot entries, so take mutable copies
        self._tok = np.array(tok)
        self._pos = np.array(pos)
        self._done = np.array(done)
        for i in active:
            for j in range(n_eff):
                if self._slots[i] is None:
                    break       # finished mid-chunk; the rest is frozen
                self._record_token(i, int(toks[j, i]))
        self._admit()       # freed slots recycle on the SAME iteration
        return self.n_active

    def _sampling_vectors(self, rows):
        """[S]-wide temperature/seed/top_p staging vectors with entries
        only at ``rows`` (other rows are frozen or empty — their picks
        are discarded, so zeros are fine)."""
        temps = np.zeros(self._S, np.float32)
        seeds = np.zeros(self._S, np.uint32)
        topps = np.zeros(self._S, np.float32)
        for i in rows:
            temps[i] = self._slots[i].temperature
            seeds[i] = self._slots[i].rng_seed or 0
            topps[i] = self._slots[i].top_p
        return temps, seeds, topps

    def _reanchor_prefill(self) -> None:
        """Re-pin every still-PREFILLING row's decode-side state after
        a device step: frozen (done=True), fed pad, positioned at the
        fill frontier — the decode part of the next fused tick then
        writes its one dead K/V entry exactly where the row's own next
        chunk will overwrite it."""
        for i, st in enumerate(self._slots):
            if st is not None and st.state == "PREFILLING":
                self._done[i] = True
                self._pos[i] = st.fill_pos
                self._tok[i] = self.pad_id

    def _chunked_tick(self, active) -> int:
        """One budget-bounded fused iteration (the tentpole): every
        DECODE row advances one token AND up to ``tick_token_budget -
        n_decode`` tokens of PREFILLING prompts land, in ONE device
        call.  Chunks are granted FIFO by admission order; a prompt's
        final chunk also picks its first token inside the same program
        (no extra admission forward, no decode stall)."""
        decode_rows = [i for i in active
                       if self._slots[i].state == "DECODE"]
        prefill_rows = sorted(
            (i for i in active
             if self._slots[i].state == "PREFILLING"),
            key=lambda i: self._slots[i].admit_seq)
        remaining = self.tick_token_budget - len(decode_rows)
        chunks: List[Tuple[int, int]] = []          # (slot, chunk len)
        for i in prefill_rows:
            if remaining <= 0:
                break
            st = self._slots[i]
            clen = min(st.plen - st.fill_pos, remaining,
                       self._chunk_buckets[-1])
            if clen <= 0:
                continue
            chunks.append((i, clen))
            remaining -= clen
        if prefill_rows and not chunks:
            # budget fully consumed by decode rows: prefill waits
            self._prefill_stall_ticks += 1
        if self.paged:
            self._grow_chunk_blocks(decode_rows, chunks)  # may preempt
            decode_rows = [i for i in decode_rows
                           if self._slots[i] is not None]
            chunks = [(i, c) for i, c in chunks
                      if self._slots[i] is not None]
        if not decode_rows and not chunks:
            self._admit()       # preemptions may have freed blocks
            return self.n_active
        self._peak_resident = max(self._peak_resident, len(active))
        self._budget_ticks += 1
        self._budget_tokens_used += len(decode_rows) \
            + sum(c for _, c in chunks)
        if not chunks:
            return self._decode_only_tick(decode_rows)
        with_decode = bool(decode_rows)
        crows = [i for i, _ in chunks]
        sampled = any(self._slots[i].temperature > 0.0
                      for i in decode_rows + crows)
        use_topp = any(self._slots[i].top_p > 0.0
                       for i in decode_rows + crows)
        temps, seeds, topps = self._sampling_vectors(decode_rows)
        # ---- chunk grid: pow2 rows x bucketed width ----
        k = len(chunks)
        kb = 1 << (k - 1).bit_length()
        Cb = _next_bucket(max(c for _, c in chunks),
                          self._chunk_buckets)
        ctoks = np.full((kb, Cb), self.pad_id, np.int32)
        cpos = np.zeros(kb, np.int32)
        clens = np.ones(kb, np.int32)
        cslots = np.full(kb, self._S, np.int32)     # pad rows: drop
        ctemps = np.zeros(kb, np.float32)
        cseeds = np.zeros(kb, np.uint32)
        ctopps = np.zeros(kb, np.float32)
        for j, (i, clen) in enumerate(chunks):
            st = self._slots[i]
            off = st.fill_pos - st.base
            ctoks[j, :clen] = st.full[off:off + clen]
            cpos[j] = st.fill_pos
            clens[j] = clen
            cslots[j] = i
            ctemps[j] = st.temperature
            cseeds[j] = st.rng_seed or 0
            ctopps[j] = st.top_p
        need = int((cpos + clens).max())
        t_fused = time.monotonic()
        if self.paged:
            Mb = self._table_width(-(-need // self._bs))
            ctabs = np.full((kb, Mb), SINK_BLOCK, np.int32)
            for j, (i, _) in enumerate(chunks):
                ctabs[j] = self._tables[i, :Mb]
            fused = self._get_fused(with_decode, sampled, use_topp)
            nxt, pos2, done2, cnxt, self._pk, self._pv = fused(
                self._pk, self._pv,
                jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(self._tables, jnp.int32),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32),
                jnp.asarray(ctoks, jnp.int32),
                jnp.asarray(cpos, jnp.int32),
                jnp.asarray(clens, jnp.int32),
                jnp.asarray(ctabs, jnp.int32),
                jnp.asarray(ctemps, jnp.float32),
                jnp.asarray(cseeds, jnp.uint32),
                jnp.asarray(ctopps, jnp.float32))
        else:
            read_len = next(b for b in self._read_buckets
                            if b >= need)
            fused = self._get_fused(with_decode, sampled, use_topp,
                                    read_len)
            nxt, pos2, done2, cnxt, self._ck, self._cv = fused(
                self._ck, self._cv,
                jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32),
                jnp.asarray(ctoks, jnp.int32),
                jnp.asarray(cpos, jnp.int32),
                jnp.asarray(clens, jnp.int32),
                jnp.asarray(cslots, jnp.int32),
                jnp.asarray(ctemps, jnp.float32),
                jnp.asarray(cseeds, jnp.uint32),
                jnp.asarray(ctopps, jnp.float32))
        # one host sync for decode picks + chunk first-token picks
        nxt, pos2, done2, cnxt = jax.device_get(
            (nxt, pos2, done2, cnxt))
        # all of a tick's chunks land in the one fused call above, so
        # they share its span (per-chunk device timing doesn't exist)
        dur_fused = time.monotonic() - t_fused
        for i, clen in chunks:
            self.telemetry.events.span(
                "prefill_chunk", t_fused, dur_fused, i,
                {"uri": self._slots[i].uri, "tokens": int(clen),
                 "fill_pos": int(self._slots[i].fill_pos)})
        self.telemetry.c_chunks.inc(len(chunks))
        if with_decode:
            self._tok = np.array(nxt)
            self._pos = np.array(pos2)
            self._done = np.array(done2)
        completed: List[Tuple[int, int]] = []
        for j, (i, clen) in enumerate(chunks):
            st = self._slots[i]
            st.fill_pos += clen
            if self.paged:
                self._publish_chunk_blocks(i, st)
            if st.fill_pos >= st.plen:
                completed.append((i, int(cnxt[j])))
        for i, first in completed:
            st = self._slots[i]
            st.state = "DECODE"
            st.full = st.hashes = None
            self._tok[i] = first
            self._pos[i] = st.plen
            self._done[i] = False
            self._record_token(i, first)    # the request's FIRST token
        self._reanchor_prefill()
        for i in decode_rows:
            if self._slots[i] is not None:
                self._record_token(i, int(nxt[i]))
        self._admit()       # freed slots recycle on the SAME iteration
        return self.n_active

    def _decode_only_tick(self, decode_rows) -> int:
        """Budget tick with no chunk grants (budget exhausted by decode
        rows, or every prefill row preempted): one unfused 1-tick step
        — the SAME compiled program as the non-chunked path, so no
        extra compile — then re-anchor the frozen PREFILLING rows."""
        sampled = any(self._slots[i].temperature > 0.0
                      for i in decode_rows)
        use_topp = any(self._slots[i].top_p > 0.0 for i in decode_rows)
        temps, seeds, topps = self._sampling_vectors(decode_rows)
        step = self._get_step(1, sampled, use_topp)
        if self.paged:
            toks, tok, pos, done, self._pk, self._pv = step(
                self._pk, self._pv, jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(self._tables, jnp.int32),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32))
        else:
            toks, tok, pos, done, self._ck, self._cv = step(
                self._ck, self._cv, jnp.asarray(self._tok, jnp.int32),
                jnp.asarray(self._pos, jnp.int32),
                jnp.asarray(self._done, jnp.bool_),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.asarray(topps, jnp.float32))
        toks = np.asarray(toks)
        self._tok = np.array(tok)
        self._pos = np.array(pos)
        self._done = np.array(done)
        self._reanchor_prefill()
        for i in decode_rows:
            if self._slots[i] is not None:
                self._record_token(i, int(toks[0, i]))
        self._admit()
        return self.n_active

    def precompile_chunked(self, sampled: bool = False,
                           use_topp: bool = False,
                           max_chunk_rows: Optional[int] = None) -> int:
        """Eagerly compile the chunked scheduler's whole fused-program
        shape grid, so steady-state serving compiles NOTHING regardless
        of arrival timing — a cold-start aid for latency-sensitive
        deployments (and for benchmarks, where a first-encounter
        compile inside a percentile would be measured as a stall).

        The grid is exactly the bounded space ``_chunked_tick`` can
        reach: chunk-row counts (pow2 up to ``max_chunk_rows``, default
        ``max_slots``), chunk widths (the prompt buckets that fit the
        budget), with/without live decode rows, and per shape the arena
        read window (pow2 buckets, capped at the largest prompt bucket)
        or the paged narrow-table width (pow2, same cap).  Unreachable
        combinations are pruned: a chunk width bucket ``Cb`` implies
        some chunk longer than the previous bucket, so windows that
        cannot contain such a chunk are skipped.  Returns the number of
        (program, shape) variants visited.  Dummy buffers are used
        throughout — engine state is untouched."""
        if not self.chunked:
            raise ValueError("precompile_chunked requires chunked=True")
        S = self._S
        kmax = min(max_chunk_rows or S, S)
        kbs, kb = [], 1
        while kb < kmax:
            kbs.append(kb)
            kb *= 2
        kbs.append(kb)
        max_prompt = self.prompt_buckets[-1]
        tok = jnp.zeros(S, jnp.int32)
        pos = jnp.zeros(S, jnp.int32)
        done = jnp.ones(S, jnp.bool_)
        temps = jnp.zeros(S, jnp.float32)
        seeds = jnp.zeros(S, jnp.uint32)
        topps = jnp.zeros(S, jnp.float32)
        count = 0
        for ci, Cb in enumerate(self._chunk_buckets):
            prev = self._chunk_buckets[ci - 1] if ci else 0
            # the need (max fill frontier) that selects this Cb spans
            # (prev, max_prompt]: every window bucket covering part of
            # that range is reachable, nothing else is
            if self.paged:
                lo = self._table_width(-(-(prev + 1) // self._bs))
                hi = self._table_width(-(-max_prompt // self._bs))
                widths = []
                v = lo
                while v <= hi:
                    widths.append(v)
                    if v >= self._M:
                        break
                    v *= 2
            else:
                # window b serves need in (previous bucket, b]; keep it
                # iff that range overlaps the reachable (prev,
                # max_prompt]
                widths = [b for bi, b in enumerate(self._read_buckets)
                          if b > prev
                          and (self._read_buckets[bi - 1] if bi else 0)
                          < max_prompt]
            for kb in kbs:
                ctoks = jnp.full((kb, Cb), self.pad_id, jnp.int32)
                cpos = jnp.zeros(kb, jnp.int32)
                clens = jnp.ones(kb, jnp.int32)
                cslots = jnp.full(kb, S, jnp.int32)
                czeros = (jnp.zeros(kb, jnp.float32),
                          jnp.zeros(kb, jnp.uint32),
                          jnp.zeros(kb, jnp.float32))
                for width in widths:
                    for wd in (False, True):
                        if self.paged:
                            fn = self._get_fused(wd, sampled, use_topp)
                            fn(jnp.zeros_like(self._pk),
                               jnp.zeros_like(self._pv),
                               tok, pos, done,
                               jnp.full((S, self._M), SINK_BLOCK,
                                        jnp.int32),
                               temps, seeds, topps, ctoks, cpos,
                               clens,
                               jnp.full((kb, width), SINK_BLOCK,
                                        jnp.int32),
                               *czeros)
                        else:
                            fn = self._get_fused(wd, sampled,
                                                 use_topp, width)
                            fn(jnp.zeros_like(self._ck),
                               jnp.zeros_like(self._cv),
                               tok, pos, done, temps, seeds, topps,
                               ctoks, cpos, clens, cslots, *czeros)
                        count += 1
        return count

    def _spec_tick(self, active) -> int:
        """One speculative round for the whole arena: every resident
        advances by its own accepted count (1..k+1 tokens) in one device
        call.  Emission recording mirrors the plain path: per slot, in
        order, stopping when the slot finishes (budget surplus dropped
        host-side)."""
        (toks, n_emit, tok, pos, dpos, done,
         self._ck, self._cv, self._dck, self._dcv) = self._spec_step(
            self._ck, self._cv, self._dck, self._dcv,
            jnp.asarray(self._tok, jnp.int32),
            jnp.asarray(self._pos, jnp.int32),
            jnp.asarray(self._dpos, jnp.int32),
            jnp.asarray(self._done, jnp.bool_))
        toks = np.asarray(toks)                 # [k+1, S]
        n_emit = np.asarray(n_emit)
        self._tok = np.array(tok)
        self._pos = np.array(pos)
        self._dpos = np.array(dpos)
        self._done = np.array(done)
        self._spec_rounds = getattr(self, "_spec_rounds", 0) + 1
        self._spec_emitted = getattr(self, "_spec_emitted", 0) + int(
            n_emit[active].sum())
        for i in active:
            for j in range(int(n_emit[i])):
                if self._slots[i] is None:
                    break       # finished mid-round; the rest is frozen
                self._record_token(i, int(toks[j, i]))
        self._admit()       # freed slots recycle on the SAME iteration
        return self.n_active

    def drain(self, max_ticks: int = 100_000) -> None:
        """Run ticks until every submitted request has finished (tests /
        batch use)."""
        for _ in range(max_ticks):
            if self.step() == 0 and self.n_waiting == 0:
                return
        raise RuntimeError("drain did not converge")

"""Flight recorder, SLO watchdog, and anomaly-triggered diagnostics.

The third observability pillar, layered on serving/telemetry.py's
metrics/spans substrate.  Metrics answer "how is the engine doing",
traces answer "what did one request experience" — this module answers
the incident question: "what was the engine doing in the 30 seconds
before it went wrong", without anyone having had the foresight to turn
a profiler on.

Four pieces, all host-side and jax-free (this module must never import
jax — same contract as telemetry.py):

- :class:`FlightRecorder` — an always-on bounded ring of per-tick
  engine state snapshots (tick kind, budget split, decode/prefill row
  sets, per-pool block levels, preemption/retrace/spec deltas, and the
  active attention read path: ``kernel`` (gather/fused/dense),
  ``kv_dtype`` (bf16/int8/...), ``kv_bytes_per_token`` — so a
  regression bundle states which kernel and KV storage mode the engine
  was actually running when it went wrong).  One
  plain dict appended to a ``deque(maxlen=...)`` per tick: O(1) host
  work, no device interaction, so greedy outputs are bitwise-identical
  with the recorder on or off.
- :class:`SloWatchdog` — per-priority-class TTFT/TPOT/queue-wait
  targets (:class:`SloPolicy`), fed by the `Telemetry` request hooks.
  Exposes goodput gauges and breach counters through the existing
  `MetricsRegistry` (``zoo_slo_*`` families) and keeps a recent-breach
  ring so the anomaly monitor can detect breach BURSTS rather than
  paging on every slow request.
- :class:`AnomalyMonitor` — turns raw signals (SLO breach bursts,
  alloc-failure streaks, steady-state retraces, engine-thread crashes)
  into at-most-one diagnostic bundle per ``min_interval_s``, dumped by
  :func:`dump_bundle`: flight ring + metrics snapshot + Perfetto trace
  + resolved config + recent structured logs, self-contained in one
  directory that `python -m analytics_zoo_tpu.serving.debug` renders.
- Correlated structured logging — :class:`JsonLogFormatter` (one JSON
  object per line) and :class:`RingLogHandler` (bounded in-memory tail
  for bundles), both stamping every record with the request uri taken
  from a ``contextvar`` the HTTP frontend sets per request, so engine,
  server, and frontend log lines join on the same id the spans carry.

Nothing here is speculative machinery: the pump thread drives the
monitor with one cheap ``poll()`` per tick, and every trigger path is
rate-limited and failure-isolated (a broken disk never takes down the
serving loop).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import shutil
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from analytics_zoo_tpu.common.log import logger
from analytics_zoo_tpu.serving.frontdoor import PRIORITIES
from analytics_zoo_tpu.serving.telemetry import (MetricsRegistry,
                                                 render_prometheus,
                                                 validate_chrome_trace)

__all__ = [
    "FLIGHT_SCHEMA_VERSION",
    "FlightRecorder", "SloPolicy", "SloWatchdog", "AnomalyMonitor",
    "dump_bundle", "prune_bundles", "JsonLogFormatter", "RingLogHandler",
    "install_flight_logging", "request_uri_context", "current_request_uri",
    "DEFAULT_SLO_TARGETS", "SLO_METRICS",
]

#: Version of the tick-record + bundle-manifest schema.  Bump whenever
#: a field changes meaning or disappears (pure additions are fine at
#: the same version); the discrete-event simulator
#: (``serving/sim/replay.py``) refuses bundles stamped with a version
#: it does not know rather than silently misreading them, and
#: docs/simulation.md pins the current number (guarded by
#: tests/test_flight.py).
#:
#: v2: every paged tick additionally records the per-tenant pool SIZE
#: (``n_blocks``, ``draft_n_blocks``) plus per-tick ``pool_resizes`` /
#: ``handoffs_out`` / ``handoffs_in`` deltas, so elastic-pool resizes
#: and prefill/decode handoffs are visible on the flight timeline.
#: The reader backfills ``n_blocks`` for v1 bundles (static pools:
#: free + used + sink), so v1 replays unchanged.
#:
#: v3: paged ticks additionally record per-tick ``kv_spills`` /
#: ``kv_readmits`` deltas (tiered KV memory, serving/kv_store.py) so
#: host-tier traffic is visible on the flight timeline.  The replayer
#: keeps accepting v1/v2 (the new fields are diagnostic-only — replay
#: does not consume them, so nothing is backfilled).
FLIGHT_SCHEMA_VERSION = 3

# ---------------------------------------------------------------------------
# request-id correlation
# ---------------------------------------------------------------------------

# The uri of the request the CURRENT thread/context is working for.
# The HTTP frontend sets it for the duration of each handler; every
# JSON log record (and the ring tail that lands in bundles) picks it
# up, so `grep '"uri": "x"' ` joins frontend, server, and engine lines.
_REQUEST_URI: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "zoo_request_uri", default=None)


def current_request_uri() -> Optional[str]:
    """The request uri bound to the current context, or None."""
    return _REQUEST_URI.get()


@contextlib.contextmanager
def request_uri_context(uri: Optional[str]):
    """Bind ``uri`` as the current request id for log correlation."""
    token = _REQUEST_URI.set(uri)
    try:
        yield
    finally:
        _REQUEST_URI.reset(token)


def _record_to_dict(record: logging.LogRecord) -> Dict[str, Any]:
    """One log record as the flat dict both the JSON formatter and the
    ring handler emit — same fields, so the stderr stream and the
    bundle tail agree line for line."""
    out: Dict[str, Any] = {
        "ts": round(record.created, 6),
        "level": record.levelname,
        "logger": record.name,
        "msg": record.getMessage(),
    }
    # explicit extra={"uri": ...} beats the ambient contextvar
    uri = getattr(record, "uri", None)
    if uri is None:
        uri = _REQUEST_URI.get()
    if uri is not None:
        out["uri"] = uri
    if record.exc_info:
        out["exc"] = logging.Formatter().formatException(record.exc_info)
    return out


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line: ts / level / logger / msg, plus the
    correlated request ``uri`` when one is bound (contextvar or
    ``extra={"uri": ...}``) and the traceback under ``exc``."""

    def format(self, record: logging.LogRecord) -> str:
        return json.dumps(_record_to_dict(record), default=str)


class RingLogHandler(logging.Handler):
    """Bounded in-memory tail of structured log records — the "recent
    logs" a diagnostic bundle ships.  Appends are deque-atomic, so the
    hot path takes no lock."""

    def __init__(self, capacity: int = 1024, level: int = logging.DEBUG):
        super().__init__(level=level)
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._ring.append(_record_to_dict(record))
        except Exception:  # logging must never raise into the caller
            self.handleError(record)

    def snapshot(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        out = list(self._ring)
        if last is not None:
            out = out[-int(last):]
        return out


def install_flight_logging(capacity: int = 1024,
                           json_stderr: Optional[bool] = None
                           ) -> RingLogHandler:
    """Attach a :class:`RingLogHandler` to the package logger (reusing
    one that is already attached — idempotent across ClusterServing
    instances in one process) and optionally switch the stderr handler
    to JSON lines.

    ``json_stderr=None`` defers to the ``ZOO_TPU_LOG_JSON`` env var
    (any non-empty value other than "0" turns it on); the plain-text
    default stays human-first for interactive runs.
    """
    for h in logger.handlers:
        if isinstance(h, RingLogHandler):
            ring = h
            break
    else:
        ring = RingLogHandler(capacity=capacity)
        logger.addHandler(ring)
    if json_stderr is None:
        json_stderr = os.environ.get("ZOO_TPU_LOG_JSON", "0") not in ("", "0")
    if json_stderr:
        for h in logger.handlers:
            if isinstance(h, logging.StreamHandler) \
                    and not isinstance(h, RingLogHandler):
                h.setFormatter(JsonLogFormatter())
    return ring


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded ring of per-tick engine state snapshots.

    The engine appends ONE plain dict per device step (see
    `ContinuousEngine._flight_record` for the schema) — no copies, no
    aggregation, no device reads beyond what the tick already computed
    for telemetry.  ``capacity`` ticks of history is the incident
    window a bundle captures; 2048 ticks at a 20 ms step is ~40 s of
    lookback for well under a megabyte of host memory.

    Appends are deque-atomic so readers (`/debug/flight`, the bundle
    writer) snapshot without a lock; a snapshot taken mid-append is
    merely one tick short, never torn.
    """

    def __init__(self, capacity: int = 2048):
        if capacity < 1:
            raise ValueError(f"flight capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0

    def next_seq(self) -> int:
        """Monotonic tick sequence number (survives ring wraparound —
        ``seq`` in the oldest retained record tells you how much
        history fell off)."""
        self._seq += 1
        return self._seq

    def record(self, rec: Dict[str, Any]) -> None:
        # every retained tick states which schema wrote it, so a ring
        # snapshot (or the bundle built from one) is self-describing
        # even when the producer predates the reader
        rec.setdefault("schema_version", FLIGHT_SCHEMA_VERSION)
        self._ring.append(rec)

    def __len__(self) -> int:
        return len(self._ring)

    def snapshot(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        """The retained ticks, oldest first; ``last`` trims to the tail."""
        out = list(self._ring)
        if last is not None:
            out = out[-int(last):]
        return out


# ---------------------------------------------------------------------------
# SLO watchdog
# ---------------------------------------------------------------------------

# the three request-latency dimensions the watchdog judges
SLO_METRICS = ("ttft", "tpot", "queue_wait")

# Per-class targets (seconds).  Interactive buys latency, batch buys
# throughput — same 8:4:1 philosophy as the QoS weights: the classes
# that preempt others also promise more.
DEFAULT_SLO_TARGETS: Dict[str, Dict[str, float]] = {
    "interactive": {"ttft": 1.0, "tpot": 0.25, "queue_wait": 0.5},
    "standard": {"ttft": 2.5, "tpot": 0.5, "queue_wait": 2.0},
    "batch": {"ttft": 10.0, "tpot": 2.0, "queue_wait": 30.0},
}


@dataclass(frozen=True)
class SloPolicy:
    """Per-priority-class latency targets, seconds.  A target of 0 or
    less disables that dimension for that class (nothing breaches)."""

    targets: Dict[str, Dict[str, float]] = field(
        default_factory=lambda: {c: dict(DEFAULT_SLO_TARGETS[c])
                                 for c in PRIORITIES})

    def target(self, cls: str, metric: str) -> float:
        return float(self.targets.get(cls, {}).get(metric, 0.0))


class SloWatchdog:
    """Judges every finished request against :class:`SloPolicy` and
    keeps the score in the shared `MetricsRegistry`.

    Fed by the `Telemetry` request hooks (queue-wait at admission,
    TTFT at the first token, mean TPOT at finish), so it sees exactly
    the stamps the histograms and spans see — one clock, every
    surface.  A request is GOOD when none of its three dimensions
    breached; ``zoo_slo_goodput_{cls}`` is the cumulative good/total
    ratio per class, the number a multi-replica router would route on.

    Breaches also land in a bounded recent ring with timestamps, which
    is what :class:`AnomalyMonitor` polls: a BURST of breaches inside
    a short window triggers a bundle, one slow request does not.
    """

    def __init__(self, policy: Optional[SloPolicy] = None,
                 registry: Optional[MetricsRegistry] = None,
                 prefix: str = "zoo_slo_", recent_capacity: int = 256):
        self.policy = policy or SloPolicy()
        self.prefix = prefix
        self._lock = threading.Lock()
        # uri -> set of breached metric names for the in-flight request
        self._open_breaches: Dict[str, set] = {}
        self._finished: Dict[str, int] = {c: 0 for c in PRIORITIES}
        self._good: Dict[str, int] = {c: 0 for c in PRIORITIES}
        self._breaches: Dict[Tuple[str, str], int] = {
            (c, m): 0 for c in PRIORITIES for m in SLO_METRICS}
        # (monotonic_ts, cls, metric, value, target, uri) — newest last
        self._recent: deque = deque(maxlen=int(recent_capacity))
        # per-class ring of recent finish outcomes (True = met every
        # target) — the brownout controller's goodput signal.  The
        # CUMULATIVE ratio above never recovers after a bad hour, so a
        # controller keyed on it could latch degraded forever; this
        # window forgets.
        self._window: Dict[str, deque] = {
            c: deque(maxlen=int(recent_capacity)) for c in PRIORITIES}
        self.metrics = registry if registry is not None else MetricsRegistry()
        self._register(self.metrics)

    def _register(self, m: MetricsRegistry) -> None:
        p = self.prefix
        for c in PRIORITIES:
            m.gauge(f"{p}requests_total_{c}",
                    f"finished {c} requests judged against the SLO",
                    fn=(lambda c=c: self._finished[c]), kind="counter")
            m.gauge(f"{p}good_requests_total_{c}",
                    f"finished {c} requests that met every SLO target",
                    fn=(lambda c=c: self._good[c]), kind="counter")
            m.gauge(f"{p}goodput_{c}",
                    f"cumulative fraction of {c} requests meeting the SLO "
                    "(1.0 before any finish)",
                    fn=(lambda c=c: self._good[c] / self._finished[c]
                        if self._finished[c] else 1.0))
            for metric in SLO_METRICS:
                m.gauge(f"{p}{metric}_breaches_total_{c}",
                        f"{c} requests whose {metric} exceeded its target",
                        fn=(lambda c=c, metric=metric:
                            self._breaches[(c, metric)]), kind="counter")

    # -- observation hooks (called by Telemetry) ----------------------

    @staticmethod
    def _cls(priority: Optional[str]) -> str:
        return priority if priority in PRIORITIES else "standard"

    def _judge(self, cls: str, metric: str, value: float,
               uri: str) -> None:
        target = self.policy.target(cls, metric)
        if target <= 0.0 or value <= target:
            return
        with self._lock:
            self._breaches[(cls, metric)] += 1
            self._open_breaches.setdefault(uri, set()).add(metric)
            self._recent.append(
                (time.monotonic(), cls, metric, float(value), target, uri))

    def observe_queue_wait(self, priority: Optional[str], wait_s: float,
                           uri: str) -> None:
        self._judge(self._cls(priority), "queue_wait", wait_s, uri)

    def observe_ttft(self, priority: Optional[str], ttft_s: float,
                     uri: str) -> None:
        self._judge(self._cls(priority), "ttft", ttft_s, uri)

    def observe_finish(self, priority: Optional[str], uri: str,
                       tpot_s: Optional[float]) -> None:
        """Final judgement at request finish: fold in the mean TPOT
        (None for single-token responses — no gap to measure) and
        score the request good iff nothing breached."""
        cls = self._cls(priority)
        if tpot_s is not None:
            self._judge(cls, "tpot", tpot_s, uri)
        with self._lock:
            breached = self._open_breaches.pop(uri, None)
            self._finished[cls] += 1
            if not breached:
                self._good[cls] += 1
            self._window[cls].append(not breached)

    def drop(self, uri: str) -> None:
        """Forget an in-flight request that errored or was cancelled —
        it neither counts toward nor against goodput."""
        with self._lock:
            self._open_breaches.pop(uri, None)

    # -- introspection -------------------------------------------------

    def windowed_goodput(self, cls: str) -> float:
        """Fraction of the last ``recent_capacity`` finished ``cls``
        requests that met every SLO target — 1.0 before any finish.
        This (not the cumulative gauge) is what ``plan_brownout``
        consumes: it recovers when the engine does."""
        with self._lock:
            win = self._window.get(cls)
            if not win:
                return 1.0
            return sum(1 for ok in win if ok) / len(win)

    def breach_burst(self, window_s: float) -> int:
        """Breaches recorded in the trailing ``window_s`` seconds."""
        cutoff = time.monotonic() - float(window_s)
        with self._lock:
            return sum(1 for rec in self._recent if rec[0] >= cutoff)

    def status(self) -> Dict[str, Any]:
        """The /healthz + /debug/flight view: targets, per-class
        score, and the tail of recent breaches."""
        with self._lock:
            per_class = {}
            for c in PRIORITIES:
                fin = self._finished[c]
                per_class[c] = {
                    "finished": fin,
                    "good": self._good[c],
                    "goodput": (self._good[c] / fin) if fin else 1.0,
                    "breaches": {m: self._breaches[(c, m)]
                                 for m in SLO_METRICS},
                }
            recent = [{"age_s": round(time.monotonic() - t, 3),
                       "class": c, "metric": m,
                       "value_s": round(v, 4), "target_s": tgt, "uri": u}
                      for (t, c, m, v, tgt, u) in list(self._recent)[-8:]]
        return {"targets": {c: dict(self.policy.targets.get(c, {}))
                            for c in PRIORITIES},
                "per_class": per_class, "recent_breaches": recent}


# ---------------------------------------------------------------------------
# anomaly monitor
# ---------------------------------------------------------------------------

class AnomalyMonitor:
    """Turns raw engine/watchdog signals into rate-limited diagnostic
    bundles.  Four trigger kinds:

    - ``slo_breach_burst`` — >= ``breach_burst`` SLO breaches inside
      ``breach_window_s`` (one slow request never pages).
    - ``alloc_failure_streak`` — >= ``alloc_streak`` CONSECUTIVE ticks
      with at least one block-pool allocation failure: the pool is not
      momentarily tight, it is dry and staying dry.
    - ``steady_state_retrace`` — jit builds or retraces after the
      first ``steady_after_ticks`` ticks.  Cold-start compiles are
      normal; a compile at tick 10,000 means a shape leaked into a
      jitted signature and every occurrence costs seconds.
    - ``engine_crash`` — the pump thread's step raised; always worth a
      bundle (subject only to the rate limit).

    ``dump_cb(reason, detail)`` does the actual writing and returns
    the bundle path (or None on failure); this class only decides WHEN
    — at most one bundle per ``min_interval_s``, and the same reason
    re-fires only after the underlying signal clears and re-asserts.
    """

    def __init__(self, dump_cb: Callable[[str, Dict[str, Any]],
                                         Optional[str]],
                 *, min_interval_s: float = 30.0,
                 breach_burst: int = 8, breach_window_s: float = 10.0,
                 alloc_streak: int = 8, steady_after_ticks: int = 500):
        self.dump_cb = dump_cb
        self.min_interval_s = float(min_interval_s)
        self.breach_burst = int(breach_burst)
        self.breach_window_s = float(breach_window_s)
        self.alloc_streak = int(alloc_streak)
        self.steady_after_ticks = int(steady_after_ticks)
        self._lock = threading.Lock()
        self._last_dump_t: Optional[float] = None
        self._armed = {"slo_breach_burst": True,
                       "alloc_failure_streak": True}
        self._compile_baseline: Optional[int] = None
        # (wall_ts, reason, path) for /debug/flight and tests
        self.bundles: List[Tuple[float, str, Optional[str]]] = []

    # -- trigger decision ---------------------------------------------

    def _trigger(self, reason: str, detail: Dict[str, Any]) -> Optional[str]:
        """Rate-limited dump.  Never raises: a full disk or a bad
        directory must not take the pump thread with it."""
        with self._lock:
            now = time.monotonic()
            if self._last_dump_t is not None \
                    and now - self._last_dump_t < self.min_interval_s:
                return None
            self._last_dump_t = now
        try:
            path = self.dump_cb(reason, detail)
        except Exception:
            logger.exception("diagnostic bundle dump failed (%s)", reason)
            path = None
        self.bundles.append((time.time(), reason, path))
        if path:
            logger.warning("anomaly %s: diagnostic bundle written to %s",
                           reason, path)
        return path

    def poll(self, *, alloc_fail_streak: int = 0, ticks: int = 0,
             compiles: int = 0,
             watchdog: Optional[SloWatchdog] = None) -> None:
        """One cheap check per engine tick, driven by the pump thread.
        ``compiles`` is cumulative jit builds + retraces; ``ticks`` the
        cumulative tick count."""
        # alloc-failure streak: edge-triggered — re-arms when the
        # streak breaks, so one long drought is one bundle
        if alloc_fail_streak >= self.alloc_streak > 0:
            if self._armed["alloc_failure_streak"]:
                self._armed["alloc_failure_streak"] = False
                self._trigger("alloc_failure_streak",
                              {"streak_ticks": int(alloc_fail_streak),
                               "threshold": self.alloc_streak})
        else:
            self._armed["alloc_failure_streak"] = True
        # steady-state retrace: any compile growth past the warmup line
        if ticks >= self.steady_after_ticks > 0:
            if self._compile_baseline is None:
                self._compile_baseline = int(compiles)
            elif compiles > self._compile_baseline:
                grew = int(compiles) - self._compile_baseline
                self._compile_baseline = int(compiles)
                self._trigger("steady_state_retrace",
                              {"new_compiles": grew, "at_tick": int(ticks)})
        # SLO breach burst: level check over the watchdog's recent ring
        if watchdog is not None and self.breach_burst > 0:
            burst = watchdog.breach_burst(self.breach_window_s)
            if burst >= self.breach_burst:
                if self._armed["slo_breach_burst"]:
                    self._armed["slo_breach_burst"] = False
                    self._trigger("slo_breach_burst",
                                  {"breaches": int(burst),
                                   "window_s": self.breach_window_s,
                                   "threshold": self.breach_burst})
            else:
                self._armed["slo_breach_burst"] = True

    def crash(self, exc_text: str) -> Optional[str]:
        """The pump thread's engine.step() raised — dump what we have."""
        return self._trigger("engine_crash", {"traceback": exc_text})

    def history(self) -> List[Dict[str, Any]]:
        return [{"ts": t, "reason": r, "path": p}
                for (t, r, p) in self.bundles]


# ---------------------------------------------------------------------------
# bundle writer
# ---------------------------------------------------------------------------

def _write_json(path: str, obj: Any) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, indent=1, default=str)


def dump_bundle(root: str, *, reason: str, detail: Dict[str, Any],
                flight: Optional[FlightRecorder] = None,
                telemetries: Sequence[Any] = (),
                config: Optional[Dict[str, Any]] = None,
                logs: Optional[List[Dict[str, Any]]] = None,
                slo: Optional[Dict[str, Any]] = None,
                spec_acceptance: Optional[Dict[str, Any]] = None,
                extra: Optional[Dict[str, Any]] = None) -> str:
    """Write one self-contained diagnostic bundle directory under
    ``root`` and return its path.

    Layout (every file optional except the manifest — a missing
    telemetry or flight ring writes an empty stub, never fails):

    - ``manifest.json`` — reason, trigger detail, wall time, file list,
      ``schema_version`` (``FLIGHT_SCHEMA_VERSION``)
    - ``flight.json`` — the flight-recorder ring, oldest tick first
    - ``metrics.json`` — merged registry snapshots + Prometheus text
    - ``trace.json`` — Chrome trace-event JSON (Perfetto-loadable)
    - ``config.json`` — the resolved ServingConfig
    - ``logs.jsonl`` — recent structured log records, one per line
    - ``spec_acceptance.json`` — recorded speculative-acceptance
      distribution (``ContinuousEngine.spec_acceptance``), written only
      when the engine runs a draft model; the simulator's calibration
      source (docs/simulation.md)

    ``telemetries`` is any iterable of `Telemetry` facades (serving
    job + engine); their registries merge in order into metrics.json
    and their event rings concatenate into trace.json.
    """
    os.makedirs(root, exist_ok=True)
    stamp = time.strftime("%Y%m%d-%H%M%S")
    base = f"flight-{stamp}-{reason}"
    path = os.path.join(root, base)
    n = 1
    while os.path.exists(path):  # same-second triggers get a suffix
        n += 1
        path = os.path.join(root, f"{base}.{n}")
    os.makedirs(path)

    files = []
    tels = [t for t in telemetries if t is not None]

    ticks = flight.snapshot() if flight is not None else []
    _write_json(os.path.join(path, "flight.json"),
                {"schema_version": FLIGHT_SCHEMA_VERSION,
                 "capacity": flight.capacity if flight else 0,
                 "n_ticks": len(ticks), "ticks": ticks})
    files.append("flight.json")

    registries = []
    seen = set()
    for t in tels:
        if id(t.metrics) not in seen:
            seen.add(id(t.metrics))
            registries.append(t.metrics)
    merged: Dict[str, Any] = {}
    for r in registries:
        for k, v in r.snapshot().items():
            merged.setdefault(k, v)
    _write_json(os.path.join(path, "metrics.json"),
                {"snapshot": merged,
                 "prometheus": render_prometheus(*registries)})
    files.append("metrics.json")

    events: List[Dict[str, Any]] = []
    seen_events = set()
    for i, t in enumerate(tels):
        if id(t.events) in seen_events:
            continue
        seen_events.add(id(t.events))
        sub = t.events.to_chrome(
            process_name=f"serving-engine/{i}" if i else "serving-engine",
            pid=i + 1)
        events.extend(sub["traceEvents"])
    trace = {"traceEvents": events, "displayTimeUnit": "ms",
             "otherData": {"producer": "analytics_zoo_tpu.serving.flight",
                           "reason": reason}}
    validate_chrome_trace(trace)
    _write_json(os.path.join(path, "trace.json"), trace)
    files.append("trace.json")

    _write_json(os.path.join(path, "config.json"), config or {})
    files.append("config.json")

    with open(os.path.join(path, "logs.jsonl"), "w") as f:
        for rec in (logs or []):
            f.write(json.dumps(rec, default=str) + "\n")
    files.append("logs.jsonl")

    if slo is not None:
        _write_json(os.path.join(path, "slo.json"), slo)
        files.append("slo.json")
    if spec_acceptance is not None:
        _write_json(os.path.join(path, "spec_acceptance.json"),
                    spec_acceptance)
        files.append("spec_acceptance.json")
    if extra:
        _write_json(os.path.join(path, "extra.json"), extra)
        files.append("extra.json")

    _write_json(os.path.join(path, "manifest.json"),
                {"schema_version": FLIGHT_SCHEMA_VERSION,
                 "reason": reason, "detail": detail,
                 "written_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
                 "unix_ts": round(time.time(), 3), "files": files,
                 "n_flight_ticks": len(ticks)})
    return path


def prune_bundles(root: str, keep: int) -> int:
    """Delete the oldest ``flight-*`` bundle directories under ``root``
    beyond ``keep`` (newest by mtime survive).  Returns the number
    removed; a missing root is zero, not an error."""
    try:
        names = [n for n in os.listdir(root) if n.startswith("flight-")
                 and os.path.isdir(os.path.join(root, n))]
    except OSError:
        return 0
    if len(names) <= keep:
        return 0
    names.sort(key=lambda n: os.path.getmtime(os.path.join(root, n)))
    removed = 0
    for n in names[:len(names) - keep]:
        shutil.rmtree(os.path.join(root, n), ignore_errors=True)
        removed += 1
    return removed

"""Discrete-event model of a disaggregated serving FLEET.

``FleetModel`` wraps N ``EngineModel`` replicas behind the REAL router
policy — every placement decision is ``policy.route_request`` on
fabricated ``ReplicaSignals``, the same pure function and the same
rank tuple the live ``ClusterServing`` router evaluates — and models
the prefill/decode KV-handoff path (docs/serving_memory.md):

* arrivals route with ``phase="prefill"`` (when roles are configured),
  so prefill-heavy replicas take new prompts first; when replicas run
  the tiered KV model (``EngineConfig.prefix_cache_blocks``), each
  arrival's ``ReplicaSignals.prefix_blocks`` is filled from per-replica
  tier residency — the sim's ``PrefixDirectory`` — so the same
  locality rank term steers repeat prefixes back to their KV;
* a prefill replica exports a row at its FIRST token
  (``EngineModel.handoff_cb`` — the sim's
  ``ContinuousEngine._handoff_slot``), the fleet routes the handoff
  with ``phase="decode"`` and delivers it ``handoff_s`` later (the
  modelled chain-snapshot + KV-slice copy cost);
* the decode replica adopts via ``EngineModel.submit_prefilled`` —
  straight into DECODE, first token not re-emitted, lifecycle record
  continued (TTFT observed from the ORIGINAL arrival, exactly like
  the live telemetry).

Clocks: each replica keeps its own virtual ``now`` (they tick
independently, like real pump threads); the fleet driver always steps
the busiest-lagging replica (minimum ``now`` among those with work)
and fast-forwards an IDLE replica to its next delivery, mirroring the
serving pump's idle wait.  No wall clock, index-ordered iteration,
one seeded RNG per replica — byte-identical runs for the same
(configs, trace, seed), which is what lets ``make sim-gate`` pin a
disaggregated scenario's envelopes.
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import policy as scheduler_policy
from ..fault import FaultInjector
from ..policy import (PRIORITIES, REPLICA_ROLES, QosPolicy,
                      ReplicaSignals, pick_retry_target,
                      plan_handoff_recovery, plan_redispatch)
from .model import (AcceptanceModel, EngineConfig, EngineModel,
                    TimingModel, summarize)
from .trace import Request

__all__ = ["FleetModel"]


class FleetModel:
    """N modelled replicas + the real routing policy + KV handoff.

    Fault twin (``faults``): the fleet consumes the SAME fault
    schedules the live ``ClusterServing`` does (``serving/fault.py``),
    against virtual time — ``crash_pump`` at ``at_t`` kills a replica
    and re-dispatches its lost requests through the same pure policy
    functions the live supervisor calls (``plan_redispatch`` /
    ``pick_retry_target``), ``drop_handoff``/``delay_handoff`` hit the
    two-phase handoff path recovered by ``plan_handoff_recovery``.
    ``faults=None`` (the default) leaves every code path bit-identical
    to the fault-free model the golden envelopes pin."""

    def __init__(self, configs: Sequence[EngineConfig],
                 roles: Optional[Sequence[Optional[str]]] = None,
                 qos: Optional[QosPolicy] = None,
                 acceptance: Optional[AcceptanceModel] = None,
                 timing: Optional[TimingModel] = None,
                 seed: int = 0, record_events: bool = True,
                 handoff_s: float = 0.0,
                 faults: Optional[Sequence[Any]] = None,
                 retry_budget: int = 2,
                 handoff_timeout_s: float = 0.0,
                 request_deadline_s: float = 0.0,
                 brownout: Optional[
                     "scheduler_policy.BrownoutPolicy"] = None,
                 slo_targets: Optional[
                     Dict[str, Dict[str, float]]] = None):
        if not configs:
            raise ValueError("FleetModel needs at least one replica")
        if roles is not None:
            if len(roles) != len(configs):
                raise ValueError(
                    f"roles has {len(roles)} entries for "
                    f"{len(configs)} replicas")
            bad = [r for r in roles
                   if r is not None and r not in REPLICA_ROLES]
            if bad:
                raise ValueError(f"unknown replica roles {bad!r} "
                                 f"(choose from {REPLICA_ROLES})")
        self.engines = [
            EngineModel(c, qos=qos, acceptance=acceptance, timing=timing,
                        seed=seed + i, record_events=record_events,
                        brownout=brownout, slo_targets=slo_targets)
            for i, c in enumerate(configs)]
        # overload brownout: ONE broker-level controller over the whole
        # fleet (the live ClusterServing._brownout_eval twin) — engines
        # keep their per-replica goodput windows / alloc streaks / tick
        # trends but never self-evaluate; the fleet aggregates the
        # worst-case signals and pushes one shared level, so replicas
        # degrade and recover together
        self.brownout = brownout
        self._bstate = scheduler_policy.BrownoutState()
        self.brownout_transitions = 0
        self.brownout_max_level = 0
        for e in self.engines:
            e.brownout_managed = True
        self.roles = list(roles) if roles is not None else None
        self.handoff_s = float(handoff_s)
        self.handoffs = 0
        self.routed = [0] * len(configs)
        self._rr = 0
        self._seq = 0               # stable tiebreak for inbox ordering
        # per-replica pending deliveries: (available_t, seq, req, record)
        self._inbox: List[List[Tuple[float, int, Any, Any]]] = [
            [] for _ in configs]
        # -- crash tolerance (the live supervisor's virtual twin) -----
        self.injector = FaultInjector(faults) if faults else None
        self.retry_budget = int(retry_budget)
        self.handoff_timeout_s = float(handoff_timeout_s)
        self.request_deadline_s = float(request_deadline_s)
        self.dead = [False] * len(configs)
        self.replica_deaths = 0
        self.redispatched = 0
        self.handoff_timeouts = 0
        self.handoff_retries = 0
        self.dropped_handoffs = 0
        #: uri -> original trace Request (the sim's "unacked stream
        #: entry": what a redispatch re-reads to re-run from scratch)
        self._requests: Dict[str, Request] = {}
        #: uri -> pending two-phase handoff awaiting adoption ack
        self._pending_handoffs: Dict[str, Dict[str, Any]] = {}
        if self.roles is not None:
            for i, e in enumerate(self.engines):
                if self.roles[i] == "prefill":
                    e.handoff_cb = (lambda row, t, _i=i:
                                    self._handoff(_i, row, t))

    # -- routing --------------------------------------------------------

    def _signals(self, request=None) -> List[ReplicaSignals]:
        """Fabricate per-replica signals; when ``request`` carries a
        shared prefix, fill the per-request ``prefix_blocks`` rank
        input from each replica's tier residency — the sim's
        ``PrefixDirectory.match_depths`` (the live router fills it the
        same way, so ``route_request`` sees identical inputs)."""
        sigs = []
        for i, e in enumerate(self.engines):
            pb = 0
            if request is not None and getattr(request, "prefix_id", ""):
                cap = (min(int(request.prefix_len),
                           int(request.prompt_len) - 1)
                       // e.config.block_size)
                pb = min(e.prefix_resident_blocks(request.prefix_id),
                         max(0, cap))
            sigs.append(ReplicaSignals(
                replica=i, live=not self.dead[i],
                queue_depth=len(e._waiting) + e.n_active
                + len(self._inbox[i]),
                allocatable_blocks=(e._pool.allocatable()
                                    if e._pool is not None else None),
                role=(self.roles[i] if self.roles is not None
                      else None),
                prefix_blocks=pb))
        return sigs

    def _route(self, priority: Optional[str],
               phase: Optional[str], request=None) -> int:
        r = scheduler_policy.route_request(
            self._signals(request), priority=priority,
            rr_cursor=self._rr,
            phase=phase if self.roles is not None else None)
        self._rr = (self._rr + 1) % len(self.engines)
        if r is None:
            # only reachable with faults: every replica crashed.  The
            # live broker parks unrouted work; the sim treats a fully
            # dead fleet as a scenario bug and says so.
            raise RuntimeError(
                "sim fleet has no live replicas left to route to "
                "(fault schedule killed every replica?)")
        return r

    def _deliver(self, dst: int, available_t: float, req, record) -> None:
        self._seq += 1
        self._inbox[dst].append((available_t, self._seq, req, record))
        self._inbox[dst].sort(key=lambda e: (e[0], e[1]))

    def _handoff(self, src: int, row, t: float) -> None:
        """A prefill replica exported ``row`` at time ``t``: route the
        decode phase and deliver the adopted request ``handoff_s``
        later.  The router may pick the source itself (every decode
        replica saturated) — self-adoption, same as the live broker's
        fallback.

        With ``handoff_timeout_s > 0`` the delivery is two-phase: a
        pending entry holds the (req, record) pair — the sim twin of
        the source keeping the exported chain referenced — until the
        destination's adoption (``_drain_inbox``) acks it; the fault
        injector may drop or delay the delivery, and ``_fault_sweep``
        recovers un-acked entries via ``plan_handoff_recovery``."""
        req = row.req
        req.handoff = int(row.emitted)
        dst = self._route(req.priority, "decode")
        self.handoffs += 1
        record = self.engines[src].records[req.uri]
        if self.handoff_timeout_s > 0:
            self._pending_handoffs[req.uri] = {
                "req": req, "record": record, "src": src, "dst": dst,
                "sent_at": t, "retries": 0}
        delay = self.handoff_s
        if self.injector is not None:
            act = self.injector.handoff_action(t)
            if act is not None:
                kind, extra = act
                if kind == "drop" and self.handoff_timeout_s > 0:
                    # swallowed delivery: the pending entry stays;
                    # the ack-timeout sweep recovers the request
                    self.dropped_handoffs += 1
                    return
                if kind == "delay":
                    delay += extra
        self._deliver(dst, t + delay, req, record)

    # -- crash tolerance (virtual twin of server.py's _supervise) -------

    def _fault_sweep(self) -> None:
        """One pass of the supervisor's virtual twin: fire due
        ``crash_pump`` faults, then recover un-acked two-phase
        handoffs — the SAME pure policy calls the live router makes
        (``plan_handoff_recovery`` / ``pick_retry_target``)."""
        n = len(self.engines)
        for i in range(n):
            if not self.dead[i] and self.injector.due_crashes(
                    i, self.engines[i].now):
                self._crash_replica(i)
        if self.handoff_timeout_s <= 0 or not self._pending_handoffs:
            return
        now = max(e.now for e in self.engines)
        for uri in list(self._pending_handoffs):
            info = self._pending_handoffs.get(uri)
            if info is None:
                continue
            verdict = plan_handoff_recovery(
                age_s=now - info["sent_at"],
                timeout_s=self.handoff_timeout_s,
                retries=info["retries"],
                retry_budget=self.retry_budget)
            if verdict == "wait":
                continue
            self.handoff_timeouts += 1
            if verdict == "give_up":
                self._pending_handoffs.pop(uri, None)
                info["record"].dropped = "handoff_failed"
                continue
            r = pick_retry_target(
                self._signals(), info["req"].priority, self._rr,
                exclude=(info["dst"],),
                phase="decode" if self.roles is not None else None)
            if r is None:
                # nothing else eligible: back to any live replica
                # (the source itself is the live broker's last resort)
                r = self._route(info["req"].priority, "decode")
            info["retries"] += 1
            info["dst"] = r
            info["sent_at"] = now
            self.handoff_retries += 1
            self._deliver(r, now + self.handoff_s, info["req"],
                          info["record"])

    def _crash_replica(self, i: int) -> None:
        """An unplanned replica death at its own virtual ``now`` (the
        live path: InjectedFault escaping the pump loop → supervisor
        declare-dead): mark it dead, then re-dispatch every lost
        request — active rows, queued waiters, and undelivered inbox
        entries — through ``plan_redispatch``, bumping each record's
        ``attempts`` exactly like the live at-least-once recovery."""
        e = self.engines[i]
        t = e.now
        self.dead[i] = True
        self.replica_deaths += 1
        lost = []
        for s in range(len(e._slots)):
            row = e._slots[s]
            if row is None:
                continue
            e._slots[s] = None
            e._free.append(s)
            e._release_blocks(row)
            lost.append(row.req)
        while len(e._waiting):
            lost.append(e._waiting.popleft())
        inbox, self._inbox[i] = self._inbox[i], []
        for _avail, _seq, req, record in inbox:
            if record is None:
                # routed-but-undelivered arrival: the live router's
                # _reroute_dead — re-place, no attempt bump (the
                # request never started anywhere)
                dst = self._route(req.priority, "prefill", request=req)
                self._deliver(dst, max(_avail, t), req, None)
            elif req.uri in self._pending_handoffs:
                pass    # the ack-timeout sweep recovers it
            elif getattr(req, "handoff", None) is not None:
                # in-flight adoption with two-phase off: re-route the
                # decode leg directly to a survivor
                dst = self._route(req.priority, "decode")
                self.handoff_retries += 1
                self._deliver(dst, t + self.handoff_s, req, record)
            else:
                lost.append(req)
        for req in lost:
            rec = e.records.get(req.uri)
            if rec is None or rec.finished or rec.dropped:
                continue
            orig = self._requests.get(req.uri)
            deadline = (orig.deadline_s if orig is not None
                        and orig.deadline_s > 0
                        else self.request_deadline_s)
            verdict = plan_redispatch(
                attempt=rec.attempts, retry_budget=self.retry_budget,
                cancelled=False, age_s=t - rec.arrival,
                deadline_s=deadline)
            if verdict != "retry":
                rec.dropped = ("cancelled" if verdict == "cancel"
                               else "retry_budget")
                continue
            if orig is None:    # adopted row whose origin we never saw
                rec.dropped = "lost_entry"
                continue
            self._pending_handoffs.pop(req.uri, None)
            rec.attempts += 1
            self.redispatched += 1
            dst = self._route(orig.priority, "prefill", request=orig)
            self._deliver(dst, t, orig, rec)

    # -- overload brownout (broker controller twin) ---------------------

    def _brownout_sweep(self) -> None:
        """One shared-controller decision over aggregated worst-case
        fleet signals: min per-class windowed goodput, max backlog
        (engine queue + undelivered inbox), max alloc-fail streak, max
        per-replica tick trend — the same aggregation the live broker's
        ``_brownout_eval`` performs over its replicas."""
        live = [i for i in range(len(self.engines)) if not self.dead[i]]
        if not live:
            return
        goodput = {
            cls: min(self.engines[i].windowed_goodput()[cls]
                     for i in live)
            for cls in PRIORITIES}
        queue_depth = max(
            len(self.engines[i]._waiting) + len(self._inbox[i])
            for i in live)
        streak = max(self.engines[i]._alloc_streak for i in live)
        tick_means = [
            sum(self.engines[i]._tick_durs)
            / len(self.engines[i]._tick_durs)
            for i in live if self.engines[i]._tick_durs]
        prev = self._bstate
        self._bstate = scheduler_policy.plan_brownout(
            self.brownout, prev, goodput=goodput,
            queue_depth=queue_depth, alloc_fail_streak=streak,
            tick_s=max(tick_means) if tick_means else None)
        if self._bstate.level != prev.level:
            self.brownout_transitions += 1
            self.brownout_max_level = max(self.brownout_max_level,
                                          self._bstate.level)
            for i in live:
                self.engines[i].set_brownout(self._bstate.level)

    # -- driving --------------------------------------------------------

    def _drain_inbox(self, i: int) -> None:
        """Hand every delivery whose time has come to replica ``i``'s
        waiting queue.  An ACTIVE replica only sees deliveries at/behind
        its own clock (a future handoff cannot jump the queue); an idle
        one fast-forwards in ``run``."""
        e = self.engines[i]
        box = self._inbox[i]
        while box and box[0][0] <= e.now:
            _, _, req, record = box.pop(0)
            if record is None:
                e.submit(req)
            elif getattr(req, "handoff", None) is not None:
                e.submit_prefilled(req, record)
                # adoption IS the ack: release the source-side pending
                # entry (the live engine's on_adopt callback)
                self._pending_handoffs.pop(req.uri, None)
            else:
                # crash-recovery redispatch: full re-run on a survivor,
                # lifecycle record continued
                e.submit_retry(req, record)

    def _has_work(self, i: int) -> bool:
        if self.dead[i]:
            return False
        e = self.engines[i]
        return e.n_active > 0 or len(e._waiting) > 0

    def run(self, trace: Sequence[Request],
            max_ticks: Optional[int] = None) -> Dict[str, Any]:
        """Feed ``trace`` through the routed fleet until every request
        finishes or drops; returns the merged per-request records."""
        pending = sorted(trace, key=lambda r: (r.arrival_t, r.uri))
        guard = max_ticks if max_ticks is not None else 20_000_000
        p = 0
        n = len(self.engines)
        while True:
            # 0. fault sweep: due crashes + un-acked handoff recovery
            if self.injector is not None:
                self._fault_sweep()
            # 1. route arrivals due at/before the busiest frontier (or
            #    all remaining ones once the fleet has gone idle)
            busy_now = [self.engines[i].now for i in range(n)
                        if self._has_work(i)]
            frontier = min(busy_now) if busy_now else None
            while p < len(pending) and (
                    frontier is None
                    or pending[p].arrival_t <= frontier):
                r = pending[p]
                self._requests[r.uri] = r
                # arrivals route prefix-locality-aware (handoffs stay
                # locality-blind, like the live broker's rebalance)
                dst = self._route(r.priority, "prefill", request=r)
                self.routed[dst] += 1
                self._deliver(dst, r.arrival_t, r, None)
                p += 1
                if frontier is None:
                    break       # idle fleet: one arrival re-busies it
            # 2. deliver matured inbox entries; fast-forward idle
            #    replicas to their next delivery
            for i in range(n):
                if self.dead[i]:
                    continue
                e = self.engines[i]
                if (not self._has_work(i)) and self._inbox[i]:
                    e.now = max(e.now, self._inbox[i][0][0])
                self._drain_inbox(i)
            # 3. step the lagging busy replica
            work = [i for i in range(n) if self._has_work(i)]
            if not work:
                if p < len(pending) or any(self._inbox[i]
                                           for i in range(n)):
                    continue    # future arrivals/deliveries remain
                if self._pending_handoffs and self.handoff_timeout_s > 0:
                    # idle fleet with un-acked handoffs (a dropped
                    # delivery): fast-forward virtual time to the
                    # earliest ack deadline so the recovery sweep
                    # fires instead of stranding the request
                    t_next = min(h["sent_at"] + self.handoff_timeout_s
                                 for h in self._pending_handoffs.values())
                    for i in range(n):
                        if not self.dead[i]:
                            self.engines[i].now = max(
                                self.engines[i].now, t_next + 1e-9)
                    continue
                break
            i = min(work, key=lambda j: (self.engines[j].now, j))
            self.engines[i].step()
            if self.brownout is not None:
                self._brownout_sweep()
            if sum(e.ticks for e in self.engines) >= guard:
                raise RuntimeError(
                    f"fleet simulation exceeded {guard} ticks "
                    f"(arrival rate beyond modelled capacity?)")
        return self.records

    # -- results --------------------------------------------------------

    @property
    def records(self) -> Dict[str, Any]:
        """Merged per-request records.  A handed-off request's record
        OBJECT is shared between source and destination replicas, so
        the union has exactly one entry per uri."""
        out: Dict[str, Any] = {}
        for e in self.engines:
            out.update(e.records)
        return out

    def summary(self, targets: Optional[Dict[str, Dict[str, float]]]
                = None) -> Dict[str, Any]:
        out = summarize(self.records, targets)
        out["ticks"] = sum(e.ticks for e in self.engines)
        out["preemptions"] = sum(e.preemptions for e in self.engines)
        out["prefill_stall_ticks"] = sum(e.prefill_stall_ticks
                                         for e in self.engines)
        out["handoffs"] = self.handoffs
        out["handoffs_adopted"] = sum(e.handoffs_in
                                      for e in self.engines)
        out["routed"] = list(self.routed)
        out["per_replica_ticks"] = [e.ticks for e in self.engines]
        if self.injector is not None:
            # chaos counters, present only when a fault schedule is
            # configured — fault-free summaries stay key-identical to
            # previous releases (golden envelopes pin on them)
            recs = list(self.records.values())
            out["replica_deaths"] = self.replica_deaths
            out["redispatched"] = self.redispatched
            out["handoff_timeouts"] = self.handoff_timeouts
            out["handoff_retries"] = self.handoff_retries
            out["dropped_handoffs"] = self.dropped_handoffs
            out["max_attempts"] = max(
                [r.attempts for r in recs] or [1])
            # the gate's zero-stranded contract: every request reached
            # a terminal state (finished or an explicit drop reason)
            out["stranded"] = sum(1 for r in recs
                                  if not r.finished and not r.dropped)
        if self.brownout is not None:
            # brownout counters, present only when the ladder is
            # configured — brownout-off summaries stay key-identical
            # to previous releases (golden envelopes pin on them)
            out["brownout_sheds"] = sum(e.brownout_sheds
                                        for e in self.engines)
            out["brownout_max_level"] = self.brownout_max_level
            out["brownout_final_level"] = self._bstate.level
            out["brownout_transitions"] = self.brownout_transitions
        if (self.brownout is not None
                or any(e.deadline_seen for e in self.engines)):
            out["deadline_sheds"] = sum(e.deadline_sheds
                                        for e in self.engines)
        if any(e._prefix_on for e in self.engines):
            # tiered-KV sums, present only when a replica runs the
            # tier — tier-off summaries stay key-identical to previous
            # releases (golden envelopes pin on them)
            out["kv_spills"] = sum(e.kv_spills for e in self.engines)
            out["kv_readmits"] = sum(e.kv_readmits
                                     for e in self.engines)
            out["kv_readmit_tokens_saved"] = sum(
                e.kv_readmit_tokens_saved for e in self.engines)
            out["recompute_tokens_saved"] = sum(
                e.recompute_tokens_saved for e in self.engines)
        return out

"""Discrete-event model of a disaggregated serving FLEET.

``FleetModel`` wraps N ``EngineModel`` replicas behind the REAL router
policy — every placement decision is ``policy.route_request`` on
fabricated ``ReplicaSignals``, the same pure function and the same
rank tuple the live ``ClusterServing`` router evaluates — and models
the prefill/decode KV-handoff path (docs/serving_memory.md):

* arrivals route with ``phase="prefill"`` (when roles are configured),
  so prefill-heavy replicas take new prompts first; when replicas run
  the tiered KV model (``EngineConfig.prefix_cache_blocks``), each
  arrival's ``ReplicaSignals.prefix_blocks`` is filled from per-replica
  tier residency — the sim's ``PrefixDirectory`` — so the same
  locality rank term steers repeat prefixes back to their KV;
* a prefill replica exports a row at its FIRST token
  (``EngineModel.handoff_cb`` — the sim's
  ``ContinuousEngine._handoff_slot``), the fleet routes the handoff
  with ``phase="decode"`` and delivers it ``handoff_s`` later (the
  modelled chain-snapshot + KV-slice copy cost);
* the decode replica adopts via ``EngineModel.submit_prefilled`` —
  straight into DECODE, first token not re-emitted, lifecycle record
  continued (TTFT observed from the ORIGINAL arrival, exactly like
  the live telemetry).

Clocks: each replica keeps its own virtual ``now`` (they tick
independently, like real pump threads); the fleet driver always steps
the busiest-lagging replica (minimum ``now`` among those with work)
and fast-forwards an IDLE replica to its next delivery, mirroring the
serving pump's idle wait.  No wall clock, index-ordered iteration,
one seeded RNG per replica — byte-identical runs for the same
(configs, trace, seed), which is what lets ``make sim-gate`` pin a
disaggregated scenario's envelopes.
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import policy as scheduler_policy
from ..policy import REPLICA_ROLES, QosPolicy, ReplicaSignals
from .model import (AcceptanceModel, EngineConfig, EngineModel,
                    TimingModel, summarize)
from .trace import Request

__all__ = ["FleetModel"]


class FleetModel:
    """N modelled replicas + the real routing policy + KV handoff."""

    def __init__(self, configs: Sequence[EngineConfig],
                 roles: Optional[Sequence[Optional[str]]] = None,
                 qos: Optional[QosPolicy] = None,
                 acceptance: Optional[AcceptanceModel] = None,
                 timing: Optional[TimingModel] = None,
                 seed: int = 0, record_events: bool = True,
                 handoff_s: float = 0.0):
        if not configs:
            raise ValueError("FleetModel needs at least one replica")
        if roles is not None:
            if len(roles) != len(configs):
                raise ValueError(
                    f"roles has {len(roles)} entries for "
                    f"{len(configs)} replicas")
            bad = [r for r in roles
                   if r is not None and r not in REPLICA_ROLES]
            if bad:
                raise ValueError(f"unknown replica roles {bad!r} "
                                 f"(choose from {REPLICA_ROLES})")
        self.engines = [
            EngineModel(c, qos=qos, acceptance=acceptance, timing=timing,
                        seed=seed + i, record_events=record_events)
            for i, c in enumerate(configs)]
        self.roles = list(roles) if roles is not None else None
        self.handoff_s = float(handoff_s)
        self.handoffs = 0
        self.routed = [0] * len(configs)
        self._rr = 0
        self._seq = 0               # stable tiebreak for inbox ordering
        # per-replica pending deliveries: (available_t, seq, req, record)
        self._inbox: List[List[Tuple[float, int, Any, Any]]] = [
            [] for _ in configs]
        if self.roles is not None:
            for i, e in enumerate(self.engines):
                if self.roles[i] == "prefill":
                    e.handoff_cb = (lambda row, t, _i=i:
                                    self._handoff(_i, row, t))

    # -- routing --------------------------------------------------------

    def _signals(self, request=None) -> List[ReplicaSignals]:
        """Fabricate per-replica signals; when ``request`` carries a
        shared prefix, fill the per-request ``prefix_blocks`` rank
        input from each replica's tier residency — the sim's
        ``PrefixDirectory.match_depths`` (the live router fills it the
        same way, so ``route_request`` sees identical inputs)."""
        sigs = []
        for i, e in enumerate(self.engines):
            pb = 0
            if request is not None and getattr(request, "prefix_id", ""):
                cap = (min(int(request.prefix_len),
                           int(request.prompt_len) - 1)
                       // e.config.block_size)
                pb = min(e.prefix_resident_blocks(request.prefix_id),
                         max(0, cap))
            sigs.append(ReplicaSignals(
                replica=i, live=True,
                queue_depth=len(e._waiting) + e.n_active
                + len(self._inbox[i]),
                allocatable_blocks=(e._pool.allocatable()
                                    if e._pool is not None else None),
                role=(self.roles[i] if self.roles is not None
                      else None),
                prefix_blocks=pb))
        return sigs

    def _route(self, priority: Optional[str],
               phase: Optional[str], request=None) -> int:
        r = scheduler_policy.route_request(
            self._signals(request), priority=priority,
            rr_cursor=self._rr,
            phase=phase if self.roles is not None else None)
        self._rr = (self._rr + 1) % len(self.engines)
        return r

    def _deliver(self, dst: int, available_t: float, req, record) -> None:
        self._seq += 1
        self._inbox[dst].append((available_t, self._seq, req, record))
        self._inbox[dst].sort(key=lambda e: (e[0], e[1]))

    def _handoff(self, src: int, row, t: float) -> None:
        """A prefill replica exported ``row`` at time ``t``: route the
        decode phase and deliver the adopted request ``handoff_s``
        later.  The router may pick the source itself (every decode
        replica saturated) — self-adoption, same as the live broker's
        fallback."""
        req = row.req
        req.handoff = int(row.emitted)
        dst = self._route(req.priority, "decode")
        self.handoffs += 1
        self._deliver(dst, t + self.handoff_s, req,
                      self.engines[src].records[req.uri])

    # -- driving --------------------------------------------------------

    def _drain_inbox(self, i: int) -> None:
        """Hand every delivery whose time has come to replica ``i``'s
        waiting queue.  An ACTIVE replica only sees deliveries at/behind
        its own clock (a future handoff cannot jump the queue); an idle
        one fast-forwards in ``run``."""
        e = self.engines[i]
        box = self._inbox[i]
        while box and box[0][0] <= e.now:
            _, _, req, record = box.pop(0)
            if record is None:
                e.submit(req)
            else:
                e.submit_prefilled(req, record)

    def _has_work(self, i: int) -> bool:
        e = self.engines[i]
        return e.n_active > 0 or len(e._waiting) > 0

    def run(self, trace: Sequence[Request],
            max_ticks: Optional[int] = None) -> Dict[str, Any]:
        """Feed ``trace`` through the routed fleet until every request
        finishes or drops; returns the merged per-request records."""
        pending = sorted(trace, key=lambda r: (r.arrival_t, r.uri))
        guard = max_ticks if max_ticks is not None else 20_000_000
        p = 0
        n = len(self.engines)
        while True:
            # 1. route arrivals due at/before the busiest frontier (or
            #    all remaining ones once the fleet has gone idle)
            busy_now = [self.engines[i].now for i in range(n)
                        if self._has_work(i)]
            frontier = min(busy_now) if busy_now else None
            while p < len(pending) and (
                    frontier is None
                    or pending[p].arrival_t <= frontier):
                r = pending[p]
                # arrivals route prefix-locality-aware (handoffs stay
                # locality-blind, like the live broker's rebalance)
                dst = self._route(r.priority, "prefill", request=r)
                self.routed[dst] += 1
                self._deliver(dst, r.arrival_t, r, None)
                p += 1
                if frontier is None:
                    break       # idle fleet: one arrival re-busies it
            # 2. deliver matured inbox entries; fast-forward idle
            #    replicas to their next delivery
            for i in range(n):
                e = self.engines[i]
                if (not self._has_work(i)) and self._inbox[i]:
                    e.now = max(e.now, self._inbox[i][0][0])
                self._drain_inbox(i)
            # 3. step the lagging busy replica
            work = [i for i in range(n) if self._has_work(i)]
            if not work:
                if p < len(pending) or any(self._inbox[i]
                                           for i in range(n)):
                    continue    # future arrivals/deliveries remain
                break
            i = min(work, key=lambda j: (self.engines[j].now, j))
            self.engines[i].step()
            if sum(e.ticks for e in self.engines) >= guard:
                raise RuntimeError(
                    f"fleet simulation exceeded {guard} ticks "
                    f"(arrival rate beyond modelled capacity?)")
        return self.records

    # -- results --------------------------------------------------------

    @property
    def records(self) -> Dict[str, Any]:
        """Merged per-request records.  A handed-off request's record
        OBJECT is shared between source and destination replicas, so
        the union has exactly one entry per uri."""
        out: Dict[str, Any] = {}
        for e in self.engines:
            out.update(e.records)
        return out

    def summary(self, targets: Optional[Dict[str, Dict[str, float]]]
                = None) -> Dict[str, Any]:
        out = summarize(self.records, targets)
        out["ticks"] = sum(e.ticks for e in self.engines)
        out["preemptions"] = sum(e.preemptions for e in self.engines)
        out["prefill_stall_ticks"] = sum(e.prefill_stall_ticks
                                         for e in self.engines)
        out["handoffs"] = self.handoffs
        out["handoffs_adopted"] = sum(e.handoffs_in
                                      for e in self.engines)
        out["routed"] = list(self.routed)
        out["per_replica_ticks"] = [e.ticks for e in self.engines]
        if any(e._prefix_on for e in self.engines):
            # tiered-KV sums, present only when a replica runs the
            # tier — tier-off summaries stay key-identical to previous
            # releases (golden envelopes pin on them)
            out["kv_spills"] = sum(e.kv_spills for e in self.engines)
            out["kv_readmits"] = sum(e.kv_readmits
                                     for e in self.engines)
            out["kv_readmit_tokens_saved"] = sum(
                e.kv_readmit_tokens_saved for e in self.engines)
            out["recompute_tokens_saved"] = sum(
                e.recompute_tokens_saved for e in self.engines)
        return out

"""Discrete-event model of the continuous-batching serving engine.

``EngineModel`` mirrors ``ContinuousEngine._step_impl``'s scheduling
skeleton — admission, the chunked/spec/decode tick dispatch, token-
budget billing, paged block accounting with pool-dry preemption, QoS
weighted admission — while replacing the device with a timing model and
the LM with a completion-length oracle (``Request.gen_len``) plus a
calibrated stochastic acceptance process for speculative rounds.

Every scheduling DECISION is made by the same pure functions the real
engine calls (``serving/policy.py``): ``grant_rank`` orders prefill
grants, ``plan_chunks`` bills the token budget, ``pick_victim`` chooses
preemptions, and ``WeightedWaitQueue`` (driven by the model's virtual
clock) orders QoS admission.  ``tests/test_sim.py`` pins decision-
sequence equivalence against the live engine.

What is modelled exactly (same code path, same order):

* chunked admission (``_admit_chunked``): pop-while-free-slots, paged
  dry/blocked/error gates, front-requeue on blocked;
* tick dispatch: spec_chunked / spec / chunked / plain decode, chosen
  by the same predicate as ``_step_impl``;
* budget billing and stall accounting (``plan_chunks``);
* paged growth per tick (``_grow_chunk_blocks`` / ``_ensure_blocks``)
  with latest-admission-prefilling-first preemption, lockstep draft
  pool, front requeue, discard-partial-tokens semantics;
* end-of-tick re-admission (freed slots recycle on the same iteration).

What is approximated (documented in docs/simulation.md):

* the prefix cache is modelled at prefix-ID granularity, not block
  hashes: ``prefix_cache_blocks`` reserves a device-tier LRU region
  (outside ``n_blocks``) and ``host_store_blocks`` a host-tier LRU
  behind it; a tagged request (``Request.prefix_id``) matches its
  shared prefix's resident depth, reducing both its block need and —
  in chunked mode — its prefill work (``fill_pos`` starts past the
  matched blocks).  Residency publishes at admission, not at fill
  completion.  Both knobs default 0 = the historical no-prefix-cache
  model, bit-identical event logs included;
* non-chunked admission prefills monolithically at admission time and
  emits the first token there (the engine's grouped-prefill batching
  is a latency detail below the model's resolution);
* all of a tick's token emissions are stamped at the tick's END (the
  engine stamps them mid-tick, inside the device-call span);
* tick duration comes from ``TimingModel`` (affine in billed tokens),
  not a device.

Virtual time only: ``EngineModel.now`` starts at 0 and advances by
modelled tick durations.  No wall clock, no hash-order iteration, one
``random.Random(seed)`` — two runs of the same (config, trace, seed)
produce byte-identical event logs (``event_log_lines``).
"""

import json
import math
import random
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import policy as scheduler_policy
from ..policy import PRIORITIES, QosPolicy, WeightedWaitQueue
from .trace import Request

__all__ = ["AcceptanceModel", "EngineConfig", "EngineModel",
           "TimingModel", "percentile", "summarize",
           "DEFAULT_SLO_TARGETS"]

#: Mirror of serving/flight.py::DEFAULT_SLO_TARGETS (seconds).  The sim
#: cannot import flight.py (numpy); tests/test_sim.py pins the two
#: tables equal so they cannot drift apart silently.
DEFAULT_SLO_TARGETS: Dict[str, Dict[str, float]] = {
    "interactive": {"ttft": 1.0, "tpot": 0.25, "queue_wait": 0.5},
    "standard": {"ttft": 2.5, "tpot": 0.5, "queue_wait": 2.0},
    "batch": {"ttft": 10.0, "tpot": 2.0, "queue_wait": 30.0},
}


def _t(x: float) -> float:
    """Stable float for event logs: fixed 9-dp rounding."""
    return round(float(x), 9)


# ---------------------------------------------------------------------------
# calibrated sub-models
# ---------------------------------------------------------------------------

class TimingModel:
    """Tick duration, affine in billed tokens:
    ``dur_s = base_s + per_token_s * tokens``.

    ``fit`` calibrates from a bundle's tick records (least squares of
    ``dur_ms`` against billed tokens), so a replayed bundle runs on the
    recorded machine's measured speed rather than a guess."""

    def __init__(self, base_s: float = 0.002,
                 per_token_s: float = 0.00005):
        self.base_s = float(base_s)
        self.per_token_s = float(per_token_s)

    def tick_s(self, tokens: int) -> float:
        return self.base_s + self.per_token_s * max(0, int(tokens))

    def to_dict(self) -> Dict[str, float]:
        return {"base_s": self.base_s, "per_token_s": self.per_token_s}

    @classmethod
    def fit(cls, samples: Sequence[Tuple[int, float]],
            default: Optional["TimingModel"] = None) -> "TimingModel":
        """Least-squares fit of ``(tokens, dur_s)`` samples; degenerate
        inputs (no samples, constant x) fall back to the mean duration
        as ``base_s`` (or ``default`` when there are no samples)."""
        samples = [(int(n), float(d)) for n, d in samples if d >= 0]
        if not samples:
            return default or cls()
        n = len(samples)
        mx = sum(s[0] for s in samples) / n
        my = sum(s[1] for s in samples) / n
        sxx = sum((s[0] - mx) ** 2 for s in samples)
        if sxx <= 0:
            return cls(base_s=my, per_token_s=0.0)
        slope = sum((s[0] - mx) * (s[1] - my) for s in samples) / sxx
        base = my - slope * mx
        if slope < 0 or base < 0:
            # noisy small bundles can fit a negative slope/intercept;
            # clamp to the physically meaningful constant model
            return cls(base_s=max(my, 0.0), per_token_s=0.0)
        return cls(base_s=base, per_token_s=slope)


class AcceptanceModel:
    """Speculative acceptance-length distribution: P(accept_len = a)
    for ``a`` in ``0..k``, sampled per decode row per spec round.

    ``from_counts`` calibrates from the engine's recorded exact counts
    (the ``spec_acceptance`` bundle section / histogram satellite);
    ``constant`` gives a degenerate distribution for what-if sweeps."""

    def __init__(self, k: int, pmf: Sequence[float]):
        if k < 0:
            raise ValueError("k must be >= 0")
        if len(pmf) != k + 1:
            raise ValueError(f"pmf needs k+1={k + 1} entries, "
                             f"got {len(pmf)}")
        total = float(sum(pmf))
        if total <= 0:
            raise ValueError("pmf must have positive mass")
        self.k = int(k)
        self.pmf = [float(p) / total for p in pmf]
        self._cdf = []
        acc = 0.0
        for p in self.pmf:
            acc += p
            self._cdf.append(acc)

    @classmethod
    def from_counts(cls, counts: Dict[Any, int], k: int) -> "AcceptanceModel":
        pmf = [0.0] * (k + 1)
        for key, v in counts.items():
            a = int(key)
            if 0 <= a <= k:
                pmf[a] += int(v)
        if sum(pmf) <= 0:
            return cls.constant(k, k)
        return cls(k, pmf)

    @classmethod
    def constant(cls, accept_len: int, k: int) -> "AcceptanceModel":
        pmf = [0.0] * (k + 1)
        pmf[max(0, min(int(accept_len), k))] = 1.0
        return cls(k, pmf)

    @property
    def mean(self) -> float:
        return sum(a * p for a, p in enumerate(self.pmf))

    def sample(self, rng: random.Random) -> int:
        x = rng.random()
        for a, c in enumerate(self._cdf):
            if x < c:
                return a
        return self.k


# ---------------------------------------------------------------------------
# engine configuration
# ---------------------------------------------------------------------------

@dataclass
class EngineConfig:
    """The scheduling-relevant subset of ``ContinuousEngine``'s
    constructor knobs.  Derived quantities (default token budget, chunk
    buckets, paged caps) reproduce the engine's formulas exactly."""

    slots: int = 8
    max_new_tokens: int = 32
    ticks_per_step: int = 1
    prompt_buckets: Tuple[int, ...] = (16, 32, 64, 128)
    chunked: bool = False
    tick_token_budget: Optional[int] = None
    paged: bool = False
    block_size: int = 16
    n_blocks: Optional[int] = None
    draft_n_blocks: Optional[int] = None
    spec_k: int = 0             # 0 = no draft model
    # tiered KV memory (serving/kv_store.py): a device-tier prefix
    # cache of ``prefix_cache_blocks`` blocks (reserved OUTSIDE
    # ``n_blocks`` — pool pressure and prefix residency are separate
    # modelled choices) with an optional ``host_store_blocks`` host
    # tier behind it.  0/0 = tier off, the historical model.
    prefix_cache_blocks: int = 0
    host_store_blocks: int = 0

    def __post_init__(self):
        self.prompt_buckets = tuple(sorted(int(b)
                                           for b in self.prompt_buckets))
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        if self.paged and self.n_blocks is None:
            # engine default: enough blocks for every slot's full
            # context is the caller's job; the sim wants an explicit
            # number so pool pressure is a modelled choice
            raise ValueError("paged=True needs n_blocks")
        if self.spec_k > 0 and self.paged and self.draft_n_blocks is None:
            self.draft_n_blocks = self.n_blocks
        if self.prefix_cache_blocks < 0 or self.host_store_blocks < 0:
            raise ValueError("tier sizes must be >= 0")
        if self.prefix_cache_blocks > 0 and not self.paged:
            raise ValueError("prefix_cache_blocks needs paged=True")
        if self.host_store_blocks > 0 and self.prefix_cache_blocks <= 0:
            # the host tier is fed by device-tier evictions; without a
            # device tier nothing ever spills into it
            raise ValueError(
                "host_store_blocks needs prefix_cache_blocks > 0")
        if self.prefix_cache_blocks > 0 and self.spec_k > 0:
            # mirror of ContinuousEngine: the tiered store refuses a
            # draft model (two pool tenants in lockstep don't compose
            # with shared-block offsets)
            raise ValueError(
                "prefix_cache_blocks does not compose with spec_k > 0")
        if self.chunked:
            per_row = self.spec_k + 1 if self.spec_k > 0 else 1
            if self.tick_token_budget is None:
                # ContinuousEngine's default budget formula
                budget = max(self.prompt_buckets[0] + per_row * self.slots,
                             2 * per_row * self.slots)
                if self.paged:
                    budget = max(budget, self.block_size)
                self.tick_token_budget = budget
            if self.tick_token_budget < self.prompt_buckets[0]:
                raise ValueError(
                    f"tick_token_budget {self.tick_token_budget} below "
                    f"the smallest prompt bucket "
                    f"{self.prompt_buckets[0]}")
            if self.paged and self.tick_token_budget < self.block_size:
                raise ValueError(
                    f"tick_token_budget {self.tick_token_budget} below "
                    f"block_size {self.block_size}")

    @property
    def chunk_buckets(self) -> Tuple[int, ...]:
        return tuple(b for b in self.prompt_buckets
                     if b <= (self.tick_token_budget or 0))

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EngineConfig":
        known = {f for f in cls.__dataclass_fields__}  # noqa: C401
        return cls(**{k: v for k, v in d.items() if k in known})


class _Pool:
    """Counter model of ``paged_cache.BlockPool``: block 0 is the sink,
    ``n_blocks - 1`` usable blocks, no prefix cache (so ``allocatable``
    is just the free count)."""

    def __init__(self, n_blocks: int):
        self.n_blocks = int(n_blocks)
        self.free = self.n_blocks - 1
        self.alloc_failures = 0

    def allocatable(self) -> int:
        return self.free

    def allocate(self) -> bool:
        if self.free <= 0:
            self.alloc_failures += 1
            return False
        self.free -= 1
        return True

    def release(self, n: int) -> None:
        self.free += int(n)


class _Row:
    """A resident slot: the sim's ``_Slot``."""

    __slots__ = ("req", "state", "fill_pos", "emitted", "admit_seq",
                 "blocks", "shared", "gen_len")

    def __init__(self, req: "_SimReq", state: str, admit_seq: int):
        self.req = req
        self.state = state
        self.fill_pos = 0
        self.emitted = 0
        self.admit_seq = admit_seq
        self.blocks = 0         # both tenants grow in lockstep
        # blocks served by the prefix-cache tier, NOT held from the
        # pool: growth targets subtract these and release ignores them
        self.shared = 0
        self.gen_len = req.gen_len

    @property
    def pos(self) -> int:
        """Next write position (engine ``_pos``).  The engine parks a
        fresh decode row at ``prompt_len`` with its first token already
        emitted — the token's K/V lands at ``prompt_len`` on the *next*
        forward pass — so a decode row's next write is
        ``prompt_len + emitted - 1``, not ``+ emitted``."""
        return self.req.prompt_len + max(0, self.emitted - 1)


class _SimReq:
    """Queue entry: carries the attributes ``WeightedWaitQueue`` reads
    (``priority`` / ``tenant`` / ``enq_t``) plus the request body.
    Deliberately a plain mutable object — the queue keys refunds by
    ``id()`` like the engine's ``_Req``."""

    __slots__ = ("uri", "prompt_len", "gen_len", "priority", "tenant",
                 "enq_t", "handoff", "prefix_id", "prefix_len",
                 "deadline_t")

    def __init__(self, r: Request, max_new_tokens: int):
        self.uri = r.uri
        self.prompt_len = int(r.prompt_len)
        self.prefix_id = r.prefix_id
        self.prefix_len = int(r.prefix_len)
        self.gen_len = max(1, min(int(r.gen_len), max_new_tokens))
        self.priority = r.priority if r.priority in PRIORITIES \
            else "standard"
        self.tenant = r.tenant
        self.enq_t = float(r.arrival_t)
        # absolute virtual-time deadline (the live wire carries an
        # absolute wall-clock ms; decode_deadline turns it into the
        # consumer's clock — here that clock is the model's ``now``).
        # 0 = none; WeightedWaitQueue EDF-ranks on this attribute.
        self.deadline_t = (self.enq_t + float(r.deadline_s)
                           if float(getattr(r, "deadline_s", 0.0)) > 0
                           else 0.0)
        # tokens already emitted on a prefill replica; None for a plain
        # request.  Set by FleetModel's handoff path — an adopted
        # request admits straight into DECODE (``_admit_adopted``), and
        # a preempted adopted row re-adopts from this same immutable
        # state, exactly like the engine's requeued handoff ``_Req``.
        self.handoff: Optional[int] = None


@dataclass
class _Record:
    """Per-request lifecycle record, mirroring what telemetry's trace
    events expose: every admission epoch observes queue-wait from the
    ORIGINAL arrival, every first token observes TTFT from the original
    arrival (the engine re-stamps both after preemption)."""

    uri: str
    priority: str
    tenant: str
    arrival: float
    admits: List[float] = field(default_factory=list)
    queue_waits: List[float] = field(default_factory=list)
    first_tokens: List[float] = field(default_factory=list)
    preempts: int = 0
    finish_t: Optional[float] = None
    tokens: int = 0
    dropped: Optional[str] = None
    #: Total placements (first submit = 1).  Bumped by the fleet's
    #: crash-recovery redispatch — the sim twin of the live router's
    #: per-request ``attempt`` counter.
    attempts: int = 1

    @property
    def finished(self) -> bool:
        return self.finish_t is not None

    @property
    def ttfts(self) -> List[float]:
        return [ft - self.arrival for ft in self.first_tokens]

    @property
    def tpot(self) -> Optional[float]:
        if not self.finished or self.tokens < 2 or not self.first_tokens:
            return None
        return (self.finish_t - self.first_tokens[-1]) / (self.tokens - 1)


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------

class EngineModel:
    """Virtual-time discrete-event model of ``ContinuousEngine``.

    ``run(trace)`` feeds a sorted request list through the modelled
    engine and returns the per-request records; ``summarize`` turns
    records into per-class latency/goodput tables.  ``record_events``
    keeps the per-tick decision log (admissions, grants, preemptions) —
    turn it off for million-request sweeps where only the summary
    matters."""

    def __init__(self, config: EngineConfig,
                 qos: Optional[QosPolicy] = None,
                 acceptance: Optional[AcceptanceModel] = None,
                 timing: Optional[TimingModel] = None,
                 seed: int = 0, record_events: bool = True,
                 brownout: Optional["scheduler_policy.BrownoutPolicy"]
                 = None,
                 slo_targets: Optional[Dict[str, Dict[str, float]]]
                 = None):
        self.config = config
        self.qos = qos
        self.timing = timing or TimingModel()
        self.rng = random.Random(seed)
        self.seed = int(seed)
        self.record_events = bool(record_events)
        if config.spec_k > 0:
            self.acceptance = acceptance or AcceptanceModel.constant(
                config.spec_k, config.spec_k)
            if self.acceptance.k != config.spec_k:
                raise ValueError(
                    f"acceptance model k={self.acceptance.k} != "
                    f"config spec_k={config.spec_k}")
        else:
            self.acceptance = None

        self.now = 0.0
        S = config.slots
        self._slots: List[Optional[_Row]] = [None] * S
        self._free: deque = deque(range(S))
        self._admit_seq = 0
        self._waiting = (WeightedWaitQueue(qos, clock=lambda: self.now)
                         if qos is not None else deque())
        self._pool = _Pool(config.n_blocks) if config.paged else None
        self._dpool = (_Pool(config.draft_n_blocks)
                       if config.paged and config.spec_k > 0 else None)
        # tiered KV memory: LRU residency at prefix-ID granularity,
        # prefix_id -> resident blocks (see _prefix_admit)
        self._prefix_on = config.paged and config.prefix_cache_blocks > 0
        self._dev_prefix: "OrderedDict[str, int]" = OrderedDict()
        self._host_prefix: "OrderedDict[str, int]" = OrderedDict()
        self.kv_spills = 0
        self.kv_readmits = 0
        self.kv_readmit_tokens_saved = 0
        self.recompute_tokens_saved = 0

        # overload brownout (policy.plan_brownout — the SAME pure
        # controller the live broker runs).  ``brownout=None`` (the
        # default) leaves every code path bit-identical to the
        # pre-brownout model the golden envelopes pin.  A standalone
        # model evaluates the controller itself each tick; FleetModel
        # flips ``brownout_managed`` and pushes fleet-wide levels via
        # ``set_brownout`` instead (the sim's broker-side controller).
        self.brownout = brownout
        self.brownout_managed = False
        self.slo_targets = slo_targets or DEFAULT_SLO_TARGETS
        self._bstate = scheduler_policy.BrownoutState()
        self._goodput_win: Dict[str, deque] = {
            c: deque(maxlen=32) for c in PRIORITIES}
        self._tick_durs: deque = deque(maxlen=8)
        self._alloc_streak = 0
        self._spec_on = True
        self.brownout_sheds = 0
        self.brownout_max_level = 0
        self.brownout_transitions = 0
        self.deadline_sheds = 0
        self.deadline_seen = False

        self.records: Dict[str, _Record] = {}
        self.events: List[Dict[str, Any]] = []
        self.ticks = 0
        # prefill/decode disaggregation (sim/fleet.py): a fleet sets
        # ``handoff_cb`` on its prefill replicas; a row then exports at
        # its first token instead of decoding here.  ``None`` (the
        # default) leaves every code path bit-identical to the
        # single-engine model the determinism tests pin.
        self.handoff_cb = None
        self.handoffs_out = 0
        self.handoffs_in = 0
        self.preemptions = 0
        self.prefill_preemptions = 0
        self.prefill_stall_ticks = 0
        self.budget_ticks = 0
        self.budget_tokens_used = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        # scratch decision lists, reset per tick, flushed to the event
        # log — lets the tick event carry what THIS tick decided
        self._ev_admitted: List[str] = []
        self._ev_preempted: List[str] = []
        self._ev_chunks: List[Tuple[str, int]] = []
        self._ev_dropped: List[str] = []
        # (row, n) emissions decided during a tick, landed at its end
        self._pending_emits: List[Tuple[_Row, int]] = []

    # -- bookkeeping ----------------------------------------------------

    @property
    def n_active(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def _emit(self, kind: str, **kw) -> None:
        if self.record_events:
            ev = {"event": kind, "t": _t(self.now)}
            ev.update(kw)
            self.events.append(ev)

    def event_log_lines(self) -> List[str]:
        """Canonical event-log serialization: one sorted-key compact
        JSON object per line.  Byte-identical across runs of the same
        (config, trace, seed) — the determinism tests hash this."""
        return [json.dumps(e, sort_keys=True, separators=(",", ":"))
                for e in self.events]

    # -- request lifecycle ----------------------------------------------

    def submit(self, r: Request) -> None:
        req = _SimReq(r, self.config.max_new_tokens)
        self.records[req.uri] = _Record(
            uri=req.uri, priority=req.priority, tenant=req.tenant,
            arrival=req.enq_t)
        if req.deadline_t > 0:
            self.deadline_seen = True
        if (self.brownout is not None and self._bstate.level > 0
                and not scheduler_policy.brownout_admit(
                    self._bstate.level, req.priority)):
            # the live front door's brownout 429: a shed class never
            # enters the queue (drops, not deferrals — the client is
            # told to retry later, so the request leaves the system)
            self.brownout_sheds += 1
            self.records[req.uri].dropped = "brownout_shed"
            self._emit("brownout_shed", uri=req.uri,
                       level=self._bstate.level)
            return
        self._waiting.append(req)

    def submit_prefilled(self, req: "_SimReq", record: _Record) -> None:
        """Adopt a handed-off request from a prefill replica
        (``ContinuousEngine.submit_handoff``): the lifecycle record
        continues — same arrival, first token already stamped on the
        source replica — and admission skips prefill entirely
        (``_admit_adopted``)."""
        if req.handoff is None:
            raise ValueError("submit_prefilled needs req.handoff set")
        self.records[req.uri] = record
        self._waiting.append(req)

    def submit_retry(self, r: Request, record: _Record) -> None:
        """Crash-recovery redispatch (``FleetModel``): re-admit a lost
        request from scratch — full re-prefill, full re-generation,
        exactly like the live broker re-reading the original unacked
        stream entry — while CONTINUING its lifecycle record (same
        arrival, ``attempts`` already bumped), so the merged fleet
        records keep one shared entry per uri and TTFT observes the
        original arrival across the death."""
        req = _SimReq(r, self.config.max_new_tokens)
        self.records[req.uri] = record
        self._waiting.append(req)

    def _drop(self, req: "_SimReq", reason: str) -> None:
        self.records[req.uri].dropped = reason
        self._ev_dropped.append(req.uri)

    def _record_admit(self, req: "_SimReq") -> None:
        rec = self.records[req.uri]
        rec.admits.append(self.now)
        rec.queue_waits.append(self.now - rec.arrival)
        self._ev_admitted.append(req.uri)

    def _record_tokens(self, row: _Row, n: int, t: float) -> None:
        """Land ``n`` generated tokens at time ``t``; finish + free the
        slot when the completion length is reached (``_record_token``'s
        finish path)."""
        if n <= 0:
            return
        rec = self.records[row.req.uri]
        if row.emitted == 0:
            rec.first_tokens.append(t)
            if self.handoff_cb is not None and n < row.gen_len:
                # disaggregated prefill replica: export at the first
                # token (ContinuousEngine._handoff_slot) — free the
                # slot like a completion and ship the row; the decode
                # replica finishes it.  A request done at its first
                # token (n >= gen_len) never hands off, matching the
                # engine's not-done condition.
                row.emitted = n
                i = self._slots.index(row)
                self._slots[i] = None
                self._free.append(i)
                self._release_blocks(row)
                self.handoffs_out += 1
                self._emit("handoff_out", uri=row.req.uri)
                self.handoff_cb(row, t)
                return
        row.emitted += n
        if row.emitted >= row.gen_len:
            row.emitted = row.gen_len
            rec.finish_t = t
            rec.tokens = row.gen_len
            i = self._slots.index(row)
            self._slots[i] = None
            self._free.append(i)
            self._release_blocks(row)
            self._emit("finish", uri=row.req.uri, tokens=row.gen_len)
            if self.brownout is not None:
                # per-class windowed goodput: what SloWatchdog's
                # finish-outcome window feeds the live controller — a
                # bounded window, so a bad burst can UNLATCH once the
                # recent finishes come good again
                self._goodput_win[rec.priority].append(self._slo_ok(rec))

    def _release_blocks(self, row: _Row) -> None:
        if self._pool is not None and row.blocks:
            self._pool.release(row.blocks)
            if self._dpool is not None:
                self._dpool.release(row.blocks)
            row.blocks = 0

    # -- preemption (engine `_preempt` / `_grow_tenant`) ----------------

    def _pick_victim(self) -> int:
        return scheduler_policy.pick_victim(
            (i, s.state, s.admit_seq)
            for i, s in enumerate(self._slots) if s is not None)

    def _preempt(self, slot: int) -> None:
        row = self._slots[slot]
        self._slots[slot] = None
        self._free.append(slot)
        self._release_blocks(row)
        self.preemptions += 1
        if row.state == "PREFILLING":
            self.prefill_preemptions += 1
        rec = self.records[row.req.uri]
        rec.preempts += 1
        # partial tokens are discarded; the epoch's first-token stamp
        # stays in history (the engine's watchdog saw it too) and the
        # NEXT epoch re-stamps TTFT from the original arrival
        self._waiting.appendleft(row.req)
        self._ev_preempted.append(row.req.uri)

    def _grow_row(self, i: int, need: int) -> None:
        # ``need`` counts TOTAL blocks for the row's context; blocks
        # served by the prefix-cache tier are already resident outside
        # the pool, so only the private remainder is allocated
        while (self._slots[i] is not None
               and (self._slots[i].blocks
                    + self._slots[i].shared) < need):
            ok = self._pool.allocate()
            if ok and self._dpool is not None:
                if not self._dpool.allocate():
                    self._pool.release(1)
                    ok = False
            if not ok:
                self._preempt(self._pick_victim())
                continue
            self._slots[i].blocks += 1

    def _ensure_blocks(self, active: List[int]) -> List[int]:
        bs = self.config.block_size
        for i in list(active):
            row = self._slots[i]
            if row is None:
                continue
            if self.config.spec_k > 0:
                last_write = row.pos + (self.config.spec_k
                                        if self._spec_on else 0)
            else:
                ticks = max(1, min(self.config.ticks_per_step,
                                   row.gen_len - row.emitted))
                last_write = row.pos + ticks - 1
            self._grow_row(i, last_write // bs + 1)
        return [i for i in active if self._slots[i] is not None]

    def _grow_chunk_blocks(self, decode_rows, chunks) -> None:
        bs = self.config.block_size
        for i in decode_rows:
            if self._slots[i] is None:
                continue
            last_write = self._slots[i].pos + (
                self.config.spec_k
                if self.config.spec_k > 0 and self._spec_on else 0)
            self._grow_row(i, last_write // bs + 1)
        for i, clen in chunks:
            row = self._slots[i]
            if row is None:
                continue
            self._grow_row(i, (row.fill_pos + clen - 1) // bs + 1)

    # -- tiered KV memory (engine kv_store.py wiring) -------------------

    def _shared_block_cap(self, req: "_SimReq") -> int:
        """FULL leading blocks of ``req``'s shared prefix (the engine
        caps matching at ``(plen - 1) // bs`` so the final write block
        is always private)."""
        return (min(int(req.prefix_len), req.prompt_len - 1)
                // self.config.block_size)

    def _prefix_peek(self, req: "_SimReq") -> int:
        """Device-tier match depth, side-effect free.  Admission gates
        use this exactly like the engine uses ``BlockPool.lookup``: the
        host tier only extends the match AFTER the gates pass, so both
        gate conservatively on the device match alone."""
        if not self._prefix_on or not req.prefix_id:
            return 0
        n_shared = self._shared_block_cap(req)
        if n_shared <= 0 or req.prefix_id not in self._dev_prefix:
            return 0
        return min(self._dev_prefix[req.prefix_id], n_shared)

    def _prefix_admit(self, req: "_SimReq") -> int:
        """Commit the tier transaction for an admitted request: match
        against the device tier, fall back to a host-tier re-admission
        (counted; the host entry stays, mirroring the engine's
        rollback contract), then publish the request's full shared
        depth to the device tier.  Returns matched full blocks."""
        if not self._prefix_on or not req.prefix_id:
            return 0
        bs = self.config.block_size
        n_shared = self._shared_block_cap(req)
        if n_shared <= 0:
            return 0
        pid = req.prefix_id
        if pid in self._dev_prefix:
            matched = min(self._dev_prefix[pid], n_shared)
        elif pid in self._host_prefix:
            matched = min(self._host_prefix[pid], n_shared)
            self._host_prefix.move_to_end(pid)
            self.kv_readmits += 1
            self.kv_readmit_tokens_saved += matched * bs
        else:
            matched = 0
        self.recompute_tokens_saved += matched * bs
        self._publish_prefix(pid, n_shared)
        return matched

    def _publish_prefix(self, pid: str, n: int) -> None:
        """Install/refresh ``pid`` in the device tier (LRU over prefix
        ids, capacity in blocks), spilling evictees to the host tier
        when one is configured."""
        self._dev_prefix[pid] = max(n, self._dev_prefix.get(pid, 0))
        self._dev_prefix.move_to_end(pid)
        cap = self.config.prefix_cache_blocks
        while (self._dev_prefix
               and sum(self._dev_prefix.values()) > cap):
            victim, d = self._dev_prefix.popitem(last=False)
            self._spill_prefix(victim, d)

    def _spill_prefix(self, pid: str, d: int) -> None:
        if self.config.host_store_blocks <= 0:
            return
        self.kv_spills += d     # the engine spills (and counts) blocks
        self._host_prefix[pid] = max(d, self._host_prefix.get(pid, 0))
        self._host_prefix.move_to_end(pid)
        while (self._host_prefix
               and (sum(self._host_prefix.values())
                    > self.config.host_store_blocks)):
            self._host_prefix.popitem(last=False)

    def prefix_resident_blocks(self, prefix_id: str) -> int:
        """Resident depth of ``prefix_id`` across BOTH tiers — what the
        fleet's ``PrefixDirectory`` lookup would report for this
        replica (``policy.ReplicaSignals.prefix_blocks``)."""
        if not self._prefix_on or not prefix_id:
            return 0
        return max(self._dev_prefix.get(prefix_id, 0),
                   self._host_prefix.get(prefix_id, 0))

    # -- overload brownout (engine/broker controller twin) --------------

    @property
    def brownout_level(self) -> int:
        return self._bstate.level

    def set_brownout(self, level: int) -> None:
        """External (fleet) controller pushing a ladder level — the
        sim's ``ContinuousEngine.set_brownout``."""
        lvl = max(0, min(int(level), scheduler_policy.BROWNOUT_MAX_LEVEL))
        if lvl != self._bstate.level:
            self.brownout_transitions += 1
            self.brownout_max_level = max(self.brownout_max_level, lvl)
            self._emit("brownout_level", level=lvl,
                       prev=self._bstate.level)
            self._bstate = scheduler_policy.BrownoutState(level=lvl)

    def _slo_ok(self, rec: _Record) -> bool:
        """Judge one finished request exactly like ``summarize`` (and
        the live SloWatchdog): good iff no observation of any dimension
        breached its class target."""
        tgt = self.slo_targets.get(rec.priority, {})
        for metric, obs in (("queue_wait", rec.queue_waits),
                            ("ttft", rec.ttfts)):
            lim = float(tgt.get(metric, 0.0))
            if lim > 0 and any(v > lim for v in obs):
                return False
        lim = float(tgt.get("tpot", 0.0))
        if lim > 0 and rec.tpot is not None and rec.tpot > lim:
            return False
        return True

    def windowed_goodput(self) -> Dict[str, float]:
        """Per-class goodput over the recent-finish window (1.0 cold,
        like the live ``SloWatchdog.windowed_goodput``)."""
        out: Dict[str, float] = {}
        for cls in PRIORITIES:
            win = self._goodput_win[cls]
            out[cls] = (sum(1 for ok in win if ok) / len(win)
                        if win else 1.0)
        return out

    def _brownout_step(self) -> None:
        """One standalone-controller decision on this tick's signals —
        the engine-level twin of the live broker's ``_brownout_eval``."""
        prev = self._bstate
        self._bstate = scheduler_policy.plan_brownout(
            self.brownout, prev,
            goodput=self.windowed_goodput(),
            queue_depth=len(self._waiting),
            alloc_fail_streak=self._alloc_streak,
            tick_s=(sum(self._tick_durs) / len(self._tick_durs)
                    if self._tick_durs else None))
        if self._bstate.level != prev.level:
            self.brownout_transitions += 1
            self.brownout_max_level = max(self.brownout_max_level,
                                          self._bstate.level)
            self._emit("brownout_level", level=self._bstate.level,
                       prev=prev.level)

    # -- admission (engine `_admit` family) -----------------------------

    def _pop_waiting(self) -> Optional["_SimReq"]:
        return self._waiting.popleft() if self._waiting else None

    def _requeue_front(self, req: "_SimReq") -> None:
        self._waiting.appendleft(req)

    def _admit(self) -> int:
        if self.deadline_seen:
            # the engine's _shed_expired_waiting: sweep the WHOLE
            # queue — including brownout-deferred classes, which is
            # what lets a shed class's backlog drain (and the ladder
            # recover) while the class is not being admitted
            expired = [r for r in self._waiting
                       if r.deadline_t > 0 and self.now > r.deadline_t]
            for r in expired:
                self._waiting.remove(r)
                self.deadline_sheds += 1
                self._drop(r, "deadline_exceeded")
        deferred: List[_SimReq] = []
        if self.brownout is not None and self._bstate.level >= 1:
            # the engine's _brownout_defer_extract: already-queued
            # requests of a shed class are HELD (still aging), not
            # dropped — only the front door drops new arrivals
            lvl = self._bstate.level
            deferred = [r for r in self._waiting
                        if not scheduler_policy.brownout_admit(
                            lvl, r.priority)]
            for r in deferred:
                self._waiting.remove(r)
        try:
            admitted = self._admit_pass()
            if deferred and admitted == 0 and self._free \
                    and not len(self._waiting):
                # work-conserving brownout (engine `_admit` second
                # pass): zero admissible demand + free slots means the
                # held backlog serves opportunistically instead of
                # idling the engine and latching the depth signal
                for r in reversed(deferred):
                    self._waiting.appendleft(r)
                deferred = []
                admitted = self._admit_pass()
            return admitted
        finally:
            for r in reversed(deferred):
                self._waiting.appendleft(r)

    def _admit_pass(self) -> int:
        if self.config.chunked:
            return self._admit_chunked()
        return self._admit_monolithic()

    def _admit_chunked(self) -> int:
        admitted = 0
        while self._free:
            req = self._pop_waiting()
            if req is None:
                break
            if req.handoff is not None:
                res = self._admit_adopted(req)
            else:
                res = (self._admit_one_chunked_paged(req)
                       if self.config.paged
                       else self._admit_one_chunked(req))
            if res == "admitted":
                admitted += 1
            elif res == "blocked":
                self._requeue_front(req)
                break
        return admitted

    def _install_prefill(self, req: "_SimReq", shared: int = 0) -> None:
        slot = self._free.popleft()
        row = _Row(req, "PREFILLING", self._admit_seq)
        self._admit_seq += 1
        if self.brownout is not None:
            # level-2 clamp, applied at install time like the engine's
            # _install_prefill — the level in force WHEN the row lands
            # decides its budget, so a descending ladder restores full
            # completions for later admissions
            row.gen_len = scheduler_policy.brownout_max_new(
                self._bstate.level, req.priority, row.gen_len,
                self.brownout.standard_max_new)
        if shared:
            # matched prefix blocks are already filled: prefill starts
            # past them (this is where recompute savings become real
            # work saved — chunked billing never sees those tokens)
            row.shared = shared
            row.fill_pos = shared * self.config.block_size
        self._slots[slot] = row
        self._record_admit(req)

    def _admit_one_chunked(self, req: "_SimReq") -> str:
        self._install_prefill(req)
        return "admitted"

    def _admit_one_chunked_paged(self, req: "_SimReq") -> str:
        bs = self.config.block_size
        plen = req.prompt_len
        need = -(-plen // bs) - self._prefix_peek(req)
        cap = self._pool.n_blocks - 1
        if self._dpool is not None:
            cap = min(cap, self._dpool.n_blocks - 1)
        if need + 1 > cap:
            self._drop(req, "prompt_exceeds_pool")
            return "error"
        dry = self._pool.allocatable() < 2 or (
            self._dpool is not None and self._dpool.allocatable() < 2)
        if dry:
            if self.n_active == 0:
                self._drop(req, "pool_dry_no_residents")
                return "error"
            return "blocked"
        # commit the tier transaction only once the gates pass — a
        # blocked request requeues and must not double-count readmits
        self._install_prefill(req, self._prefix_admit(req))
        return "admitted"

    def _admit_adopted(self, req: "_SimReq") -> str:
        """Admit a handed-off row straight into DECODE
        (``ContinuousEngine._admit_handoff``): blocks for the prompt's
        KV chain plus one decode block of headroom, no prefill phase,
        first token NOT re-emitted (the source replica stamped it)."""
        if self.config.paged:
            bs = self.config.block_size
            need = -(-req.prompt_len // bs)
            cap = self._pool.n_blocks - 1
            if self._dpool is not None:
                cap = min(cap, self._dpool.n_blocks - 1)
            if need + 1 > cap:
                self._drop(req, "prompt_exceeds_pool")
                return "error"
            short = self._pool.allocatable() < need + 1 or (
                self._dpool is not None
                and self._dpool.allocatable() < need + 1)
            if short:
                if self.n_active == 0:
                    self._drop(req, "pool_dry_no_residents")
                    return "error"
                return "blocked"
        slot = self._free.popleft()
        row = _Row(req, "DECODE", self._admit_seq)
        self._admit_seq += 1
        row.fill_pos = req.prompt_len
        row.emitted = int(req.handoff)
        self._slots[slot] = row
        if self.config.paged:
            need = -(-req.prompt_len // self.config.block_size)
            row.blocks = need
            self._pool.free -= need
            if self._dpool is not None:
                self._dpool.free -= need
        self.handoffs_in += 1
        self._record_admit(req)
        self._emit("handoff_in", uri=req.uri)
        return "admitted"

    def _admit_monolithic(self) -> int:
        """Non-chunked admission, approximated: the whole prompt
        prefills at admission time (first token stamped immediately);
        paged admission gates on blocks for the full prompt plus one
        decode block of headroom, requeueing at the front when the pool
        cannot take it (``_admit_paged``'s plan gate)."""
        admitted = 0
        while self._free:
            req = self._pop_waiting()
            if req is None:
                break
            if req.handoff is not None:
                res = self._admit_adopted(req)
                if res == "admitted":
                    admitted += 1
                elif res == "blocked":
                    self._requeue_front(req)
                    break
                continue
            if self.config.paged:
                bs = self.config.block_size
                need = -(-req.prompt_len // bs) + 1 \
                    - self._prefix_peek(req)
                cap = self._pool.n_blocks - 1
                if self._dpool is not None:
                    cap = min(cap, self._dpool.n_blocks - 1)
                if need > cap:
                    self._drop(req, "prompt_exceeds_pool")
                    continue
                short = self._pool.allocatable() < need or (
                    self._dpool is not None
                    and self._dpool.allocatable() < need)
                if short:
                    if self.n_active == 0:
                        self._drop(req, "pool_dry_no_residents")
                        continue
                    self._requeue_front(req)
                    break
                # gates passed: commit the tier transaction (the host
                # tier may extend the match, so recompute need)
                shared = self._prefix_admit(req)
                need = -(-req.prompt_len // bs) + 1 - shared
            slot = self._free.popleft()
            row = _Row(req, "DECODE", self._admit_seq)
            self._admit_seq += 1
            if self.brownout is not None:
                row.gen_len = scheduler_policy.brownout_max_new(
                    self._bstate.level, req.priority, row.gen_len,
                    self.brownout.standard_max_new)
            row.fill_pos = req.prompt_len
            self._slots[slot] = row
            if self.config.paged:
                row.blocks = need
                row.shared = shared
                self._pool.free -= need
                if self._dpool is not None:
                    self._dpool.free -= need
            self._record_admit(req)
            # monolithic prefill picks the request's first token
            self._record_tokens(row, 1, self.now)
            admitted += 1
        return admitted

    # -- grant ordering --------------------------------------------------

    def _grant_rank(self, i: int):
        row = self._slots[i]
        return scheduler_policy.grant_rank(
            self.qos, row.req.priority, self.now - row.req.enq_t,
            row.admit_seq)

    # -- ticks (engine `_step_impl` dispatch) ----------------------------

    def step(self) -> int:
        """One engine iteration on virtual time.  Returns active slots
        after the tick; 0 means idle (no tick happened)."""
        if self.n_active == 0 and not self._waiting:
            return 0
        self._ev_admitted, self._ev_preempted = [], []
        self._ev_chunks, self._ev_dropped = [], []
        t0 = self.now
        f0 = 0
        if self._pool is not None:
            f0 = self._pool.alloc_failures + (
                self._dpool.alloc_failures
                if self._dpool is not None else 0)
        self._admit()
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            # every waiting request errored out during admission — or,
            # under brownout, everything left waiting is a deferred
            # shed-class request: idle-tick the clock forward so the
            # controller can observe the drained engine and descend
            # (the model must not spin without advancing time)
            self._tick_event("admit", t0, 0.0, 0)
            if self.brownout is not None and len(self._waiting) > 0:
                dur = self.timing.tick_s(0)
                self.now = t0 + dur
                self.ticks += 1
                self._tick_durs.append(dur)
                self._alloc_streak = 0
                if not self.brownout_managed:
                    self._brownout_step()
            return 0
        spec = self.config.spec_k > 0
        if spec and self.brownout is not None:
            # level-3: park the draft model (the engine's
            # brownout_spec_enabled gate in _step_impl)
            spec = scheduler_policy.brownout_spec_enabled(
                self._bstate.level)
        self._spec_on = spec or self.config.spec_k == 0
        prefilling = any(self._slots[i].state == "PREFILLING"
                         for i in active)
        if spec and self.config.chunked and prefilling:
            kind, work = self._chunked_tick(active,
                                            self.config.spec_k + 1)
            kind = "spec_chunked"
        elif spec:
            kind, work = self._spec_tick(active)
        elif self.config.chunked and prefilling:
            kind, work = self._chunked_tick(active, 1)
        else:
            kind, work = self._decode_tick(active)
        dur = self.timing.tick_s(work)
        self.now = t0 + dur
        self._apply_emissions()
        self._admit()       # freed slots recycle on the SAME iteration
        self.ticks += 1
        self._tick_event(kind, t0, dur, work)
        if self.brownout is not None:
            self._tick_durs.append(dur)
            if self._pool is not None:
                f1 = self._pool.alloc_failures + (
                    self._dpool.alloc_failures
                    if self._dpool is not None else 0)
                self._alloc_streak = (self._alloc_streak + 1
                                      if f1 > f0 else 0)
            if not self.brownout_managed:
                self._brownout_step()
        return self.n_active

    def _tick_event(self, kind: str, t0: float, dur: float,
                    work: int) -> None:
        if not self.record_events:
            return
        ev = {"event": "tick", "seq": self.ticks, "t": _t(t0),
              "dur_s": _t(dur), "kind": kind, "work": int(work),
              "active": self.n_active,
              "queue_depth": len(self._waiting),
              "admitted": list(self._ev_admitted),
              "preempted": list(self._ev_preempted),
              "chunks": [[u, int(c)] for u, c in self._ev_chunks]}
        if self._ev_dropped:
            ev["dropped"] = list(self._ev_dropped)
        if self._pool is not None:
            ev["free_blocks"] = self._pool.allocatable()
            if self._dpool is not None:
                ev["draft_free_blocks"] = self._dpool.allocatable()
        if self._prefix_on:
            # cumulative, like the flight recorder's v3 counters; only
            # tiered configs emit them so tier-off logs stay
            # byte-identical to previous releases
            ev["kv_spills"] = self.kv_spills
            ev["kv_readmits"] = self.kv_readmits
        self.events.append(ev)

    # Emissions are decided during the tick but land at its END (see
    # module docstring); the tick body queues (row, n) pairs here.
    def _queue_emit(self, row: _Row, n: int) -> None:
        self._pending_emits.append((row, n))

    def _apply_emissions(self) -> None:
        for row, n in self._pending_emits:
            # a row preempted AFTER its emission was queued lost those
            # tokens (the engine discards them too)
            if row in self._slots:
                self._record_tokens(row, n, self.now)
        self._pending_emits = []

    def _decode_tick(self, active: List[int]) -> Tuple[str, int]:
        self._pending_emits = []
        if self.config.paged:
            active = self._ensure_blocks(active)
            if not active:
                return "decode", 0
        if self.config.spec_k > 0 and not self._spec_on:
            # brownout level 3 parked the draft: plain decode, one
            # token per tick (the engine forces n_eff=1 whenever a
            # draft tenant exists, to hold the lockstep write frontier)
            n_eff = 1
        else:
            n_eff = max(1, min(
                self.config.ticks_per_step,
                max(self._slots[i].gen_len - self._slots[i].emitted
                    for i in active)))
        work = 0
        for i in active:
            row = self._slots[i]
            n = min(n_eff, row.gen_len - row.emitted)
            self._queue_emit(row, n)
            work += n_eff
        return "decode", work

    def _spec_tick(self, active: List[int]) -> Tuple[str, int]:
        self._pending_emits = []
        if self.config.paged:
            active = self._ensure_blocks(active)
            if not active:
                return "spec", 0
        k = self.config.spec_k
        work = 0
        for i in active:
            row = self._slots[i]
            a = self.acceptance.sample(self.rng)
            self.spec_proposed += k
            self.spec_accepted += a
            self._queue_emit(row, min(a + 1, row.gen_len - row.emitted))
            work += k + 1
        return "spec", work

    def _chunked_tick(self, active: List[int],
                      per_row: int) -> Tuple[str, int]:
        self._pending_emits = []
        decode_rows = [i for i in active
                       if self._slots[i].state == "DECODE"]
        prefill_rows = sorted(
            (i for i in active
             if self._slots[i].state == "PREFILLING"),
            key=self._grant_rank)
        chunks, stalled = scheduler_policy.plan_chunks(
            self.config.tick_token_budget, per_row, len(decode_rows),
            [(i, self._slots[i].req.prompt_len - self._slots[i].fill_pos)
             for i in prefill_rows],
            self.config.chunk_buckets[-1])
        if stalled:
            self.prefill_stall_ticks += 1
        if self.config.paged:
            self._grow_chunk_blocks(decode_rows, chunks)  # may preempt
            decode_rows = [i for i in decode_rows
                           if self._slots[i] is not None]
            chunks = [(i, c) for i, c in chunks
                      if self._slots[i] is not None]
        if not decode_rows and not chunks:
            return "chunked", 0
        self.budget_ticks += 1
        work = per_row * len(decode_rows) + sum(c for _, c in chunks)
        self.budget_tokens_used += work
        k = self.config.spec_k if self._spec_on else 0
        for i in decode_rows:
            row = self._slots[i]
            if k > 0:
                a = self.acceptance.sample(self.rng)
                self.spec_proposed += k
                self.spec_accepted += a
                n = min(a + 1, row.gen_len - row.emitted)
            else:
                n = 1
            self._queue_emit(row, n)
        for i, clen in chunks:
            row = self._slots[i]
            row.fill_pos += clen
            self._ev_chunks.append((row.req.uri, clen))
            if row.fill_pos >= row.req.prompt_len:
                row.state = "DECODE"
                # the prompt's final chunk also picks its first token
                self._queue_emit(row, 1)
        return "chunked", work

    # -- driving ---------------------------------------------------------

    def run(self, trace: Sequence[Request],
            max_ticks: Optional[int] = None) -> Dict[str, _Record]:
        """Feed ``trace`` (sorted by arrival) through the model until
        every request finishes or drops.  The clock jumps across idle
        gaps to the next arrival, mirroring the serving pump's idle
        wait."""
        pending = sorted(trace, key=lambda r: (r.arrival_t, r.uri))
        i = 0
        guard = max_ticks if max_ticks is not None else \
            20_000_000
        while True:
            while i < len(pending) and pending[i].arrival_t <= self.now:
                self.submit(pending[i])
                i += 1
            if self.n_active == 0 and not self._waiting:
                if i < len(pending):
                    self.now = max(self.now, pending[i].arrival_t)
                    continue
                break
            self.step()
            if self.ticks >= guard:
                raise RuntimeError(
                    f"simulation exceeded {guard} ticks "
                    f"(arrival rate beyond modelled capacity?)")
        return self.records


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def percentile(xs: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (deterministic, no interpolation)."""
    if not xs:
        return 0.0
    s = sorted(xs)
    k = max(0, min(len(s) - 1, math.ceil(q / 100.0 * len(s)) - 1))
    return s[k]


def _dist(xs: List[float]) -> Dict[str, float]:
    return {"n": len(xs),
            "mean": _t(sum(xs) / len(xs)) if xs else 0.0,
            "p50": _t(percentile(xs, 50)),
            "p99": _t(percentile(xs, 99))}


def summarize(records, targets: Optional[Dict[str, Dict[str, float]]]
              = None) -> Dict[str, Any]:
    """Per-class latency distributions + SLO goodput from request
    records.  Judges requests exactly like ``SloWatchdog``: a request
    is GOOD when no observation of any dimension breached its class
    target (every admission epoch's queue wait and TTFT counts — a
    preempted request that breached before preemption stays bad).
    ``records`` accepts the model's ``_Record`` map or any iterable of
    objects/dicts with the same fields."""
    targets = targets or DEFAULT_SLO_TARGETS
    if isinstance(records, dict):
        records = list(records.values())
    per_class: Dict[str, Dict[str, Any]] = {}
    dropped = 0
    total_tokens = 0
    end_t = 0.0
    for cls in PRIORITIES:
        rows = [r for r in records if r.priority == cls]
        if not rows:
            continue
        fin = [r for r in rows if r.finished]
        tgt = targets.get(cls, {})
        good = 0
        for r in fin:
            ok = True
            for metric, obs in (("queue_wait", r.queue_waits),
                                ("ttft", r.ttfts)):
                lim = float(tgt.get(metric, 0.0))
                if lim > 0 and any(v > lim for v in obs):
                    ok = False
            lim = float(tgt.get("tpot", 0.0))
            if lim > 0 and r.tpot is not None and r.tpot > lim:
                ok = False
            if ok:
                good += 1
        dropped += sum(1 for r in rows if r.dropped)
        total_tokens += sum(r.tokens for r in fin)
        if fin:
            end_t = max(end_t, max(r.finish_t for r in fin))
        per_class[cls] = {
            "submitted": len(rows),
            "finished": len(fin),
            "good": good,
            "goodput": _t(good / len(fin)) if fin else 1.0,
            "preemptions": sum(r.preempts for r in rows),
            "ttft": _dist([r.ttfts[-1] for r in fin if r.ttfts]),
            "tpot": _dist([r.tpot for r in fin
                           if r.tpot is not None]),
            "queue_wait": _dist([w for r in fin
                                 for w in r.queue_waits]),
        }
    n_fin = sum(c["finished"] for c in per_class.values())
    n_good = sum(c["good"] for c in per_class.values())
    return {
        "per_class": per_class,
        "finished": n_fin,
        "good": n_good,
        "goodput": _t(n_good / n_fin) if n_fin else 1.0,
        "dropped": dropped,
        "tokens": total_tokens,
        "duration_s": _t(end_t),
        "tokens_per_s": _t(total_tokens / end_t) if end_t > 0 else 0.0,
    }

"""Discrete-event simulator for the continuous-batching serving engine.

A jax-free, numpy-free model of the engine's SCHEDULING behavior —
admission, block-pool accounting, token-budget chunked ticks, QoS
deficit-round-robin queues, speculative acceptance as a stochastic
process — that answers scheduler-policy questions (QoS weights, aging
constants, ``tick_token_budget``, pool sizes) offline, in seconds, at
million-request scale, with no device anywhere (docs/simulation.md).

Two modes:

* **Replay** (``sim.replay``): load a diagnostic bundle
  (``serving/flight.py::dump_bundle``), re-derive per-request
  TTFT/TPOT/queue-wait and per-class goodput from its trace, cross-check
  them against the bundle's own recorded telemetry within documented
  tolerances, and re-simulate the recorded request schedule to compare
  modelled against measured behavior.
* **Scenario** (``sim.model`` + ``sim.trace``): run a seeded synthetic
  trace (Poisson or diurnal arrivals, mixed priority classes and
  tenants) through the modelled engine and report p50/p99 latencies and
  per-class goodput — the offline sweep surface, and the substrate of
  the ``make sim-gate`` golden-trace regression envelope.

The simulator makes scheduling decisions by calling the SAME pure
functions the real engine calls (``serving/policy.py``: ``grant_rank``,
``pick_victim``, ``plan_chunks``, ``WeightedWaitQueue``) — equivalence
is pinned by tests/test_sim.py driving both from one request schedule.

Import contract: stdlib + ``serving/policy.py`` only.  The package
must load on a bare box with neither jax nor numpy installed —
``serving/debug.py --replay`` bootstraps it file-by-file exactly that
way.  Time never comes from the wall clock: the model runs on virtual
seconds, which is what makes two runs of the same seed byte-identical.
"""

from ..policy import SCHEDULER_POLICY_VERSION  # noqa: F401
from .model import (AcceptanceModel, EngineConfig, EngineModel,  # noqa: F401
                    TimingModel, percentile, summarize)
from .replay import (SUPPORTED_SCHEMA_VERSIONS,  # noqa: F401
                     SchemaVersionError, load_bundle, replay_bundle)
from .trace import Request, diurnal_trace, poisson_trace  # noqa: F401

__all__ = [
    "AcceptanceModel", "EngineConfig", "EngineModel", "TimingModel",
    "Request", "poisson_trace", "diurnal_trace",
    "SUPPORTED_SCHEMA_VERSIONS", "SchemaVersionError",
    "load_bundle", "replay_bundle",
    "percentile", "summarize", "SCHEDULER_POLICY_VERSION",
]

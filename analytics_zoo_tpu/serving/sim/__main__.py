"""``python -m analytics_zoo_tpu.serving.sim`` — the simulator CLI.

Three subcommands (docs/simulation.md):

* ``replay <bundle-dir>`` — load a diagnostic bundle, re-derive its
  request metrics from the trace, cross-check against the recorded
  watchdog score, re-simulate the recorded schedule, and print the
  SLO timeline + deltas.  Exit 0 when the cross-check holds, 1 on a
  tolerance breach, 2 on an unreadable/unknown-schema bundle.
* ``run <scenario.(json|yaml)>`` — run a synthetic scenario (seeded
  Poisson/diurnal arrivals, mixed classes/tenants) and print the
  per-class p50/p99 + goodput table.  A ``sweep`` section expands into
  the cartesian product of its value lists — one table row per combo —
  which is the offline QoS-weight / budget / pool-size tuning surface.
* ``gate <golden.json>`` — run the pinned golden scenario and assert
  its recorded envelopes (min/max bounds per metric).  Exit 0 in
  envelope, 1 out (the CI hook: ``make sim-gate``).

Scenario files are JSON always; YAML when pyyaml happens to be
importable (the sim itself stays stdlib-only).
"""

import argparse
import itertools
import json
import sys
from typing import Any, Dict, List, Optional

from ..policy import BrownoutPolicy, QosPolicy
from .fleet import FleetModel
from .model import (DEFAULT_SLO_TARGETS, AcceptanceModel, EngineConfig,
                    EngineModel, TimingModel, summarize)
from .replay import SchemaVersionError, replay_bundle
from .trace import diurnal_trace, poisson_trace, requests_from_dicts

__all__ = ["main", "run_scenario", "load_scenario", "check_envelopes"]


def _load_doc(path: str) -> Dict[str, Any]:
    with open(path) as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        try:
            import yaml  # an optional nicety, never a requirement
        except ImportError:
            raise SystemExit(
                f"{path}: YAML scenarios need pyyaml; re-write the "
                f"scenario as JSON (same keys) to stay stdlib-only")
        return yaml.safe_load(text) or {}
    return json.loads(text)


def load_scenario(path: str) -> Dict[str, Any]:
    doc = _load_doc(path)
    if not isinstance(doc, dict) or "trace" not in doc:
        raise SystemExit(f"{path}: a scenario needs a 'trace' section "
                         f"(see docs/simulation.md)")
    return doc


def _build_trace(spec: Dict[str, Any], seed: int):
    kind = spec.get("kind", "poisson")
    kw = dict(prompt_len=spec.get("prompt_len", (16, 256)),
              gen_len=spec.get("gen_len", (8, 64)),
              class_mix=spec.get("classes"),
              tenants=spec.get("tenants", ("",)),
              prefixes=spec.get("prefixes"),
              prefix_frac=float(spec.get("prefix_frac", 0.0)))
    if kind == "poisson":
        return _stamp_deadlines(
            poisson_trace(n_requests=int(spec["n_requests"]),
                          rate_rps=float(spec["rate_rps"]),
                          seed=seed, **kw), spec)
    if kind == "diurnal":
        return _stamp_deadlines(
            diurnal_trace(n_requests=int(spec["n_requests"]),
                          base_rps=float(spec["base_rps"]),
                          peak_rps=float(spec["peak_rps"]),
                          period_s=float(spec["period_s"]),
                          seed=seed, **kw), spec)
    if kind == "explicit":
        return _stamp_deadlines(requests_from_dicts(spec["requests"]),
                                spec)
    raise SystemExit(f"unknown trace kind {kind!r} "
                     f"(poisson | diurnal | explicit)")


def _stamp_deadlines(trace, spec: Dict[str, Any]):
    """Apply a per-class ``deadlines`` mapping (class -> seconds after
    arrival) AFTER generation: no RNG draws, so traces without the
    section stay byte-identical to previous releases."""
    dls = spec.get("deadlines")
    if not dls:
        return trace
    from dataclasses import replace
    out = []
    for r in trace:
        d = dls.get(r.priority)
        out.append(replace(r, deadline_s=float(d))
                   if d is not None else r)
    return out


def run_scenario(doc: Dict[str, Any],
                 seed: Optional[int] = None,
                 record_events: bool = False) -> Dict[str, Any]:
    """Run one scenario document; returns the summary (the model is
    discarded).  ``seed`` overrides the document's seed."""
    seed = int(doc.get("seed", 0)) if seed is None else int(seed)
    econf = EngineConfig.from_dict(doc.get("engine") or {})
    qos_doc = doc.get("qos") or {}
    qos = None
    if qos_doc.get("enabled"):
        qos = QosPolicy(
            weights=dict(qos_doc.get("weights") or {}),
            aging_s=float(qos_doc.get("aging_s", 30.0)))
    acc = None
    acc_doc = doc.get("spec_acceptance")
    if econf.spec_k > 0 and acc_doc:
        if "counts" in acc_doc:
            acc = AcceptanceModel.from_counts(acc_doc["counts"],
                                              econf.spec_k)
        elif "mean" in acc_doc:
            acc = AcceptanceModel.constant(round(acc_doc["mean"]),
                                           econf.spec_k)
    timing = TimingModel(**(doc.get("timing")
                            or {"base_s": 0.002,
                                "per_token_s": 0.00005}))
    targets = doc.get("slo") or DEFAULT_SLO_TARGETS
    brownout = None
    b_doc = doc.get("brownout") or {}
    if b_doc.get("enabled"):
        # the SAME BrownoutPolicy knobs ServingConfig exposes (see
        # docs/serving_qos.md "Overload & brownout")
        brownout = BrownoutPolicy(
            goodput_floor=float(b_doc.get("goodput_floor", 0.9)),
            queue_high=int(b_doc.get("queue_high", 64)),
            queue_recover_frac=float(
                b_doc.get("queue_recover_frac", 0.5)),
            alloc_streak_high=int(b_doc.get("alloc_streak_high", 4)),
            tick_s_high=float(b_doc.get("tick_s_high", 0.0)),
            enter_ticks=int(b_doc.get("enter_ticks", 3)),
            exit_ticks=int(b_doc.get("exit_ticks", 6)),
            standard_max_new=int(b_doc.get("standard_max_new", 16)))
    fleet_doc = doc.get("fleet")
    if fleet_doc:
        # disaggregated fleet scenario (docs/simulation.md): N modelled
        # replicas behind the real router, optional prefill/decode
        # roles with modelled KV handoff
        roles = fleet_doc.get("roles")
        n = int(fleet_doc.get("n_replicas",
                              len(roles) if roles else 1))
        fleet = FleetModel(
            [EngineConfig.from_dict(doc.get("engine") or {})
             for _ in range(n)],
            roles=roles, qos=qos, acceptance=acc, timing=timing,
            seed=seed, record_events=record_events,
            handoff_s=float(fleet_doc.get("handoff_s", 0.0)),
            # chaos twin: the same fault-schedule dicts
            # ServingConfig.fault_injection takes (serving/fault.py)
            faults=fleet_doc.get("faults"),
            retry_budget=int(fleet_doc.get("retry_budget", 2)),
            handoff_timeout_s=float(
                fleet_doc.get("handoff_timeout_s", 0.0)),
            request_deadline_s=float(
                fleet_doc.get("request_deadline_s", 0.0)),
            brownout=brownout, slo_targets=targets)
        fleet.run(_build_trace(doc["trace"], seed))
        out = fleet.summary(targets)
        out["seed"] = seed
        if record_events:
            out["event_log_lines"] = [
                line for e in fleet.engines
                for line in e.event_log_lines()]
        return out
    model = EngineModel(econf, qos=qos, acceptance=acc, timing=timing,
                        seed=seed, record_events=record_events,
                        brownout=brownout, slo_targets=targets)
    model.run(_build_trace(doc["trace"], seed))
    out = summarize(model.records, targets)
    out["seed"] = seed
    out["ticks"] = model.ticks
    out["preemptions"] = model.preemptions
    out["prefill_stall_ticks"] = model.prefill_stall_ticks
    if model.brownout is not None:
        # only-when-on keys, like the tiered-KV block below
        out["brownout_sheds"] = model.brownout_sheds
        out["brownout_max_level"] = model.brownout_max_level
        out["brownout_final_level"] = model.brownout_level
        out["brownout_transitions"] = model.brownout_transitions
    if model.brownout is not None or model.deadline_seen:
        out["deadline_sheds"] = model.deadline_sheds
    if model._prefix_on:
        # tiered-KV counters, present only when the tier is on (see
        # FleetModel.summary — same key-stability contract)
        out["kv_spills"] = model.kv_spills
        out["kv_readmits"] = model.kv_readmits
        out["kv_readmit_tokens_saved"] = model.kv_readmit_tokens_saved
        out["recompute_tokens_saved"] = model.recompute_tokens_saved
    if record_events:
        out["event_log_lines"] = model.event_log_lines()
    return out


def _apply_override(doc: Dict[str, Any], dotted: str, value) -> None:
    node = doc
    parts = dotted.split(".")
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def _sweep_rows(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Expand a ``sweep`` mapping of dotted-key -> value-list into the
    cartesian product of overridden scenario documents."""
    sweep = doc.get("sweep")
    if not sweep:
        return [{"label": "-", "doc": doc}]
    keys = list(sweep.keys())
    rows = []
    for combo in itertools.product(*(sweep[k] for k in keys)):
        d = json.loads(json.dumps(doc))     # deep copy, JSON-safe
        d.pop("sweep", None)
        for k, v in zip(keys, combo):
            _apply_override(d, k, v)
        rows.append({"label": " ".join(f"{k}={v}"
                                       for k, v in zip(keys, combo)),
                     "doc": d})
    return rows


def _fmt_ms(x: float) -> str:
    return f"{x * 1e3:8.1f}"


def _print_summary(out: Dict[str, Any], label: str = "",
                   file=None) -> None:
    f = file or sys.stdout
    if label and label != "-":
        print(f"--- {label}", file=f)
    print(f"{'class':<12} {'fin':>7} {'goodput':>8} {'ttft p50':>9} "
          f"{'ttft p99':>9} {'tpot p99':>9} {'qwait p99':>10}  (ms)",
          file=f)
    for cls, c in out["per_class"].items():
        print(f"{cls:<12} {c['finished']:>7} {c['goodput']:>8.3f} "
              f"{_fmt_ms(c['ttft']['p50']):>9} "
              f"{_fmt_ms(c['ttft']['p99']):>9} "
              f"{_fmt_ms(c['tpot']['p99']):>9} "
              f"{_fmt_ms(c['queue_wait']['p99']):>10}", file=f)
    extra = (f", {out['handoffs']} handoffs"
             if "handoffs" in out else "")
    print(f"total: {out['finished']} finished, {out['dropped']} "
          f"dropped, goodput {out['goodput']:.3f}, "
          f"{out['tokens_per_s']:.0f} tok/s over "
          f"{out['duration_s']:.2f}s simulated "
          f"({out.get('ticks', out.get('sim_ticks', 0))} ticks, "
          f"{out.get('preemptions', 0)} preemptions{extra})", file=f)


def check_envelopes(summary: Dict[str, Any],
                    envelopes: Dict[str, Dict[str, float]]
                    ) -> List[Dict[str, Any]]:
    """Assert envelope bounds against a summary.  Envelope keys are
    dotted metric paths rooted at the summary (e.g.
    ``per_class.interactive.ttft.p99``), each with optional ``min`` /
    ``max``.  Returns the list of violations (empty = in envelope)."""
    violations = []
    for path, bound in sorted(envelopes.items()):
        node: Any = summary
        ok_path = True
        for part in path.split("."):
            if isinstance(node, dict) and part in node:
                node = node[part]
            else:
                ok_path = False
                break
        if not ok_path or not isinstance(node, (int, float)):
            violations.append({"metric": path, "value": None,
                               "error": "metric missing from summary"})
            continue
        lo, hi = bound.get("min"), bound.get("max")
        if lo is not None and node < lo:
            violations.append({"metric": path, "value": node,
                               "min": lo})
        if hi is not None and node > hi:
            violations.append({"metric": path, "value": node,
                               "max": hi})
    return violations


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------

def _cmd_replay(args) -> int:
    try:
        report = replay_bundle(args.bundle, seed=args.seed,
                               resim=not args.no_resim)
    except SchemaVersionError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0 if report["ok"] else 1
    print(f"bundle {report['bundle']} (schema_version "
          f"{report['schema_version']}, reason: {report['reason']})")
    print("observed (re-derived from trace.json):")
    _print_summary(report["observed"])
    print("crosscheck vs recorded slo.json:")
    for c in report["crosscheck"]["checks"]:
        if c["verdict"] == "skipped_ring_truncated":
            print(f"  {c['class']:<12} goodput  SKIPPED (trace ring "
                  f"truncated: {c['observed_finished']} of "
                  f"{c['recorded_finished']} requests visible)")
        else:
            print(f"  {c['class']:<12} goodput  observed "
                  f"{c['observed']:.3f}  recorded {c['recorded']:.3f}  "
                  f"delta {c['delta']:+.3f}  [{c['verdict']}]")
    if "simulated" in report:
        print("simulated (modelled engine on the recorded schedule):")
        _print_summary(report["simulated"])
        for cls, d in sorted(report["sim_vs_observed"].items()):
            print(f"  {cls:<12} sim-vs-observed  goodput "
                  f"{d['goodput']:+.3f}  ttft p99 "
                  f"{d['ttft_p99_s'] * 1e3:+.1f}ms  tpot p99 "
                  f"{d['tpot_p99_s'] * 1e3:+.1f}ms")
    print("crosscheck:", "OK" if report["ok"] else "BREACH")
    return 0 if report["ok"] else 1


def _cmd_run(args) -> int:
    doc = load_scenario(args.scenario)
    rows = _sweep_rows(doc)
    results = []
    for row in rows:
        out = run_scenario(row["doc"], seed=args.seed)
        results.append({"label": row["label"], "summary": out})
    if args.json:
        json.dump(results, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    for r in results:
        _print_summary(r["summary"], r["label"])
    return 0


def _cmd_gate(args) -> int:
    doc = load_scenario(args.golden)
    envelopes = doc.get("envelopes")
    if not envelopes:
        print(f"error: {args.golden} has no 'envelopes' section — "
              f"nothing to gate on", file=sys.stderr)
        return 2
    # the pinned primary scenario plus any embedded extra_scenarios
    # (each a complete scenario doc with its own envelopes — e.g. the
    # disaggregated-fleet fixture); ALL must hold for exit 0
    gates = [(doc.get("name", args.golden), doc, envelopes)]
    for sub in doc.get("extra_scenarios") or []:
        sub_env = sub.get("envelopes")
        if not sub_env:
            print(f"error: extra scenario "
                  f"{sub.get('name', '?')!r} has no 'envelopes' "
                  f"section — nothing to gate on", file=sys.stderr)
            return 2
        gates.append((sub.get("name", "extra"), sub, sub_env))
    results = []
    all_violations = []
    for name, d, env in gates:
        summary = run_scenario(d, seed=args.seed)
        violations = check_envelopes(summary, env)
        results.append((name, summary, env, violations))
        all_violations.extend(
            dict(v, scenario=name) for v in violations)
    if args.json:
        json.dump({"summary": results[0][1],
                   "violations": all_violations},
                  sys.stdout, indent=2, sort_keys=True)
        print()
        return 1 if all_violations else 0
    for name, summary, env, violations in results:
        _print_summary(summary, name)
        if violations:
            print("ENVELOPE VIOLATIONS (see docs/simulation.md for "
                  "how to read and, when intended, re-pin these):")
            for v in violations:
                bound = (f">= {v['min']}" if "min" in v
                         else f"<= {v['max']}" if "max" in v
                         else v.get("error", "?"))
                print(f"  {v['metric']}: value {v['value']} violates "
                      f"{bound}")
        else:
            print(f"gate OK: {len(env)} envelope(s) hold")
    return 1 if all_violations else 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.serving.sim",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("replay", help="replay a diagnostic bundle")
    pr.add_argument("bundle", help="bundle directory (manifest.json...)")
    pr.add_argument("--seed", type=int, default=0)
    pr.add_argument("--no-resim", action="store_true",
                    help="derive + crosscheck only, skip re-simulation")
    pr.add_argument("--json", action="store_true")
    pr.set_defaults(fn=_cmd_replay)

    pu = sub.add_parser("run", help="run a synthetic scenario (+sweep)")
    pu.add_argument("scenario", help="scenario JSON (or YAML) file")
    pu.add_argument("--seed", type=int, default=None,
                    help="override the scenario's seed")
    pu.add_argument("--json", action="store_true")
    pu.set_defaults(fn=_cmd_run)

    pg = sub.add_parser("gate",
                        help="assert a golden scenario's envelopes")
    pg.add_argument("golden", help="golden fixture JSON with envelopes")
    pg.add_argument("--seed", type=int, default=None)
    pg.add_argument("--json", action="store_true")
    pg.set_defaults(fn=_cmd_gate)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

"""Flight-bundle replay: load a diagnostic bundle, re-derive its
request metrics, cross-check them against the bundle's own telemetry,
and re-simulate the recorded schedule on the modelled engine.

Three layers, each usable alone:

* ``load_bundle`` — parse + schema-gate a bundle directory written by
  ``serving/flight.py::dump_bundle``.  Unknown ``schema_version``
  values are REFUSED with a clear error (``SchemaVersionError``);
  bundles written before versioning existed are accepted as version 1
  (their field meanings match — the constant was introduced without a
  breaking change).
* ``derive_requests`` / ``observed_metrics`` — rebuild per-request
  queue-wait / TTFT / TPOT and per-class goodput from the bundle's
  Chrome-trace lifecycle events alone (``enqueued`` / ``queue_wait`` /
  ``admitted`` / ``first_token`` / ``request`` / ``preempted``), then
  cross-check against ``slo.json`` (the watchdog's own score).  The two
  views come from the same clock stamps, so agreement is tight; the
  documented tolerances (docs/simulation.md) exist because the trace
  ring is bounded — a long run's earliest events may have fallen off.
* ``resimulate`` — rebuild the request schedule (arrivals from
  ``enqueued``, prompt lengths from summed ``prefill_chunk`` spans,
  completion lengths from ``request`` span token counts) and run it
  through ``EngineModel`` with a ``TimingModel`` fitted to the recorded
  tick durations and the ``spec_acceptance`` calibration section.

Stdlib only (json + math) — part of the bare-box import contract.
"""

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..policy import QosPolicy
from .model import (DEFAULT_SLO_TARGETS, AcceptanceModel, EngineConfig,
                    EngineModel, TimingModel, _Record, _t, summarize)
from .trace import Request

__all__ = ["SUPPORTED_SCHEMA_VERSIONS", "SchemaVersionError",
           "load_bundle", "derive_requests", "observed_metrics",
           "crosscheck", "resimulate", "replay_bundle",
           "DEFAULT_TOLERANCES"]

#: Flight/bundle schema versions this simulator understands.  Must
#: track ``serving/flight.py::FLIGHT_SCHEMA_VERSION`` — pinned against
#: it by tests/test_sim.py (this module cannot import flight.py: numpy).
SUPPORTED_SCHEMA_VERSIONS: Tuple[int, ...] = (1, 2, 3)

#: Replay cross-check tolerances (documented in docs/simulation.md).
#: ``goodput``: absolute per-class delta between trace-derived and
#: watchdog-recorded goodput.  ``count_slack``: relative shortfall of
#: trace-visible finished requests vs watchdog counts before the
#: goodput check is skipped as "ring truncated".  ``latency_rel`` /
#: ``latency_abs_s``: a latency percentile agrees when within
#: rel * recorded OR the absolute floor.
DEFAULT_TOLERANCES: Dict[str, float] = {
    "goodput": 0.05,
    "count_slack": 0.1,
    "latency_rel": 0.25,
    "latency_abs_s": 0.05,
}


class SchemaVersionError(ValueError):
    """The bundle's schema_version is newer/unknown to this simulator."""


def _read_json(path: str) -> Optional[Any]:
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _check_version(v: Any, where: str) -> None:
    if v is None:
        return      # pre-versioning producer: schema 1 by definition
    if not isinstance(v, int) or v not in SUPPORTED_SCHEMA_VERSIONS:
        raise SchemaVersionError(
            f"{where} carries schema_version={v!r} but this simulator "
            f"understands {list(SUPPORTED_SCHEMA_VERSIONS)}; upgrade "
            f"analytics_zoo_tpu (or replay with a matching checkout) "
            f"instead of guessing at field meanings")


def load_bundle(path: str) -> Dict[str, Any]:
    """Load a bundle directory into plain dicts, refusing unknown
    schema versions.  Returns keys: ``manifest``, ``flight`` (dict),
    ``ticks`` (list), ``trace_events`` (list), ``metrics``, ``config``,
    ``slo``, ``spec_acceptance`` (absent files -> None/empty)."""
    if not os.path.isdir(path):
        raise FileNotFoundError(f"not a bundle directory: {path}")
    manifest = _read_json(os.path.join(path, "manifest.json"))
    if manifest is None:
        raise FileNotFoundError(f"no manifest.json under {path} — not "
                                f"a flight bundle")
    _check_version(manifest.get("schema_version"), "manifest.json")
    flight = _read_json(os.path.join(path, "flight.json")) or {}
    _check_version(flight.get("schema_version"), "flight.json")
    ticks = flight.get("ticks") or []
    for rec in ticks:
        _check_version(rec.get("schema_version"),
                       f"flight tick seq={rec.get('seq')}")
        # v1 producers predate elastic pools: the pool size was static,
        # so every tick's n_blocks is free + used + the sink block
        if "free_blocks" in rec:
            rec.setdefault("n_blocks", int(rec["free_blocks"])
                           + int(rec.get("used_blocks", 0)) + 1)
    trace = _read_json(os.path.join(path, "trace.json")) or {}
    return {
        "path": path,
        "manifest": manifest,
        "flight": flight,
        "ticks": ticks,
        "trace_events": trace.get("traceEvents") or [],
        "metrics": _read_json(os.path.join(path, "metrics.json")),
        "config": _read_json(os.path.join(path, "config.json")),
        "slo": _read_json(os.path.join(path, "slo.json")),
        "spec_acceptance": _read_json(
            os.path.join(path, "spec_acceptance.json")),
    }


# ---------------------------------------------------------------------------
# trace-derived request records
# ---------------------------------------------------------------------------

def derive_requests(trace_events: List[Dict[str, Any]]
                    ) -> Dict[str, _Record]:
    """Rebuild per-request lifecycle records from Chrome-trace events
    (timestamps are microseconds of the recorder's monotonic clock;
    records keep seconds).  Mirrors the stamps telemetry fed the
    watchdog: queue-wait per admission epoch, TTFT per first-token
    epoch from the ORIGINAL arrival, TPOT over the final epoch."""
    recs: Dict[str, _Record] = {}

    def rec_for(uri: str, ts: float) -> _Record:
        r = recs.get(uri)
        if r is None:
            r = recs[uri] = _Record(uri=uri, priority="standard",
                                    tenant="", arrival=ts)
        return r

    for ev in trace_events:
        name = ev.get("name")
        args = ev.get("args") or {}
        uri = args.get("uri")
        if uri is None:
            continue
        ts = ev.get("ts", 0.0) / 1e6
        if name == "enqueued":
            rec_for(uri, ts).arrival = ts
        elif name == "queue_wait":
            r = rec_for(uri, ts)
            r.queue_waits.append(ev.get("dur", 0.0) / 1e6)
        elif name == "admitted":
            r = rec_for(uri, ts)
            r.admits.append(ts)
            if args.get("priority"):
                r.priority = args["priority"]
        elif name == "first_token":
            rec_for(uri, ts).first_tokens.append(ts)
        elif name == "preempted":
            rec_for(uri, ts).preempts += 1
        elif name == "request":
            r = rec_for(uri, ts)
            r.finish_t = ts + ev.get("dur", 0.0) / 1e6
            r.tokens = int(args.get("tokens", 0))
    return recs


def _prompt_lengths(trace_events: List[Dict[str, Any]]) -> Dict[str, int]:
    """Per-uri prompt length: the sum of its prefill_chunk span tokens
    in the FIRST admission epoch (later epochs re-stream the same
    prompt after preemption; summing all would double-count)."""
    out: Dict[str, int] = {}
    epoch_done: Dict[str, bool] = {}
    for ev in trace_events:
        name = ev.get("name")
        args = ev.get("args") or {}
        uri = args.get("uri")
        if uri is None:
            continue
        if name == "prefill_chunk" and not epoch_done.get(uri):
            out[uri] = out.get(uri, 0) + int(args.get("tokens", 0))
        elif name == "preempted" and not epoch_done.get(uri):
            out[uri] = 0        # mid-prefill eviction: restream counts fresh
        elif name == "first_token":
            epoch_done[uri] = True
    return out


def slo_targets_from_config(config: Optional[Dict[str, Any]]
                            ) -> Dict[str, Dict[str, float]]:
    """Per-class targets from a bundle's resolved ServingConfig
    (``slo_<metric>_s_<class>`` knobs), defaults where absent."""
    out = {c: dict(v) for c, v in DEFAULT_SLO_TARGETS.items()}
    if not config:
        return out
    for cls in out:
        for metric in ("ttft", "tpot", "queue_wait"):
            key = f"slo_{metric}_s_{cls}"
            if key in config:
                out[cls][metric] = float(config[key])
    return out


def observed_metrics(bundle: Dict[str, Any]) -> Dict[str, Any]:
    """Per-class latency/goodput summary re-derived purely from the
    bundle's trace events, judged against the bundle's own configured
    SLO targets."""
    recs = derive_requests(bundle["trace_events"])
    return summarize(recs, slo_targets_from_config(bundle.get("config")))


def crosscheck(observed: Dict[str, Any], slo: Optional[Dict[str, Any]],
               tolerances: Optional[Dict[str, float]] = None
               ) -> Dict[str, Any]:
    """Compare trace-derived per-class goodput against the recorded
    watchdog score (``slo.json``).  Returns ``{"ok", "checks": [...]}``
    where each check names the class, both values, the delta, and its
    verdict (``ok`` / ``breach`` / ``skipped_ring_truncated``)."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    checks: List[Dict[str, Any]] = []
    ok = True
    per_class = (slo or {}).get("per_class") or {}
    for cls, rec in sorted(per_class.items()):
        rec_fin = int(rec.get("finished", 0))
        if rec_fin == 0:
            continue
        obs = observed["per_class"].get(cls)
        obs_fin = obs["finished"] if obs else 0
        if obs_fin < rec_fin * (1.0 - tol["count_slack"]):
            checks.append({
                "class": cls, "metric": "goodput",
                "verdict": "skipped_ring_truncated",
                "observed_finished": obs_fin,
                "recorded_finished": rec_fin})
            continue
        delta = abs(obs["goodput"] - float(rec.get("goodput", 1.0)))
        good = delta <= tol["goodput"]
        ok = ok and good
        checks.append({
            "class": cls, "metric": "goodput",
            "observed": obs["goodput"],
            "recorded": _t(float(rec.get("goodput", 1.0))),
            "delta": _t(delta),
            "tolerance": tol["goodput"],
            "verdict": "ok" if good else "breach"})
    return {"ok": ok, "checks": checks}


# ---------------------------------------------------------------------------
# re-simulation
# ---------------------------------------------------------------------------

def engine_config_from_bundle(bundle: Dict[str, Any]) -> EngineConfig:
    """Map the bundle's resolved ServingConfig (+ tick samples where
    the config leaves a knob implicit) onto the sim's EngineConfig."""
    cfg = bundle.get("config") or {}
    ticks = bundle.get("ticks") or []
    spec = bundle.get("spec_acceptance") or {}
    spec_k = int(spec.get("k") or cfg.get("engine_speculation_k") or 0)
    paged = bool(cfg.get("engine_paged", False))
    n_blocks = cfg.get("engine_blocks")
    if paged:
        # v2 ticks carry the pool size directly (elastic pools move it
        # mid-run — size to the high-water mark); v1 falls back to the
        # static reconstruction used + free + sink
        peak = 0
        for rec in ticks:
            if "n_blocks" in rec:
                peak = max(peak, int(rec["n_blocks"]))
            elif "free_blocks" in rec:
                peak = max(peak, int(rec.get("free_blocks", 0))
                           + int(rec.get("used_blocks", 0)) + 1)
        if peak:
            n_blocks = max(int(n_blocks or 0), peak)
        if n_blocks is None:
            n_blocks = 256
    max_new = 0
    for ev in bundle.get("trace_events") or []:
        if ev.get("name") == "request":
            max_new = max(max_new,
                          int((ev.get("args") or {}).get("tokens", 0)))
    return EngineConfig(
        slots=int(cfg.get("engine_slots", 8)),
        max_new_tokens=max(max_new, 1) if max_new else 32,
        ticks_per_step=int(cfg.get("engine_ticks", 1)),
        chunked=bool(cfg.get("engine_chunked", False)),
        tick_token_budget=cfg.get("engine_tick_token_budget"),
        paged=paged,
        block_size=int(cfg.get("engine_block_size", 16)),
        n_blocks=int(n_blocks) if n_blocks is not None else None,
        spec_k=spec_k,
    )


def qos_from_config(cfg: Optional[Dict[str, Any]]) -> Optional[QosPolicy]:
    if not cfg or not cfg.get("qos_enabled"):
        return None
    return QosPolicy(
        weights={"interactive": float(cfg.get("qos_weight_interactive",
                                              8.0)),
                 "standard": float(cfg.get("qos_weight_standard", 4.0)),
                 "batch": float(cfg.get("qos_weight_batch", 1.0))},
        aging_s=float(cfg.get("qos_aging_s", 30.0)))


def requests_from_bundle(bundle: Dict[str, Any],
                         econf: EngineConfig) -> List[Request]:
    """The recorded request schedule: arrivals from ``enqueued``
    stamps (normalized so the first arrival is t=0), prompt lengths
    from first-epoch ``prefill_chunk`` sums (fallback: the smallest
    prompt bucket — non-chunked bundles don't record per-request
    prompt sizes), completion lengths from ``request`` span tokens
    (unfinished requests are skipped: their length is unknowable)."""
    evs = bundle["trace_events"]
    recs = derive_requests(evs)
    plens = _prompt_lengths(evs)
    arrivals = [r.arrival for r in recs.values() if r.finished]
    if not arrivals:
        return []
    t0 = min(arrivals)
    out = []
    for uri in sorted(recs):
        r = recs[uri]
        if not r.finished or r.tokens < 1:
            continue
        out.append(Request(
            uri=uri,
            arrival_t=_t(r.arrival - t0),
            prompt_len=max(1, plens.get(uri,
                                        econf.prompt_buckets[0])),
            gen_len=r.tokens,
            priority=r.priority,
            tenant=r.tenant))
    out.sort(key=lambda r: (r.arrival_t, r.uri))
    return out


def timing_from_ticks(ticks: List[Dict[str, Any]]) -> TimingModel:
    samples = []
    clean = []
    for rec in ticks:
        dur = rec.get("dur_ms")
        if dur is None:
            continue
        tokens = rec.get("budget_used")
        if tokens is None:
            # non-chunked ticks: active rows each advance ~1 token
            tokens = rec.get("active", 1)
        sample = (int(tokens), float(dur) / 1e3)
        samples.append(sample)
        # Ticks that triggered a jit build or retrace measure the
        # compiler, not the schedule; calibrate steady-state cost from
        # compile-free ticks whenever enough of them exist.
        if not rec.get("compiles"):
            clean.append(sample)
    if len(clean) >= 4:
        samples = clean
    return TimingModel.fit(samples)


def resimulate(bundle: Dict[str, Any], seed: int = 0,
               record_events: bool = False) -> Dict[str, Any]:
    """Re-run the bundle's recorded request schedule through the
    modelled engine (config from the bundle, timing fitted to its tick
    durations, spec acceptance from its calibration section) and
    summarize with the bundle's SLO targets."""
    econf = engine_config_from_bundle(bundle)
    acceptance = None
    spec = bundle.get("spec_acceptance")
    if econf.spec_k > 0 and spec and spec.get("counts"):
        acceptance = AcceptanceModel.from_counts(spec["counts"],
                                                 econf.spec_k)
    model = EngineModel(
        econf, qos=qos_from_config(bundle.get("config")),
        acceptance=acceptance, timing=timing_from_ticks(bundle["ticks"]),
        seed=seed, record_events=record_events)
    reqs = requests_from_bundle(bundle, econf)
    model.run(reqs)
    summary = summarize(model.records,
                        slo_targets_from_config(bundle.get("config")))
    summary["n_requests"] = len(reqs)
    summary["sim_ticks"] = model.ticks
    summary["preemptions"] = model.preemptions
    return summary


def replay_bundle(path: str, seed: int = 0,
                  resim: bool = True,
                  tolerances: Optional[Dict[str, float]] = None
                  ) -> Dict[str, Any]:
    """The whole replay pipeline: load + schema-gate, derive observed
    metrics, cross-check against the recorded watchdog score, and
    (optionally) re-simulate.  Returns one JSON-serializable report."""
    bundle = load_bundle(path)
    observed = observed_metrics(bundle)
    check = crosscheck(observed, bundle.get("slo"), tolerances)
    report: Dict[str, Any] = {
        "bundle": os.path.basename(os.path.abspath(path)),
        "schema_version": bundle["manifest"].get("schema_version", 1),
        "reason": bundle["manifest"].get("reason"),
        "observed": observed,
        "recorded_slo": (bundle.get("slo") or {}).get("per_class"),
        "crosscheck": check,
        "ok": check["ok"],
    }
    if resim:
        simulated = resimulate(bundle, seed=seed)
        report["simulated"] = simulated
        deltas = {}
        for cls, obs in observed["per_class"].items():
            sim_cls = simulated["per_class"].get(cls)
            if not sim_cls or not sim_cls["finished"]:
                continue
            deltas[cls] = {
                "goodput": _t(sim_cls["goodput"] - obs["goodput"]),
                "ttft_p99_s": _t(sim_cls["ttft"]["p99"]
                                 - obs["ttft"]["p99"]),
                "tpot_p99_s": _t(sim_cls["tpot"]["p99"]
                                 - obs["tpot"]["p99"]),
            }
        report["sim_vs_observed"] = deltas
    return report

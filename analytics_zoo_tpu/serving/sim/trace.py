"""Synthetic request traces for the engine simulator.

Generators are fully deterministic under a seed: the same
``(seed, parameters)`` pair yields the same request list on any
platform, any ``PYTHONHASHSEED``, any process.  All randomness flows
through one ``random.Random(seed)`` instance and every iteration order
is over explicit sequences (never over set/dict views of non-string
keys), so there is no hash-order leakage.

Arrival processes:

* ``poisson_trace`` — homogeneous Poisson arrivals at ``rate_rps``
  (exponential inter-arrival gaps).
* ``diurnal_trace`` — nonhomogeneous Poisson with a sinusoidal rate
  between ``base_rps`` and ``peak_rps`` over ``period_s``, sampled by
  thinning against the peak rate.

Shared-prefix traffic (tiered-KV scenarios): ``prefixes`` names a set
of shared system prompts and ``prefix_frac`` the fraction of requests
that open with one; a tagged request carries ``prefix_id`` (which
prompt) and ``prefix_len`` (its length in tokens, a leading slice of
``prompt_len``).  Both default off, and the prefix draws happen ONLY
when ``prefix_frac > 0`` — a prefix-free call consumes exactly the
RNG stream it always did, so existing seeded traces (and the golden
envelopes pinned on them) are byte-identical.

Stdlib only — this module is part of the bare-box import contract of
``serving/sim`` (see the package docstring).
"""

import math
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Request", "poisson_trace", "diurnal_trace", "requests_from_dicts"]

#: Default priority-class mix (must be a subset of policy.PRIORITIES).
DEFAULT_CLASS_MIX: Tuple[Tuple[str, float], ...] = (
    ("interactive", 0.5), ("standard", 0.3), ("batch", 0.2))


@dataclass(frozen=True)
class Request:
    """One simulated request.

    ``gen_len`` is the number of tokens the request will emit before
    finishing — the simulator does not model EOS sampling, so the
    completion length is part of the trace.  When re-simulating a
    recorded bundle, ``gen_len`` is the realized token count from the
    bundle's trace, which is exactly the "completion-length oracle"
    trick the engine-vs-sim equivalence tests use.

    ``prefix_id``/``prefix_len`` tag a request that opens with a
    shared system prompt: the first ``prefix_len`` tokens of
    ``prompt_len`` are identical across every request carrying the
    same ``prefix_id`` (the tiered-KV model keys residency on it).
    ``""``/0 — the defaults, and everything a prefix-free generator
    emits — mean no shared prefix.
    """

    uri: str
    arrival_t: float
    prompt_len: int
    gen_len: int
    priority: Optional[str] = "standard"
    tenant: str = ""
    prefix_id: str = ""
    prefix_len: int = 0
    #: Optional end-to-end deadline (seconds after arrival).  Read by
    #: the fleet's crash-recovery redispatch (``plan_redispatch``):
    #: a lost request older than its deadline error-terminates instead
    #: of retrying.  0 = no per-request deadline (the fleet-level
    #: ``request_deadline_s`` still applies, if set).
    deadline_s: float = 0.0

    def to_dict(self) -> Dict[str, object]:
        out = {
            "uri": self.uri,
            "arrival_t": round(float(self.arrival_t), 9),
            "prompt_len": int(self.prompt_len),
            "gen_len": int(self.gen_len),
            "priority": self.priority,
            "tenant": self.tenant,
        }
        if self.prefix_id:
            # only tagged requests carry the fields, so prefix-free
            # traces serialize byte-identically to previous releases
            out["prefix_id"] = self.prefix_id
            out["prefix_len"] = int(self.prefix_len)
        if self.deadline_s:
            out["deadline_s"] = float(self.deadline_s)
        return out


def requests_from_dicts(rows: Sequence[Dict[str, object]]) -> List[Request]:
    """Parse an explicit request list (scenario files, golden fixtures)."""
    out = []
    for i, row in enumerate(rows):
        out.append(Request(
            uri=str(row.get("uri", "req-%06d" % i)),
            arrival_t=float(row.get("arrival_t", 0.0)),
            prompt_len=int(row["prompt_len"]),
            gen_len=int(row.get("gen_len", row.get("max_new", 1))),
            priority=row.get("priority", "standard"),  # type: ignore[arg-type]
            tenant=str(row.get("tenant", "")),
            prefix_id=str(row.get("prefix_id", "")),
            prefix_len=int(row.get("prefix_len", 0)),
            deadline_s=float(row.get("deadline_s", 0.0)),
        ))
    out.sort(key=lambda r: (r.arrival_t, r.uri))
    return out


def _normalize_mix(class_mix) -> List[Tuple[str, float]]:
    if class_mix is None:
        items = list(DEFAULT_CLASS_MIX)
    elif isinstance(class_mix, dict):
        # dicts preserve insertion order; scenario files are parsed in
        # file order, so this is deterministic for a given file.
        items = [(str(k), float(v)) for k, v in class_mix.items()]
    else:
        items = [(str(k), float(v)) for k, v in class_mix]
    total = sum(w for _, w in items)
    if total <= 0:
        raise ValueError("class mix weights must sum to a positive value")
    return [(k, w / total) for k, w in items]


def _normalize_prefixes(prefixes) -> List[Tuple[str, int]]:
    """``prefixes`` as an explicit (id, length) list.  Accepts a dict
    (``{"sysA": 128}``, insertion-ordered like the class mix) or a
    sequence of (id, length) pairs."""
    if isinstance(prefixes, dict):
        items = [(str(k), int(v)) for k, v in prefixes.items()]
    else:
        items = [(str(k), int(v)) for k, v in prefixes]
    if not items:
        raise ValueError("prefixes must name at least one shared prefix")
    for k, n in items:
        if n < 1:
            raise ValueError(f"prefix {k!r} needs a positive length, "
                             f"got {n}")
    return items


def _pick(rng: random.Random, items: List[Tuple[str, float]]) -> str:
    x = rng.random()
    acc = 0.0
    for key, w in items:
        acc += w
        if x < acc:
            return key
    return items[-1][0]


def _body(rng: random.Random, i: int, t: float, prompt_len, gen_len,
          mix, tenants: Sequence[str],
          prefixes: Optional[List[Tuple[str, int]]] = None,
          prefix_frac: float = 0.0) -> Request:
    plo, phi = int(prompt_len[0]), int(prompt_len[-1])
    glo, ghi = int(gen_len[0]), int(gen_len[-1])
    plen = rng.randint(plo, phi)
    glen = rng.randint(glo, ghi)
    priority = _pick(rng, mix)
    tenant = rng.choice(list(tenants)) if tenants else ""
    prefix_id, prefix_len = "", 0
    if prefixes is not None and prefix_frac > 0.0:
        # the prefix draws run ONLY on this branch: prefix-free calls
        # consume the exact RNG stream previous releases did, keeping
        # every existing seeded trace byte-identical
        if rng.random() < prefix_frac:
            prefix_id, prefix_len = rng.choice(prefixes)
            if plen <= prefix_len:
                # the shared prefix is a LEADING slice; leave at least
                # one private token so admission always has work
                plen = prefix_len + 1
    return Request(
        uri="req-%06d" % i,
        arrival_t=t,
        prompt_len=plen,
        gen_len=glen,
        priority=priority,
        tenant=tenant,
        prefix_id=prefix_id,
        prefix_len=prefix_len,
    )


def poisson_trace(*, n_requests: int, rate_rps: float, seed: int,
                  prompt_len: Sequence[int] = (16, 256),
                  gen_len: Sequence[int] = (8, 64),
                  class_mix=None,
                  tenants: Sequence[str] = ("",),
                  prefixes=None,
                  prefix_frac: float = 0.0) -> List[Request]:
    """Homogeneous Poisson arrivals: exponential gaps at ``rate_rps``."""
    if rate_rps <= 0:
        raise ValueError("rate_rps must be positive")
    rng = random.Random(seed)
    mix = _normalize_mix(class_mix)
    pfx = _normalize_prefixes(prefixes) if prefixes is not None else None
    t = 0.0
    out = []
    for i in range(int(n_requests)):
        t += rng.expovariate(rate_rps)
        out.append(_body(rng, i, t, prompt_len, gen_len, mix, tenants,
                         pfx, prefix_frac))
    return out


def diurnal_trace(*, n_requests: int, base_rps: float, peak_rps: float,
                  period_s: float, seed: int,
                  prompt_len: Sequence[int] = (16, 256),
                  gen_len: Sequence[int] = (8, 64),
                  class_mix=None,
                  tenants: Sequence[str] = ("",),
                  prefixes=None,
                  prefix_frac: float = 0.0) -> List[Request]:
    """Sinusoidal-rate Poisson arrivals sampled by thinning.

    Instantaneous rate at time ``t``::

        rate(t) = base + (peak - base) * (1 - cos(2*pi*t/period)) / 2

    which starts at ``base_rps``, peaks at ``peak_rps`` mid-period, and
    returns to base — one "day" per ``period_s``.
    """
    if not (0 < base_rps <= peak_rps):
        raise ValueError("need 0 < base_rps <= peak_rps")
    if period_s <= 0:
        raise ValueError("period_s must be positive")
    rng = random.Random(seed)
    mix = _normalize_mix(class_mix)
    pfx = _normalize_prefixes(prefixes) if prefixes is not None else None
    t = 0.0
    out = []
    i = 0
    while i < int(n_requests):
        t += rng.expovariate(peak_rps)
        rate = base_rps + (peak_rps - base_rps) * (
            1.0 - math.cos(2.0 * math.pi * t / period_s)) / 2.0
        if rng.random() * peak_rps < rate:
            out.append(_body(rng, i, t, prompt_len, gen_len, mix,
                             tenants, pfx, prefix_frac))
            i += 1
    return out

"""Synthetic request traces for the engine simulator.

Generators are fully deterministic under a seed: the same
``(seed, parameters)`` pair yields the same request list on any
platform, any ``PYTHONHASHSEED``, any process.  All randomness flows
through one ``random.Random(seed)`` instance and every iteration order
is over explicit sequences (never over set/dict views of non-string
keys), so there is no hash-order leakage.

Arrival processes:

* ``poisson_trace`` — homogeneous Poisson arrivals at ``rate_rps``
  (exponential inter-arrival gaps).
* ``diurnal_trace`` — nonhomogeneous Poisson with a sinusoidal rate
  between ``base_rps`` and ``peak_rps`` over ``period_s``, sampled by
  thinning against the peak rate.

Stdlib only — this module is part of the bare-box import contract of
``serving/sim`` (see the package docstring).
"""

import math
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Request", "poisson_trace", "diurnal_trace", "requests_from_dicts"]

#: Default priority-class mix (must be a subset of policy.PRIORITIES).
DEFAULT_CLASS_MIX: Tuple[Tuple[str, float], ...] = (
    ("interactive", 0.5), ("standard", 0.3), ("batch", 0.2))


@dataclass(frozen=True)
class Request:
    """One simulated request.

    ``gen_len`` is the number of tokens the request will emit before
    finishing — the simulator does not model EOS sampling, so the
    completion length is part of the trace.  When re-simulating a
    recorded bundle, ``gen_len`` is the realized token count from the
    bundle's trace, which is exactly the "completion-length oracle"
    trick the engine-vs-sim equivalence tests use.
    """

    uri: str
    arrival_t: float
    prompt_len: int
    gen_len: int
    priority: Optional[str] = "standard"
    tenant: str = ""

    def to_dict(self) -> Dict[str, object]:
        return {
            "uri": self.uri,
            "arrival_t": round(float(self.arrival_t), 9),
            "prompt_len": int(self.prompt_len),
            "gen_len": int(self.gen_len),
            "priority": self.priority,
            "tenant": self.tenant,
        }


def requests_from_dicts(rows: Sequence[Dict[str, object]]) -> List[Request]:
    """Parse an explicit request list (scenario files, golden fixtures)."""
    out = []
    for i, row in enumerate(rows):
        out.append(Request(
            uri=str(row.get("uri", "req-%06d" % i)),
            arrival_t=float(row.get("arrival_t", 0.0)),
            prompt_len=int(row["prompt_len"]),
            gen_len=int(row.get("gen_len", row.get("max_new", 1))),
            priority=row.get("priority", "standard"),  # type: ignore[arg-type]
            tenant=str(row.get("tenant", "")),
        ))
    out.sort(key=lambda r: (r.arrival_t, r.uri))
    return out


def _normalize_mix(class_mix) -> List[Tuple[str, float]]:
    if class_mix is None:
        items = list(DEFAULT_CLASS_MIX)
    elif isinstance(class_mix, dict):
        # dicts preserve insertion order; scenario files are parsed in
        # file order, so this is deterministic for a given file.
        items = [(str(k), float(v)) for k, v in class_mix.items()]
    else:
        items = [(str(k), float(v)) for k, v in class_mix]
    total = sum(w for _, w in items)
    if total <= 0:
        raise ValueError("class mix weights must sum to a positive value")
    return [(k, w / total) for k, w in items]


def _pick(rng: random.Random, items: List[Tuple[str, float]]) -> str:
    x = rng.random()
    acc = 0.0
    for key, w in items:
        acc += w
        if x < acc:
            return key
    return items[-1][0]


def _body(rng: random.Random, i: int, t: float, prompt_len, gen_len,
          mix, tenants: Sequence[str]) -> Request:
    plo, phi = int(prompt_len[0]), int(prompt_len[-1])
    glo, ghi = int(gen_len[0]), int(gen_len[-1])
    return Request(
        uri="req-%06d" % i,
        arrival_t=t,
        prompt_len=rng.randint(plo, phi),
        gen_len=rng.randint(glo, ghi),
        priority=_pick(rng, mix),
        tenant=rng.choice(list(tenants)) if tenants else "",
    )


def poisson_trace(*, n_requests: int, rate_rps: float, seed: int,
                  prompt_len: Sequence[int] = (16, 256),
                  gen_len: Sequence[int] = (8, 64),
                  class_mix=None,
                  tenants: Sequence[str] = ("",)) -> List[Request]:
    """Homogeneous Poisson arrivals: exponential gaps at ``rate_rps``."""
    if rate_rps <= 0:
        raise ValueError("rate_rps must be positive")
    rng = random.Random(seed)
    mix = _normalize_mix(class_mix)
    t = 0.0
    out = []
    for i in range(int(n_requests)):
        t += rng.expovariate(rate_rps)
        out.append(_body(rng, i, t, prompt_len, gen_len, mix, tenants))
    return out


def diurnal_trace(*, n_requests: int, base_rps: float, peak_rps: float,
                  period_s: float, seed: int,
                  prompt_len: Sequence[int] = (16, 256),
                  gen_len: Sequence[int] = (8, 64),
                  class_mix=None,
                  tenants: Sequence[str] = ("",)) -> List[Request]:
    """Sinusoidal-rate Poisson arrivals sampled by thinning.

    Instantaneous rate at time ``t``::

        rate(t) = base + (peak - base) * (1 - cos(2*pi*t/period)) / 2

    which starts at ``base_rps``, peaks at ``peak_rps`` mid-period, and
    returns to base — one "day" per ``period_s``.
    """
    if not (0 < base_rps <= peak_rps):
        raise ValueError("need 0 < base_rps <= peak_rps")
    if period_s <= 0:
        raise ValueError("period_s must be positive")
    rng = random.Random(seed)
    mix = _normalize_mix(class_mix)
    t = 0.0
    out = []
    i = 0
    while i < int(n_requests):
        t += rng.expovariate(peak_rps)
        rate = base_rps + (peak_rps - base_rps) * (
            1.0 - math.cos(2.0 * math.pi * t / period_s)) / 2.0
        if rng.random() * peak_rps < rate:
            out.append(_body(rng, i, t, prompt_len, gen_len, mix, tenants))
            i += 1
    return out

"""Cluster Serving client queues — InputQueue / OutputQueue.

Reference surface (SURVEY.md §2.6, §3.5; ref: pyzoo/zoo/serving/client.py):
``InputQueue.enqueue(uri, **data)`` Arrow-encodes + base64s ndarrays and
XADDs to the ``serving_stream``; ``OutputQueue.query(uri)`` /
``dequeue()`` read base64 ndarrays from result hashes.

Parity choices: the stream/hash keys and the enqueue/query/dequeue call
shapes match the reference; the tensor encoding is base64(npy) instead of
base64(Arrow) — self-describing, numpy-native, and decodes to the same
ndarray on any client.
"""

from __future__ import annotations

import base64
import io
import time
import uuid
from typing import Dict, Optional

import numpy as np

from analytics_zoo_tpu.serving.resp import RespClient

INPUT_STREAM = "serving_stream"
RESULT_PREFIX = "result:"
SIGNAL_PREFIX = "rsig:"   # per-uri wakeup stream: XREAD BLOCK, not polling
TOKEN_PREFIX = "tok:"     # per-uri token stream (streaming requests):
#                           the pump publishes generated tokens + a
#                           terminal marker; stream_events() tails it
CANCEL_STREAM = "serving_cancel"  # client -> pump live-cancel requests
IMG_MAGIC = b"IMG!"       # field prefix: raw encoded image (JPEG/PNG bytes)
#                           decoded server-side — ref: Cluster Serving
#                           clients enqueued base64 image bytes and the
#                           Flink job decoded/resized before inference


class BacklogFull(RuntimeError):
    """The bounded admission queue refused an enqueue.  Subclasses
    ``RuntimeError`` so pre-existing ``except RuntimeError`` callers
    keep working; carries the observed depth and the cap so the HTTP
    frontend can map it to ``429`` with a computed ``Retry-After``."""

    def __init__(self, depth: int, max_backlog: int):
        self.depth = int(depth)
        self.max_backlog = int(max_backlog)
        super().__init__(
            f"serving backlog {self.depth} >= max_backlog "
            f"{self.max_backlog}; request rejected (not trimmed)")


def encode_ndarray(a: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, np.asarray(a), allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode()


def decode_ndarray(s) -> np.ndarray:
    raw = base64.b64decode(s)
    return np.load(io.BytesIO(raw), allow_pickle=False)


class ImageBytes(bytes):
    """Marker type: a value that is ENCODED image bytes (JPEG/PNG), to be
    decoded server-side — lets image payloads travel through the generic
    ``enqueue(uri, col=value)`` surface (and the HTTP frontend) alongside
    dense-tensor columns."""


class InputQueue:
    """ref-parity: InputQueue(host, port).enqueue(uri, key=ndarray, ...)"""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 stream: str = INPUT_STREAM, max_backlog: int = 10000):
        """max_backlog > 0 rejects enqueues (BacklogFull) once the pending
        stream holds that many entries; 0 disables the cap.  No MAXLEN
        trimming is used: the server XDELs entries as it consumes them, so
        trimming could only ever drop requests that were never read."""
        self.client = RespClient(host, port)
        self.stream = stream
        self.max_backlog = max_backlog

    def enqueue(self, uri: Optional[str] = None, **data) -> str:
        """Enqueue one request; returns its uri (generated when omitted).
        `data` values are ndarrays (or scalars) keyed by input name."""
        uri = uri or str(uuid.uuid4())
        if "uri" in data:
            raise ValueError(
                "'uri' is the request id, not an input column name")
        fields = ["uri", uri]
        for k, v in data.items():
            if isinstance(v, ImageBytes):
                fields += [k, IMG_MAGIC + bytes(v)]
            elif isinstance(v, (bytes, bytearray, memoryview, str)):
                # np.asarray(bytes/str) would silently make a |S/|U
                # string scalar that explodes much later inside the
                # server's jit with an inscrutable error — refuse it
                # HERE with the fix named
                raise TypeError(
                    f"field {k!r} is {type(v).__name__}; wrap encoded "
                    f"images as ImageBytes(b) (or use enqueue_image), "
                    f"send tensors as ndarrays, and generative prompts "
                    f"as 1-D int32 token arrays (the prompt_col "
                    f"contract)")
            else:
                fields += [k, encode_ndarray(np.asarray(v))]
        return self._xadd_capped(uri, fields)

    def _xadd_capped(self, uri: str, fields) -> str:
        if not self.max_backlog:
            self.client.execute("XADD", self.stream, "*", *fields)
            return uri
        # add-then-check in ONE round-trip: concurrent producers that
        # overshoot each remove their own entry, so the cap holds under
        # racing threads without a MAXLEN trim dropping unread requests
        entry_id, depth = self.client.pipeline([
            ("XADD", self.stream, "*", *fields),
            ("XLEN", self.stream)])
        if int(depth or 0) > self.max_backlog:
            self.client.execute("XDEL", self.stream, entry_id)
            raise BacklogFull(int(depth) - 1, self.max_backlog)
        return uri

    def cancel(self, uri: str) -> None:
        """Request live cancellation of an in-flight request: the
        serving pump drains the cancel stream every loop iteration and
        calls ``engine.abort(uri)`` on its own thread, freeing BOTH
        pool tenants' blocks immediately instead of waiting for the
        ``result_ttl_s`` prune.  Idempotent; unknown uris are ignored
        server-side."""
        self.client.execute("XADD", CANCEL_STREAM, "*", "uri", uri)

    def enqueue_image(self, uri: Optional[str] = None, *,
                      image: bytes, col: str = "x") -> str:
        """Enqueue one ENCODED image (JPEG/PNG bytes) — the server decodes
        it natively (C++ libjpeg/libpng), resizes per its config, and
        batches it into the model input (ref: InputQueue.enqueue_image).
        The wire carries the compressed bytes, not a dense tensor."""
        uri = uri or str(uuid.uuid4())
        return self._xadd_capped(
            uri, ["uri", uri, col, IMG_MAGIC + bytes(image)])

    def close(self):
        self.client.close()


class OutputQueue:
    """ref-parity: OutputQueue().query(uri) / dequeue()."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379):
        self.client = RespClient(host, port)

    def query(self, uri: str, timeout: float = 30.0,
              poll_interval: float = 0.01) -> Optional[np.ndarray]:
        """Block until the result for `uri` lands (or timeout -> None).

        Waits on the per-uri signal stream with XREAD BLOCK — one blocking
        round-trip instead of a poll storm (the broker's condvar wakes the
        read the instant the server publishes).  `poll_interval` is kept
        for API compatibility; it only paces the legacy fallback path."""
        deadline = time.monotonic() + timeout
        key = RESULT_PREFIX + uri
        sig = SIGNAL_PREFIX + uri
        h = self.client.execute("HGETALL", key)
        while not h:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # the blocking XREAD auto-created the signal stream on the
                # broker; remove it so abandoned queries don't leak keys
                self.client.execute("DEL", sig)
                return None
            try:
                self.client.execute(
                    "XREAD", "COUNT", 1, "BLOCK",
                    max(1, int(remaining * 1000)), "STREAMS", sig, "0-0")
            except Exception:
                time.sleep(poll_interval)   # legacy broker: plain polling
            h = self.client.execute("HGETALL", key)
        fields = {h[i].decode(): h[i + 1] for i in range(0, len(h), 2)}
        self.client.execute("DEL", key, sig)
        self.client.execute("SREM", "__result_keys__", uri)
        if "error" in fields:
            # the server could not process this request (bad payload,
            # shape mismatch) — fail fast rather than hand back None
            raise RuntimeError(
                f"serving error for {uri!r}: "
                f"{fields['error'].decode(errors='replace')}")
        return decode_ndarray(fields["value"])

    def stream_events(self, uri: str, timeout: float = 30.0,
                      poll_s: float = 1.0):
        """Tail the per-token stream of a ``stream=True`` request.

        Yields dicts in emission order: ``{"token": t, "index": i}``
        per generated token, then exactly one terminal —
        ``{"done": True}`` / ``{"cancelled": True}`` /
        ``{"error": msg}`` — after which the stream key is deleted and
        the generator returns.  ``{"ping": True}`` heartbeats surface
        between events (at most every ``poll_s``) so an SSE writer can
        touch its socket and detect a dead client while the engine is
        between tokens.  Re-emitted tokens after an engine preemption
        are deduplicated by index (a readmitted row regenerates its
        tokens deterministically).  A ``{"restart": attempt}`` event
        surfaces a crash-recovery redispatch (the broker re-placed
        the request on a surviving replica): the emitted-token index
        resets to 0 and the generation re-streams from the start —
        consumers must discard buffered tokens, never splice.
        Raises ``TimeoutError`` when no event lands for ``timeout``
        seconds."""
        key = TOKEN_PREFIX + uri
        last = b"0-0"
        next_index = 0
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.client.execute("DEL", key)
                raise TimeoutError(
                    f"no stream event for {uri!r} in {timeout}s")
            block_ms = max(1, int(min(remaining, poll_s) * 1000))
            resp = self.client.execute(
                "XREAD", "COUNT", 256, "BLOCK", block_ms,
                "STREAMS", key, last)
            if not resp:
                yield {"ping": True}
                continue
            for eid, flat in resp[0][1]:
                last = eid
                f = {flat[i].decode(): flat[i + 1]
                     for i in range(0, len(flat), 2)}
                if "restart" in f:
                    # crash-recovery redispatch: the replay starts
                    # over at index 0, so the dedup watermark must
                    # reset or every re-emitted token gets swallowed
                    next_index = 0
                    deadline = time.monotonic() + timeout
                    yield {"restart": int(f["restart"])}
                elif "t" in f:
                    idx = int(f.get("i", b"-1"))
                    if idx < next_index:    # preemption re-emission
                        continue
                    next_index = idx + 1
                    deadline = time.monotonic() + timeout
                    yield {"token": int(f["t"]), "index": idx}
                elif "done" in f:
                    self.client.execute("DEL", key)
                    yield {"done": True}
                    return
                elif "cancelled" in f:
                    self.client.execute("DEL", key)
                    yield {"cancelled": True}
                    return
                elif "error" in f:
                    self.client.execute("DEL", key)
                    yield {"error":
                           f["error"].decode(errors="replace")}
                    return

    def dequeue(self) -> Dict[str, np.ndarray]:
        """Drain every available result (ref: OutputQueue.dequeue).
        Results are stored under result:<uri>; the server keeps a set index
        of unread uris, which `query` prunes as results are consumed."""
        out: Dict[str, np.ndarray] = {}
        keys = self.client.execute("SMEMBERS", "__result_keys__") or []
        for uri in keys:
            try:
                v = self.query(uri.decode(), timeout=0.05)
            except RuntimeError:    # errored request: consumed, not drained
                continue
            if v is not None:
                out[uri.decode()] = v
        return out

    def close(self):
        self.client.close()
